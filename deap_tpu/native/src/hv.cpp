// Native exact hypervolume for deap_tpu.
//
// Counterpart of the reference's C extension (_hv.c / hv.cpp — the
// Fonseca–Paquete–López-Ibáñez dimension-sweep implementation,
// /root/reference/deap/tools/_hypervolume/_hv.c:59,1456). This is an
// independent implementation of the WFG exclusive-hypervolume recursion
// (While, Bradstreet & Barone 2012) with the dimension-dropping slicing
// step (each sorted-last-objective term factorises into slab x a
// (d-1)-dim problem), linearithmic 2-D/3-D staircase-sweep base cases,
// and a fused d=4 sweep — written for this framework, not a port of
// the reference's AVL-tree sweep code. Benchmarks vs the reference
// extension: BASELINE.md "Native hypervolume" (parity-or-better at
// every d except large-n d=4). Exposed through a plain C ABI consumed
// via ctypes (deap_tpu/native/hv_binding.py), mirroring the
// reference's graceful-fallback import seam (deap/tools/indicator.py:3-8).
//
// Convention: MINIMISATION relative to `ref`; points not strictly below
// the reference point in every objective contribute nothing.

#include <algorithm>
#include <cstddef>
#include <vector>

namespace {

struct Front {
    // Flat row-major [n, d] storage with index indirection to avoid
    // copying rows during sorts.
    std::vector<double> data;
    int d = 0;

    std::size_t size() const { return d ? data.size() / d : 0; }
    const double* row(std::size_t i) const { return data.data() + i * d; }
    void push(const double* p) { data.insert(data.end(), p, p + d); }
};

double hv2d(Front& f, const double* ref) {
    // Staircase sweep: ascending f0, keep the running minimum of f1.
    const std::size_t n = f.size();
    std::vector<std::size_t> idx(n);
    for (std::size_t i = 0; i < n; ++i) idx[i] = i;
    std::sort(idx.begin(), idx.end(), [&](std::size_t a, std::size_t b) {
        const double *pa = f.row(a), *pb = f.row(b);
        return pa[0] < pb[0] || (pa[0] == pb[0] && pa[1] < pb[1]);
    });
    double vol = 0.0, ymin = ref[1];
    for (std::size_t i : idx) {
        const double* p = f.row(i);
        if (p[1] < ymin) {
            vol += (ref[0] - p[0]) * (ymin - p[1]);
            ymin = p[1];
        }
    }
    return vol;
}

// Incremental 2-D staircase over (x, y) with x ascending, y strictly
// descending, tracking the dominated AREA relative to (ref_x, ref_y).
// Flat sorted vector, not a node-based container: entries a new point
// dominates form a CONTIGUOUS run erased in one range op, and the d=4
// sweep performs O(n^2) inserts, so allocation cost would dominate.
// Robust to projection-dominated and duplicate inserts (they add 0).
// The single home of this logic — both the 3-D base case and the
// fused d=4 sweep sweep z levels through it.
struct Staircase {
    std::vector<std::pair<double, double>> st;
    double area = 0.0;

    void reset() {
        st.clear();
        area = 0.0;
    }

    void insert(double x, double y, const double* ref) {
        auto it = std::lower_bound(
            st.begin(), st.end(), x,
            [](const std::pair<double, double>& e, double v) {
                return e.first < v;
            });
        if (it != st.begin() && (it - 1)->second <= y)
            return;  // projection-dominated by a strictly-left entry
        if (it != st.end() && it->first == x && it->second <= y)
            return;  // projection-dominated by an equal-x entry
        // Area gained: overlap of [x, ref_x) x [y, oldY(u)) with the
        // old staircase's min-y step function oldY, walking segments
        // rightward; entries the new point dominates are erased.
        double gain = 0.0;
        double seg_start = x;
        double prev_y = (it == st.begin()) ? ref[1] : (it - 1)->second;
        auto run = it;  // first surviving entry after the dominated run
        for (;;) {
            const double seg_end = (run == st.end()) ? ref[0]
                                                     : run->first;
            if (prev_y > y) gain += (seg_end - seg_start) * (prev_y - y);
            if (run == st.end() || run->second < y) break;
            seg_start = run->first;
            prev_y = run->second;
            ++run;
        }
        if (run != it) {  // overwrite the run's head, erase the rest
            *it = {x, y};
            st.erase(it + 1, run);
        } else {
            st.insert(it, {x, y});
        }
        area += gain;
    }
};

double hv3d(const Front& f, const double* ref) {
    // O(n log n) sweep on the 3rd objective (the performance class of
    // the reference's specialized 3-D base case, _hv.c:540-545, by a
    // different algorithm): sort ascending z and push (x, y) through
    // the incremental staircase; volume accrues as area x slab between
    // consecutive z levels. Robust to projection-dominated and
    // duplicate points, so callers may pass un-filtered limited sets.
    const std::size_t n = f.size();
    if (n == 0) return 0.0;
    static thread_local std::vector<std::size_t> idx;
    static thread_local Staircase sc;  // leaf: never two live at once
    idx.resize(n);
    for (std::size_t i = 0; i < n; ++i) idx[i] = i;
    std::sort(idx.begin(), idx.end(), [&](std::size_t a, std::size_t b) {
        return f.row(a)[2] < f.row(b)[2];
    });
    sc.reset();
    double vol = 0.0;
    double cur_z = f.row(idx[0])[2];
    for (std::size_t ii = 0; ii < n; ++ii) {
        const double* p = f.row(idx[ii]);
        vol += sc.area * (p[2] - cur_z);
        cur_z = p[2];
        sc.insert(p[0], p[1], ref);
    }
    vol += sc.area * (ref[2] - cur_z);
    return vol;
}

double inclhv(const double* p, const double* ref, int d) {
    double v = 1.0;
    for (int k = 0; k < d; ++k) v *= ref[k] - p[k];
    return v;
}

// b weakly dominates a (minimisation); `strict` excludes equality.
inline bool dominates(const double* b, const double* a, int d) {
    bool any_lt = false;
    for (int k = 0; k < d; ++k) {
        if (b[k] > a[k]) return false;
        if (b[k] < a[k]) any_lt = true;
    }
    return any_lt;
}

inline bool equal_pt(const double* b, const double* a, int d) {
    for (int k = 0; k < d; ++k)
        if (b[k] != a[k]) return false;
    return true;
}

// Non-dominated filter (keeps one copy of duplicates), O(m² d).
Front nds(const Front& f) {
    const std::size_t n = f.size();
    Front out;
    out.d = f.d;
    std::vector<bool> keep(n, true);
    for (std::size_t a = 0; a < n; ++a) {
        if (!keep[a]) continue;
        for (std::size_t b = 0; b < n; ++b) {
            if (a == b || !keep[b]) continue;
            if (dominates(f.row(b), f.row(a), f.d) ||
                (b < a && equal_pt(f.row(b), f.row(a), f.d))) {
                keep[a] = false;
                break;
            }
        }
    }
    for (std::size_t a = 0; a < n; ++a)
        if (keep[a]) out.push(f.row(a));
    return out;
}

double wfg(Front& f, const double* ref);

// Exclusive hypervolume of point i against the points after it, for
// d >= 4 (wfg's base cases absorb d <= 3). Because wfg sorts its
// front DESCENDING on the last objective, every later point has
// last coordinate <= p_i's, so each limited point max(p_i, p_j)
// shares p_i's last coordinate exactly and the union of their boxes
// is a slab: the whole term factorises into
//   (ref[d-1] - p_i[d-1]) * exclusive volume in the first d-1 dims.
// Each recursion level therefore DROPS a dimension (the WFG "slicing"
// step) instead of re-recursing at full d, bottoming out in the
// linearithmic 2-D/3-D staircase sweeps.
double exclhv(const Front& f, std::size_t i, const double* ref) {
    const int d = f.d;
    const double* pi = f.row(i);
    const std::size_t n = f.size();
    const double slab = ref[d - 1] - pi[d - 1];
    double inner = inclhv(pi, ref, d - 1);
    if (i + 1 < n) {
        Front lim;
        lim.d = d - 1;
        std::vector<double> q(d - 1);
        for (std::size_t j = i + 1; j < n; ++j) {
            const double* pj = f.row(j);
            // maxes of below-ref points stay below ref: no clipping
            for (int k = 0; k < d - 1; ++k)
                q[k] = std::max(pi[k], pj[k]);
            lim.push(q.data());
        }
        // exclhv only runs at d >= 5 (wfg's base cases take d <= 3 and
        // wfg4_sorted takes d == 4), so lim.d >= 4: always worth the
        // non-domination filter before recursing
        Front limited = nds(lim);
        inner -= wfg(limited, ref);
    }
    return slab * inner;
}

// d=4 sweep over a front already sorted DESCENDING on the 4th
// objective: each term is (slab in obj 4) x (3-D exclusive volume),
// and the 3-D limited set {max(p_i, p_j) : j > i} streams out
// already z-sorted — max(z_i, z_j) is non-decreasing along an
// ascending-3rd-objective walk — so each inner pass is pure
// staircase sweep, no sort.
//
// The outer loop runs i DESCENDING while a z-sorted
// structure-of-arrays of the points {j : j > i} grows by one
// insertion per step — and is PRUNED to its 3-D-nondominated subset.
// Pruning is volume-neutral: if q 3-D-dominates p (minimisation,
// componentwise), then max(p_i, q) <= max(p_i, p) componentwise for
// every p_i, so p's limited box is inside q's and the staircase union
// never misses it. A newly inserted point i has the LARGEST 4th
// objective among the live set, and on real fronts that correlates
// with small first-three coordinates, so insertions keep collapsing
// the live set — the inner sweep walks a short Pareto staircase, not
// all n-1-i survivors. This is where the old 1.6x constant-factor
// loss to the reference's AVL dimension-sweep at large-n d=4
// (BASELINE.md) was paid.
double wfg4_sorted(const Front& f, const double* ref) {
    const std::size_t n = f.size();
    // z-sorted arrays of the live (3-D-nondominated) points after i;
    // grown by memmove (sequential doubles — cheaper than any node
    // structure at the resulting sizes)
    std::vector<double> zx, zy, zz;
    zx.reserve(n);
    zy.reserve(n);
    zz.reserve(n);
    Staircase sc;
    double total = 0.0;
    for (std::size_t ii = n; ii-- > 0;) {
        const double* pi = f.row(ii);
        const double slab = ref[3] - pi[3];
        const double pi0 = pi[0], pi1 = pi[1], pi2 = pi[2];
        double inner = inclhv(pi, ref, 3);
        sc.reset();
        double vol3 = 0.0, cur_z = 0.0;
        bool first = true;
        const std::size_t live = zz.size();
        for (std::size_t k = 0; k < live; ++k) {
            const double z = std::max(pi2, zz[k]);
            if (first) {
                cur_z = z;
                first = false;
            }
            vol3 += sc.area * (z - cur_z);
            cur_z = z;
            sc.insert(std::max(pi0, zx[k]), std::max(pi1, zy[k]), ref);
        }
        if (!first) vol3 += sc.area * (ref[2] - cur_z);
        total += slab * (inner - vol3);
        // point i joins the live set for the remaining (smaller) i's
        // unless 3-D-dominated; any members it dominates drop out
        bool dominated = false;
        for (std::size_t k = 0; k < zz.size(); ++k) {
            if (zz[k] > pi2) break;  // z-sorted: no dominator past here
            if (zx[k] <= pi0 && zy[k] <= pi1) {
                dominated = true;
                break;
            }
        }
        if (dominated) continue;
        std::size_t w = 0;
        for (std::size_t k = 0; k < zz.size(); ++k) {
            const bool doomed =
                zz[k] >= pi2 && zx[k] >= pi0 && zy[k] >= pi1;
            if (!doomed) {
                zx[w] = zx[k];
                zy[w] = zy[k];
                zz[w] = zz[k];
                ++w;
            }
        }
        zx.resize(w);
        zy.resize(w);
        zz.resize(w);
        const std::size_t pos = std::lower_bound(zz.begin(), zz.end(),
                                                 pi2) - zz.begin();
        zz.insert(zz.begin() + pos, pi2);
        zx.insert(zx.begin() + pos, pi0);
        zy.insert(zy.begin() + pos, pi1);
    }
    return total;
}

double wfg(Front& f, const double* ref) {
    if (f.size() == 0) return 0.0;
    if (f.d == 1) {
        double m = ref[0];
        for (std::size_t i = 0; i < f.size(); ++i)
            m = std::min(m, f.row(i)[0]);
        return ref[0] - m;
    }
    if (f.d == 2) return hv2d(f, ref);
    if (f.d == 3) return hv3d(f, ref);
    // Sorting by the last objective descending shrinks limited sets
    // fastest (the classic WFG heuristic) — and makes the dimension-
    // dropping factorisation in exclhv/wfg4_sorted valid.
    const std::size_t n = f.size();
    std::vector<std::size_t> idx(n);
    for (std::size_t i = 0; i < n; ++i) idx[i] = i;
    const int d = f.d;
    std::sort(idx.begin(), idx.end(), [&](std::size_t a, std::size_t b) {
        return f.row(a)[d - 1] > f.row(b)[d - 1];
    });
    Front sorted;
    sorted.d = d;
    for (std::size_t i : idx) sorted.push(f.row(i));
    if (d == 4) return wfg4_sorted(sorted, ref);
    double total = 0.0;
    for (std::size_t i = 0; i < n; ++i) total += exclhv(sorted, i, ref);
    return total;
}

Front prepare(const double* data, int n, int d, const double* ref) {
    Front f;
    f.d = d;
    for (int i = 0; i < n; ++i) {
        const double* p = data + static_cast<std::size_t>(i) * d;
        bool below = true;
        for (int k = 0; k < d; ++k)
            if (p[k] >= ref[k]) { below = false; break; }
        if (below) f.push(p);
    }
    // the d<=3 base cases absorb dominated/duplicate points natively,
    // and the d=4 sweep's pruned live set does too (a 4-D-dominated
    // point's term telescopes to zero; WFG's exclusive-volume chain
    // is an identity for ANY set, filtered or not) — at those dims
    // the O(n^2) filter would dominate the actual computation
    // (measured: 40 of 42 ms at d=3 n=2000, 40 of 66 ms at d=4
    // n=2000 was this filter). From d=5 the recursion's limited sets
    // multiply, so pre-shrinking the front is worth the quadratic
    // pass.
    return d <= 4 ? f : nds(f);
}

}  // namespace

extern "C" {

// Exact hypervolume of `data` ([n, d] row-major, minimisation) w.r.t. ref.
double deap_tpu_hypervolume(const double* data, int n, int d,
                            const double* ref) {
    if (n <= 0 || d <= 0) return 0.0;
    Front f = prepare(data, n, d, ref);
    return wfg(f, ref);
}

// Leave-one-out exclusive contribution of every point — the quantity
// behind the reference's least-contributor indicator
// (deap/tools/indicator.py:10-31). Computed DIRECTLY per point:
//   contrib(i) = V(box(p_i, ref)) - HV({p_j maxed with p_i : j != i})
// i.e. the inclusive box minus the part the others cover once clipped
// into it — no full-front recompute per point (the r3 implementation
// paid n whole-front WFG runs; the clipped sets here are small and
// heavily dominated, and d==3 dispatches to the linearithmic sweep).
// Points that are dominated, duplicated, or not strictly below the
// reference get exactly 0, as with leave-one-out.
void deap_tpu_hv_contributions(const double* data, int n, int d,
                               const double* ref, double* out) {
    if (n <= 0 || d <= 0) return;
    std::vector<double> q(d);
    for (int i = 0; i < n; ++i) {
        const double* pi = data + static_cast<std::size_t>(i) * d;
        bool below = true;
        for (int k = 0; k < d; ++k)
            if (pi[k] >= ref[k]) { below = false; break; }
        if (!below) { out[i] = 0.0; continue; }
        Front lim;
        lim.d = d;
        for (int j = 0; j < n; ++j) {
            if (j == i) continue;
            const double* pj = data + static_cast<std::size_t>(j) * d;
            bool inside = true;
            for (int k = 0; k < d; ++k) {
                q[k] = std::max(pi[k], pj[k]);
                if (q[k] >= ref[k]) { inside = false; break; }
            }
            if (inside) lim.push(q.data());
        }
        double covered = 0.0;
        if (lim.size()) {
            if (d <= 4) {
                // the d<=3 staircase base cases and the d=4 pruned
                // sweep absorb dominated/duplicate rows natively (the
                // same telescoping identity as prepare()); the O(m^2)
                // filter would dominate them
                covered = wfg(lim, ref);
            } else {
                Front reduced = nds(lim);
                covered = wfg(reduced, ref);
            }
        }
        out[i] = inclhv(pi, ref, d) - covered;
    }
}

}  // extern "C"
