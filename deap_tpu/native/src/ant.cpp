// Native artificial-ant simulator over prefix-encoded GP action trees.
//
// Counterpart of the reference's AntSimulatorFast
// (/root/reference/examples/gp/ant/AntSimulatorFast.cpp) — the "fast
// native fitness" pattern (SURVEY.md §2.2): the hot rollout runs in
// C++ while generation/variation stay in the Python framework. Where
// the reference's C++ simulator calls back into Python GP closures
// per node (AntSimulatorFast.cpp:167-200), trees here arrive as the
// framework's prefix arrays and execute natively end-to-end.
//
// Exposed C ABI (ctypes-loaded by deap_tpu/native/ant_binding.py):
//   deap_tpu_ant_eval(nodes, lengths, pop, max_len, trail, rows, cols,
//                     max_moves, start_row, start_col, start_dir,
//                     out_eaten)
//
// Node encoding matches deap_tpu.gp.ant.ant_pset(): ops 0/1/2 =
// if_food_ahead/prog2/prog3, terminals const_id+0/1/2 =
// move_forward/turn_left/turn_right (const_id == 3 for this set).

#include <cstdint>
#include <cstring>
#include <vector>

namespace {

constexpr int IF_FOOD_AHEAD = 0;
constexpr int PROG2 = 1;
constexpr int PROG3 = 2;
constexpr int CONST_ID = 3;  // ant_pset: 3 ops, 0 args
constexpr int MOVE_FORWARD = 0;
constexpr int TURN_LEFT = 1;
constexpr int TURN_RIGHT = 2;

const int DIR_ROW[4] = {1, 0, -1, 0};   // north/east/south/west
const int DIR_COL[4] = {0, 1, 0, -1};

struct Sim {
    const int32_t* nodes;
    int len;
    std::vector<uint8_t> grid;   // row-major food map (mutated)
    int rows, cols;
    int row, col, dir;
    int moves, max_moves, eaten;

    int arity(int32_t node) const {
        if (node == PROG3) return 3;
        if (node < CONST_ID) return 2;
        return 0;
    }

    // exclusive end of the subtree at i (searchSubtree arity walk)
    int skip(int i) const {
        int pending = 1;
        while (pending > 0 && i < len) {
            pending += arity(nodes[i]) - 1;
            ++i;
        }
        return i;
    }

    bool food_ahead() const {
        int r = (row + DIR_ROW[dir] + rows) % rows;
        int c = (col + DIR_COL[dir] + cols) % cols;
        return grid[r * cols + c] != 0;
    }

    void action(int a) {
        if (moves >= max_moves) return;
        ++moves;
        if (a == TURN_LEFT) {
            dir = (dir + 3) % 4;
        } else if (a == TURN_RIGHT) {
            dir = (dir + 1) % 4;
        } else {  // MOVE_FORWARD
            row = (row + DIR_ROW[dir] + rows) % rows;
            col = (col + DIR_COL[dir] + cols) % cols;
            uint8_t& cell = grid[row * cols + col];
            if (cell) {
                ++eaten;
                cell = 0;
            }
        }
    }

    // execute the subtree at i; returns its exclusive end
    int exec(int i) {
        int32_t node = nodes[i];
        switch (node) {
            case IF_FOOD_AHEAD: {
                int c1 = i + 1;
                int c2 = skip(c1);
                int end = skip(c2);
                if (food_ahead()) exec(c1); else exec(c2);
                return end;
            }
            case PROG2: {
                int c2 = exec(i + 1);
                return exec(c2);
            }
            case PROG3: {
                int c2 = exec(i + 1);
                int c3 = exec(c2);
                return exec(c3);
            }
            default:
                action(node - CONST_ID);
                return i + 1;
        }
    }

    int run() {
        while (moves < max_moves) exec(0);
        return eaten;
    }
};

}  // namespace

extern "C" void deap_tpu_ant_eval(
    const int32_t* nodes, const int32_t* lengths, int pop, int max_len,
    const uint8_t* trail, int rows, int cols, int max_moves,
    int start_row, int start_col, int start_dir, int32_t* out_eaten) {
    for (int p = 0; p < pop; ++p) {
        Sim sim;
        sim.nodes = nodes + static_cast<int64_t>(p) * max_len;
        sim.len = lengths[p];
        sim.grid.assign(trail, trail + rows * cols);
        sim.rows = rows;
        sim.cols = cols;
        sim.row = start_row;
        sim.col = start_col;
        sim.dir = start_dir;
        sim.moves = 0;
        sim.max_moves = max_moves;
        sim.eaten = 0;
        out_eaten[p] = sim.run();
    }
}
