"""Native (C++) accelerated routines, with graceful Python fallbacks.

Mirrors the reference's import pattern for its C hypervolume extension
(/root/reference/deap/tools/indicator.py:3-8, benchmarks/tools.py:18-23):
try the compiled extension, fall back to the pure implementation with a
warning.
"""

import warnings

try:
    from deap_tpu.native.hv_binding import hypervolume as _hv_native
    from deap_tpu.native.hv_binding import hv_contributions
    HAVE_NATIVE_HV = True

    def hypervolume(points, ref):
        return _hv_native(points, ref)
except Exception:  # pragma: no cover - exercised when the ext is absent
    HAVE_NATIVE_HV = False
    warnings.warn(
        "Native hypervolume extension not built; using the pure-Python "
        "WFG fallback (slow for large fronts). Build it with "
        "`python -m deap_tpu.native.build`.")
    from deap_tpu.native.pyhv import hypervolume

    def hv_contributions(points, ref):
        """Leave-one-out contributions via the pure-Python hv."""
        import numpy as np

        pts = np.asarray(points, dtype=np.float64)
        total = hypervolume(pts, ref)
        return np.asarray([
            total - hypervolume(np.delete(pts, i, axis=0), ref)
            for i in range(pts.shape[0])])

__all__ = ["hypervolume", "hv_contributions", "HAVE_NATIVE_HV"]
