"""ctypes binding for the native ant simulator.

The reference accelerates the artificial-ant fitness with a hand-written
CPython extension (/root/reference/examples/gp/ant/AntSimulatorFast.cpp,
built by buildAntSimFast.py); here the C++ simulator exports a plain C
ABI over the framework's prefix-tree arrays and this module loads it
with ctypes. Importing raises if the library is missing and cannot be
built (callers fall back to the vmap'd JAX rollout in
:mod:`deap_tpu.gp.ant`).
"""

from __future__ import annotations

import ctypes
import pathlib

import numpy as np

_LIB_PATH = pathlib.Path(__file__).resolve().parent / "_libant.so"
_SRC_PATH = pathlib.Path(__file__).resolve().parent / "src" / "ant.cpp"

if not _LIB_PATH.exists() or (
    _SRC_PATH.exists() and _SRC_PATH.stat().st_mtime > _LIB_PATH.stat().st_mtime
):
    from deap_tpu.native.build import build

    build(verbose=False, target="ant.cpp")

_lib = ctypes.CDLL(str(_LIB_PATH))

_i32p = ctypes.POINTER(ctypes.c_int32)
_lib.deap_tpu_ant_eval.restype = None
_lib.deap_tpu_ant_eval.argtypes = [
    _i32p, _i32p, ctypes.c_int, ctypes.c_int,
    ctypes.POINTER(ctypes.c_uint8), ctypes.c_int, ctypes.c_int,
    ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_int, _i32p]


def ant_eval(nodes, lengths, trail, start, max_moves: int = 600,
             start_dir: int = 1) -> np.ndarray:
    """Evaluate a population of ant trees natively.

    :param nodes: int32 [pop, max_len] prefix node arrays
        (deap_tpu.gp.ant.ant_pset encoding).
    :param lengths: int32 [pop].
    :param trail: bool [rows, cols] food map.
    :param start: (row, col) start cell.
    :returns: int32 [pop] food eaten.
    """
    nodes = np.ascontiguousarray(nodes, np.int32)
    lengths = np.ascontiguousarray(lengths, np.int32)
    trail8 = np.ascontiguousarray(trail, np.uint8)
    pop, max_len = nodes.shape
    out = np.zeros((pop,), np.int32)
    _lib.deap_tpu_ant_eval(
        nodes.ctypes.data_as(_i32p), lengths.ctypes.data_as(_i32p),
        pop, max_len,
        trail8.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        trail8.shape[0], trail8.shape[1], max_moves,
        int(start[0]), int(start[1]), start_dir,
        out.ctypes.data_as(_i32p))
    return out
