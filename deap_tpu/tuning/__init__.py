"""Self-tuning dispatch runtime — probe-and-persist winner selection
for every static dispatch knob (see :mod:`deap_tpu.tuning.tuner` for
the protocol and docs/advanced/tuning.md for the knob table)."""

from deap_tpu.tuning.cache import CACHE_FORMAT, TuningCache, default_dir
from deap_tpu.tuning.tuner import (DispatchTuner, KNOBS, active_tuner,
                                   disable, enable, env_override,
                                   int_env, is_concrete, note_hlo_drift,
                                   resolve, resolve_int, shape_bucket)

__all__ = [
    "CACHE_FORMAT", "TuningCache", "default_dir", "DispatchTuner",
    "KNOBS", "active_tuner", "disable", "enable", "env_override",
    "int_env", "is_concrete", "note_hlo_drift", "resolve",
    "resolve_int", "shape_bucket",
]
