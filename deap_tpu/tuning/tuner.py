"""Probe-and-persist dispatch tuner.

Every hardcoded dispatch guess in the codebase — the nd-sort impl
thresholds, the GP interpreter mode, host-vs-device compaction, the
CMA eigh solver, fused-vs-unfused variation, serving ``segment_len``,
and the Scheduler's batched-vs-solo GP admission — was measured on one
CPU.  On a new backend those numbers are guesses.  This module closes
the loop: at first use of a tunable decision point on a given
``(backend, device_kind, knob, shape-bucket)`` key the
:class:`DispatchTuner` *short-probes* the candidate implementations —
warm each (the compile), time min-of-reps, assert bit-identity between
candidates before trusting either (the ``bench_gp.suite_gps`` probe
protocol, generalised) — picks the measured winner, persists it in the
:class:`~deap_tpu.tuning.cache.TuningCache` next to the compile cache,
and journals the decision as a ``tuning_decision`` event.

Decision ladder (first match wins), implemented by :func:`resolve`:

1. ``DEAP_TPU_TUNE_<KNOB>`` env var — the explicit escape hatch,
   honoured even when the tuner is disabled.
2. Tuning-cache hit (tuner enabled) — a prior process probed this key.
3. Short probe (tuner enabled, call site can probe — concrete inputs,
   not under jit tracing) — measure, persist, journal.
4. The static heuristic default — exactly the pre-tuner behaviour.

The tuner is **off by default** (``DEAP_TPU_TUNE=1`` or
:func:`enable` opts in), so every existing code path, test pin and
benchmark keeps today's static behaviour bit-for-bit until a user asks
for measured dispatch.  Correctness never rides on the probe: every
candidate set is either bit-identical by construction (pinned by the
existing parity suites) or cross-checked by a tolerance predicate
(``eigh``), and an identity failure falls back to the static default
and journals the failure instead of trusting a fast wrong answer.

Stale entries are evicted by the cost observatory's ``hlo_drift``
alarm (:func:`note_hlo_drift`, wired in ``telemetry/costs.py``) and by
the cache-format / jax-version stamp (``cache.py``).
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from deap_tpu.tuning.cache import CACHE_FORMAT, TuningCache

#: master switch: truthy value auto-enables a process-wide tuner
ENV_ENABLE = "DEAP_TPU_TUNE"

#: per-knob override prefix: ``DEAP_TPU_TUNE_<KNOB>`` (knob upper-cased)
ENV_PREFIX = "DEAP_TPU_TUNE_"

#: the tunable decision points: knob -> (candidate values, static default
#: description).  The single source for the docs table and the health
#: report's ledger; candidate sets marked '*' are cross-checked by
#: tolerance instead of bitwise (see docs/advanced/tuning.md).
KNOBS = {
    "nd_impl": (("matrix", "tiled", "staircase", "sweep", "dc"),
                "backend/n/nobj threshold matrix (mo/emo.py)"),
    "nd_cross": (("xla", "pallas"),
                 "'pallas' on TPU, 'xla' elsewhere (cache/env only)"),
    "gp_mode": (("scan", "sweep", "grouped"),
                "'grouped' in make_symbreg_loop, 'scan' elsewhere"),
    "compaction": (("host", "device"),
                   "'host' on CPU, 'device' on accelerators"),
    "eigh_impl": (("lapack", "jacobi"),
                  "'lapack' (tolerance-checked*, not bitwise)"),
    "fused": (("unfused", "fused_xla", "fused_kernel"),
              "fused when capable: kernel on TPU, XLA elsewhere"),
    "segment_len": (None, "10 (cache/env only; probed by bench --tuning)"),
    "gp_batch": (("batched", "solo"),
                 "'batched' (union-mask multi-tenant lanes)"),
}

_ACTIVE: list = [None]
_ENV_CHECKED: list = [False]

#: (knob, bucket) pairs already journaled this process — decisions are
#: journaled once per key, not once per call (nd_rank runs every
#: generation; the ledger wants decisions, not a heartbeat)
_SEEN: set = set()


# ----------------------------------------------------------- env plumbing ----

def _truthy(value: Optional[str]) -> bool:
    return bool(value) and value.strip().lower() in ("1", "on", "true",
                                                     "yes")


def env_override(knob: str) -> Optional[str]:
    """The ``DEAP_TPU_TUNE_<KNOB>`` escape hatch, or None."""
    value = os.environ.get(ENV_PREFIX + knob.upper())
    if value is None or not value.strip():
        return None
    return value.strip()


def int_env(name: str, default: int) -> int:
    """Integer threshold override ``DEAP_TPU_TUNE_<NAME>`` (the
    ``ND_*_THRESHOLD`` family), falling back to ``default`` on unset
    or unparseable values."""
    value = os.environ.get(ENV_PREFIX + name.upper())
    if value is None or not value.strip():
        return default
    try:
        return int(value)
    except ValueError:
        return default


# ------------------------------------------------------------- activation ----

def enable(cache_dir: Optional[str] = None, *, reps: int = 2,
           strict_identity: bool = False) -> "DispatchTuner":
    """Install a process-wide tuner (idempotent per call — a second
    call replaces the first, dropping its session memo)."""
    tuner = DispatchTuner(cache_dir, reps=reps,
                          strict_identity=strict_identity)
    _ACTIVE[0] = tuner
    _ENV_CHECKED[0] = True
    return tuner


def disable() -> None:
    """Remove the active tuner; also blocks the ``DEAP_TPU_TUNE`` env
    auto-enable for the rest of the process (tests use this to pin
    static behaviour regardless of environment)."""
    _ACTIVE[0] = None
    _ENV_CHECKED[0] = True


def active_tuner() -> Optional["DispatchTuner"]:
    """The installed tuner, auto-creating one on first call when
    ``DEAP_TPU_TUNE`` is truthy. None == every decision point uses its
    static default (today's behaviour)."""
    tuner = _ACTIVE[0]
    if tuner is not None:
        return tuner
    if not _ENV_CHECKED[0]:
        _ENV_CHECKED[0] = True
        if _truthy(os.environ.get(ENV_ENABLE)):
            _ACTIVE[0] = DispatchTuner()
            return _ACTIVE[0]
    return None


def _reset_for_tests() -> None:
    """Forget activation latches and journal dedup (test isolation)."""
    _ACTIVE[0] = None
    _ENV_CHECKED[0] = False
    _SEEN.clear()


# ------------------------------------------------------------- inspection ----

def is_concrete(*trees: Any) -> bool:
    """True when no leaf of any pytree is a jax tracer — probing (and
    any timing at all) is only meaningful on concrete values; under a
    ``jit`` trace the decision ladder stops at the cache."""
    import jax

    for tree in trees:
        for leaf in jax.tree_util.tree_leaves(tree):
            if isinstance(leaf, jax.core.Tracer):
                return False
    return True


def shape_bucket(n: int) -> int:
    """Pow-2 ceiling — the shape-bucket component of tuning keys, so a
    pop of 4000 and 4096 share one probed winner."""
    n = max(int(n), 1)
    return 1 << (n - 1).bit_length()


def _journal(knob: str, bucket: Tuple, **payload: Any) -> None:
    from deap_tpu.telemetry.journal import broadcast

    broadcast("tuning_decision", knob=knob,
              bucket="/".join(str(b) for b in bucket), **payload)


def _journal_once(knob: str, bucket: Tuple, **payload: Any) -> None:
    token = (knob, tuple(bucket), payload.get("source"),
             payload.get("winner"))
    if token in _SEEN:
        return
    _SEEN.add(token)
    _journal(knob, bucket, **payload)


# ------------------------------------------------------------ the tuner ----

class DispatchTuner:
    """Probe-and-persist winner selection for one process.

    ``reps`` is the min-of-reps timing count after the warm-up call
    (which pays the compile and is excluded). ``strict_identity=True``
    turns an identity failure into a raise instead of a journaled
    fallback — the test suite's setting."""

    def __init__(self, cache: Any = None, *, reps: int = 2,
                 strict_identity: bool = False):
        self.cache = (cache if isinstance(cache, TuningCache)
                      else TuningCache(cache))
        self.reps = max(int(reps), 1)
        self.strict_identity = bool(strict_identity)
        #: key -> winner, the in-process memo (one probe / file read
        #: per key per process)
        self._session: Dict[str, str] = {}

    # ------------------------------------------------------------- keys ----

    def stamp(self) -> Dict[str, Any]:
        import jax

        return {"format": CACHE_FORMAT, "jax": jax.__version__}

    def key_for(self, knob: str, bucket: Sequence[Any]) -> str:
        import jax

        device = jax.devices()[0]
        parts = [jax.default_backend(),
                 str(getattr(device, "device_kind", "unknown")).replace(
                     " ", "_"),
                 str(knob)] + [str(b) for b in bucket]
        return "/".join(parts)

    # ---------------------------------------------------------- deciding ----

    def decide(self, knob: str, *, bucket: Tuple, default: str,
               candidates: Optional[Dict[str, Any]] = None,
               check: Any = "bitwise",
               program: Optional[str] = None) -> str:
        """Cache → probe → static, returning the winning candidate
        name. ``candidates`` maps name -> zero-arg probe fn, ``(fn,
        weight)`` (timing divided by ``weight`` — the batched-vs-solo
        per-lane normalisation), or ``None`` when this call site
        cannot probe (tracing, missing inputs)."""
        key = self.key_for(knob, bucket)
        memo = self._session.get(key)
        if memo is not None:
            return memo
        names = tuple(candidates) if candidates else ()
        entry = self.cache.get(key, stamp=self.stamp())
        if entry is not None and (not names
                                  or entry.get("winner") in names):
            winner = str(entry["winner"])
            _journal_once(knob, bucket, source="cache", winner=winner,
                          default=default, cache_hit=True,
                          probe_s=entry.get("probe_s"),
                          program=program)
            self._session[key] = winner
            return winner
        probeable = bool(candidates) and all(
            callable(c[0] if isinstance(c, tuple) else c)
            for c in candidates.values())
        if not probeable:
            # not memoised: a later call with concrete inputs on the
            # same key should still get its chance to probe
            _journal_once(knob, bucket, source="static", winner=default,
                          default=default, cache_hit=False,
                          program=program)
            return default
        winner, timings, probe_s, identity = self._probe(candidates,
                                                         check)
        if winner is None or identity == "failed":
            reason = ("identity" if identity == "failed"
                      else "all candidates failed")
            if identity == "failed" and self.strict_identity:
                raise AssertionError(
                    f"tuning probe for {knob!r} {bucket!r}: candidates "
                    "disagree — refusing to pick a winner")
            _journal(knob, bucket, source="static", winner=default,
                     default=default, cache_hit=False, timings=timings,
                     probe_s=round(probe_s, 6), identity=identity,
                     reason=reason, program=program)
            self._session[key] = default
            return default
        self.record(knob, bucket, winner, timings=timings,
                    probe_s=probe_s, identity=identity, program=program,
                    default=default)
        return winner

    def record(self, knob: str, bucket: Tuple, winner: str, *,
               timings: Dict[str, Optional[float]], probe_s: float,
               identity: str = "bitwise",
               program: Optional[str] = None,
               default: Optional[str] = None) -> None:
        """Persist + journal a measured decision (the tail of
        :meth:`decide`; also the entry point for external probes like
        ``bench.py --tuning``'s segment-length sweep)."""
        key = self.key_for(knob, bucket)
        self.cache.put(key, {
            "winner": winner,
            "timings": {k: (round(v, 6) if v is not None else None)
                        for k, v in timings.items()},
            "probe_s": round(float(probe_s), 6),
            "identity": identity,
            "program": program,
            "stamp": self.stamp(),
            "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        })
        _journal(knob, bucket, source="probe", winner=winner,
                 default=default, cache_hit=False, timings=timings,
                 probe_s=round(float(probe_s), 6), identity=identity,
                 program=program)
        self._session[key] = winner

    # ----------------------------------------------------------- probing ----

    def _probe(self, candidates: Dict[str, Any], check: Any):
        import jax

        t0 = time.perf_counter()
        timings: Dict[str, Optional[float]] = {}
        results: Dict[str, Any] = {}
        for name, cand in candidates.items():
            fn, weight = (cand if isinstance(cand, tuple)
                          else (cand, 1.0))
            try:
                results[name] = jax.block_until_ready(fn())  # warm
                best = float("inf")
                for _ in range(self.reps):
                    t1 = time.perf_counter()
                    jax.block_until_ready(fn())
                    best = min(best, time.perf_counter() - t1)
                timings[name] = best / float(weight)
            except Exception:
                # a candidate that cannot run must never win — and a
                # broken probe must never break the caller
                results.pop(name, None)
                timings[name] = None
        probe_s = time.perf_counter() - t0
        live = {k: v for k, v in timings.items() if v is not None}
        if not live:
            return None, timings, probe_s, "skipped"
        identity = self._check_identity(results, check)
        winner = min(live, key=live.get)
        return winner, timings, probe_s, identity

    @staticmethod
    def _check_identity(results: Dict[str, Any], check: Any) -> str:
        """'bitwise' / 'tolerance' / 'failed' / 'skipped'."""
        if check is None or len(results) < 2:
            return "skipped"
        if callable(check):
            try:
                return "tolerance" if check(results) else "failed"
            except Exception:
                return "failed"
        import jax
        import numpy as np

        ref_leaves = None
        for res in results.values():
            leaves = [np.asarray(leaf)
                      for leaf in jax.tree_util.tree_leaves(res)]
            if ref_leaves is None:
                ref_leaves = leaves
                continue
            if len(leaves) != len(ref_leaves):
                return "failed"
            for a, b in zip(ref_leaves, leaves):
                if (a.shape != b.shape or a.dtype != b.dtype
                        or a.tobytes() != b.tobytes()):
                    return "failed"
        return "bitwise"


# ----------------------------------------------------------- entry points ----

def resolve(knob: str, *, bucket: Tuple = (), default: str,
            candidates: Optional[Dict[str, Any]] = None,
            check: Any = "bitwise",
            program: Optional[str] = None) -> str:
    """The one call every tunable decision point makes — the env /
    cache / probe / static ladder (module docstring). Returns a
    candidate name; the caller maps it onto its own dispatch."""
    env = env_override(knob)
    if env is not None:
        names = tuple(candidates) if candidates else ()
        if names and env not in names:
            raise ValueError(
                f"{ENV_PREFIX}{knob.upper()}={env!r} is not a valid "
                f"candidate here (expected one of {sorted(names)})")
        _journal_once(knob, bucket, source="env", winner=env,
                      default=default, cache_hit=False, program=program)
        return env
    tuner = active_tuner()
    if tuner is None:
        return default
    return tuner.decide(knob, bucket=bucket, default=default,
                        candidates=candidates, check=check,
                        program=program)


def resolve_int(knob: str, *, bucket: Tuple = (), default: int,
                program: Optional[str] = None) -> int:
    """:func:`resolve` for integer-valued knobs (``segment_len``):
    env / cache / static — never probed inline (an integer knob has no
    candidate closures at the call site; ``bench.py --tuning`` probes
    and persists it out of band via :meth:`DispatchTuner.record`)."""
    winner = resolve(knob, bucket=bucket, default=str(int(default)),
                     candidates=None, check=None, program=program)
    try:
        value = int(winner)
    except (TypeError, ValueError):
        return int(default)
    return value if value >= 1 else int(default)


def note_hlo_drift(program: str) -> int:
    """Evict every tuning entry recorded against observatory label
    ``program`` — called from ``ProgramObservatory._drift`` when the
    same (label, signature) recompiles to a different HLO hash. The
    measured winner belonged to the old program; re-probe. Returns the
    eviction count (0 when no tuner is active)."""
    tuner = active_tuner()
    if tuner is None:
        return 0
    evicted = tuner.cache.evict_program(str(program))
    if evicted:
        from deap_tpu.telemetry.journal import broadcast

        for key in evicted:
            tuner._session.pop(key, None)
            broadcast("tuning_invalidation", key=key,
                      program=str(program), reason="hlo_drift")
    return len(evicted)
