"""Persistent probe-winner cache for the dispatch tuner.

One JSON document holding every measured dispatch decision, stored next
to the persistent XLA compile cache (``support/compilecache.py``) so
the two artifacts that make a process warm-start — compiled programs
and the dispatch choices that select between them — live side by side
and are wiped together.

Stdlib-only by design: ``telemetry/report.py`` renders the tuning
ledger without jax, and offline tooling (CI tripwires, a human with
``python -m json.tool``) must be able to read and edit the cache the
same way.

Entry shape (one per tuner key — see ``tuner.DispatchTuner.key_for``)::

    {
      "winner": "dc",                    # candidate name that measured fastest
      "timings": {"dc": 0.0021, ...},    # min-of-reps seconds per candidate
      "probe_s": 0.31,                   # wall cost of the whole probe
      "identity": "bitwise",             # how candidates were cross-checked
      "program": "nd_rank",              # observatory label for drift eviction
      "stamp": {"format": 1, "jax": "0.9.0"},
      "recorded_at": "2026-08-07T..",
    }

The file-level ``format`` stamp and the per-entry ``stamp`` implement
the invalidation ladder: a cache-format bump discards the whole file, a
jax upgrade misses every old entry (backend and device kind are part of
the *key*, so a new accelerator simply probes fresh keys), and an
``hlo_drift`` alarm evicts the entries whose ``program`` recompiled to
a different HLO (``tuner.note_hlo_drift``).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Dict, List, Optional

#: bump to discard every existing cache file on format changes
CACHE_FORMAT = 1

#: directory override for the tuning cache (highest precedence)
ENV_DIR = "DEAP_TPU_TUNING_CACHE"

FILENAME = "tuning_cache.json"


def default_dir() -> str:
    """Resolve the cache directory: ``$DEAP_TPU_TUNING_CACHE``, else
    the enabled compile-cache directory (the "next to the compile
    cache" contract), else ``~/.cache/deap_tpu``."""
    env = os.environ.get(ENV_DIR)
    if env:
        return env
    try:
        from deap_tpu.support import compilecache
        path = compilecache.sibling_cache_dir()
        if path:
            return path
    except Exception:
        pass
    return os.path.join(os.path.expanduser("~"), ".cache", "deap_tpu")


class TuningCache:
    """Atomic read-merge-write JSON store of probe winners.

    Writes go through a tempfile + ``os.replace`` so a crashed or
    concurrent process can never leave a torn file, and every ``put``
    re-reads the file first so two processes probing different knobs
    merge instead of clobbering (last writer wins per key, which is
    fine — both measured the same machine)."""

    def __init__(self, directory: Optional[str] = None):
        self.dir = str(directory) if directory else default_dir()
        self.path = os.path.join(self.dir, FILENAME)
        self._entries: Optional[Dict[str, Dict[str, Any]]] = None

    # -------------------------------------------------------------- read ----

    def _read_file(self) -> Dict[str, Dict[str, Any]]:
        try:
            with open(self.path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict) or doc.get("format") != CACHE_FORMAT:
            # unknown format: ignore rather than guess — the probe
            # protocol re-derives everything in one short pass
            return {}
        entries = doc.get("entries")
        return dict(entries) if isinstance(entries, dict) else {}

    def entries(self) -> Dict[str, Dict[str, Any]]:
        if self._entries is None:
            self._entries = self._read_file()
        return self._entries

    def refresh(self) -> None:
        """Drop the in-memory view; next access re-reads the file."""
        self._entries = None

    def get(self, key: str, stamp: Optional[Dict[str, Any]] = None
            ) -> Optional[Dict[str, Any]]:
        entry = self.entries().get(key)
        if entry is None:
            return None
        if stamp is not None and entry.get("stamp") != stamp:
            return None
        return entry

    # ------------------------------------------------------------- write ----

    def put(self, key: str, entry: Dict[str, Any]) -> None:
        merged = self._read_file()
        merged.update(self.entries())
        merged[str(key)] = dict(entry)
        self._entries = merged
        self._write(merged)

    def evict(self, keys) -> List[str]:
        """Remove ``keys`` (those present); returns the evicted list."""
        merged = self._read_file()
        merged.update(self.entries())
        gone = [k for k in keys if merged.pop(k, None) is not None]
        self._entries = merged
        if gone:
            self._write(merged)
        return gone

    def evict_program(self, program: str) -> List[str]:
        """Evict every entry recorded against observatory label
        ``program`` — the ``hlo_drift`` invalidation path."""
        entries = self.entries()
        stale = [k for k, e in entries.items()
                 if e.get("program") == program]
        return self.evict(stale)

    def clear(self) -> None:
        self._entries = {}
        try:
            os.remove(self.path)
        except OSError:
            pass

    def _write(self, entries: Dict[str, Dict[str, Any]]) -> None:
        doc = {
            "format": CACHE_FORMAT,
            "updated_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "entries": entries,
        }
        os.makedirs(self.dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.dir, prefix=".tuning.",
                                   suffix=".json")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
