"""Island-model evolution over a device mesh.

Counterpart of the reference's island examples: master-driven SCOOP
islands (examples/ga/onemax_island_scoop.py:51-69, P4), peer-to-peer
pipe-ring processes (examples/ga/onemax_island.py:45-75, P5) and
in-process multi-demic evolution (onemax_multidemic.py, P6). Here all
three collapse into one SPMD program: demes are stacked in a
``[n_islands, island_size, ...]`` tensor, sharded over the mesh's
``"island"`` axis with ``shard_map``; every deme evolves ``freq``
generations locally (a vmapped, scanned generation step), then the
emigrant block rides a ``ppermute`` ring one hop — intra-device demes
shift locally, the boundary deme crosses ICI. The blocking send/recv of
the reference's ``migPipe`` is inherent to SPMD lockstep.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deap_tpu.algorithms import evaluate_invalid, var_and
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population, gather, init_population
from deap_tpu.ops.selection import sel_best
from deap_tpu.parallel.mesh import axis_size, shard_map, sharding_fallback
from deap_tpu.support.profiling import span

IslandState = Population  # demes stacked on the leading axis


def island_init(key: jax.Array, n_islands: int, island_size: int,
                init_genome: Callable, spec: FitnessSpec) -> Population:
    """Stacked island populations: leaves ``[n_islands, island_size, ...]``."""
    keys = jax.random.split(key, n_islands)
    return jax.vmap(
        lambda k: init_population(k, island_size, init_genome, spec))(keys)


def _local_generation(key, pop, toolbox, cxpb, mutpb):
    """One eaSimple generation on a single deme (algorithms.py:163-181)."""
    k_sel, k_var = jax.random.split(key)
    idx = toolbox.select(k_sel, pop.wvalues, pop.size)
    off = var_and(k_var, gather(pop, idx), toolbox, cxpb, mutpb)
    return evaluate_invalid(off, toolbox.evaluate)


def _migrate_local(key, pops, k, selection):
    """Ring-shift emigrants across the deme axis of a stacked tensor."""
    from deap_tpu.parallel.migration import mig_ring
    return mig_ring(key, pops, k, selection=selection)


def _migrate_sharded(key, pops, k, selection, axis_name):
    """Ring migration when the deme axis is split over ``axis_name``:
    demes shift emigrants locally; the last local deme's emigrants
    ppermute to the next mesh slice's first deme."""
    m = pops.valid.shape[0]  # local demes per device
    key = jax.random.fold_in(key, lax.axis_index(axis_name))
    keys = jax.random.split(key, m)

    w = pops.fitness * pops.spec.warray
    w = jnp.where(pops.valid[..., None], w, -jnp.inf)
    emi_idx = jax.vmap(lambda kk, ww: selection(kk, ww, k))(keys, w)

    def take_rows(a):
        return jax.vmap(lambda x, i: jnp.take(x, i, axis=0))(a, emi_idx)

    def shift(rows):
        # rows: [m, k, ...]; destination deme j gets rows from deme j-1,
        # deme 0 gets the previous device's deme m-1 over the ring.
        n = axis_size(axis_name)
        perm = [(i, (i + 1) % n) for i in range(n)]
        with span("island/ppermute"):
            incoming0 = lax.ppermute(rows[-1], axis_name, perm)
        return jnp.concatenate([incoming0[None], rows[:-1]], axis=0)

    def put_rows(a, rows):
        return jax.vmap(lambda x, i, r: x.at[i].set(r))(a, emi_idx, rows)

    move = lambda a: put_rows(a, shift(take_rows(a)))
    return pops.replace(
        genomes=jax.tree_util.tree_map(move, pops.genomes),
        extras=jax.tree_util.tree_map(move, pops.extras),
        fitness=move(pops.fitness),
        valid=put_rows(pops.valid,
                       shift(jax.vmap(jnp.take)(pops.valid, emi_idx))),
    )


def _flatten_demes(pops: Population) -> Population:
    """Merge the deme axis into the individual axis — a stacked
    ``[n_islands, island_size, ...]`` island tensor viewed as one flat
    population, the shape every standard probe expects."""
    flat = lambda a: jnp.reshape(a, (-1,) + a.shape[2:])
    return pops.replace(
        genomes=jax.tree_util.tree_map(flat, pops.genomes),
        extras=jax.tree_util.tree_map(flat, pops.extras),
        fitness=flat(pops.fitness),
        valid=flat(pops.valid),
    )


def make_island_step(toolbox, cxpb: float, mutpb: float, freq: int,
                     mig_k: int, mesh: Optional[Mesh] = None,
                     axis_name: str = "island",
                     selection: Callable = sel_best,
                     telemetry=None, probes=(), plan=None,
                     donate: bool = False):
    """Build ``step(key, pops) -> pops``: ``freq`` local generations then
    one ring migration (the reference's FREQ-generation epoch,
    onemax_island_scoop.py:64-67). Jit-compatible; pass a ``mesh`` to run
    each deme on its own mesh slice.

    ``plan`` (a :class:`deap_tpu.parallel.ShardingPlan`, mutually
    exclusive with ``mesh``) selects the **mesh-native** formulation:
    the epoch is ONE global jitted program whose stacked-deme tensor is
    sharded over the plan's axis, and migration is plain
    :func:`~deap_tpu.parallel.migration.mig_ring` over the deme axis —
    the XLA partitioner turns the emigrant roll into a
    collective-permute, i.e. migration becomes *resharding under one
    jitted program* instead of hand-written ``ppermute`` choreography.
    Because the program is global, its results are bit-identical to
    the single-device path on ANY mesh size — the property elastic
    resume relies on (checkpoint at n=8, resume at n=4/n=1;
    ``tests/test_sharding_plan.py``). ``donate=True`` additionally
    donates the ``pops`` (and meter) carry per epoch — the caller must
    not reuse the argument after the call. On a jax without pjit-plan
    support the builder falls back to the ``mesh``/shard_map path with
    a journaled ``sharding_fallback`` event.

    With ``telemetry`` (a :class:`deap_tpu.telemetry.RunTelemetry`) the
    returned step is ``step(key, pops, mstate) -> (pops, mstate)``: a
    Meter state rides the same jit'd program (epoch counters, migrant
    counter, cross-island best/mean gauges — still zero host round
    trips). On the mesh path the best/mean gauges are reduced *inside*
    the shard_map'd epoch via ``pmax``/``psum`` collectives (each under
    a named profiling span, like every collective in this package), so
    the probe pipeline survives sharded demes without gathering the
    population. ``probes`` adds population probes, applied to the
    deme-flattened epoch output. Build the initial state with
    ``telemetry.meter.init()`` *after* this call (declaration happens
    here), and journal epochs via ``telemetry.record_row`` or
    ``telemetry.journal.meter_rows``.
    """

    def epoch(key, pops, migrate):
        n_local = pops.valid.shape[0]

        def gen(pops, k):
            keys = jax.random.split(k, n_local)
            return jax.vmap(
                lambda kk, p: _local_generation(kk, p, toolbox, cxpb, mutpb)
            )(keys, pops), None

        k_gen, k_mig = jax.random.split(key)
        pops, _ = lax.scan(gen, pops, jax.random.split(k_gen, freq))
        return migrate(k_mig, pops)

    tel = telemetry

    def _local_stats(pops):
        """Per-shard sufficient statistics for the cross-island
        best/mean gauges: max, sum and valid count over local demes."""
        w0 = jnp.where(pops.valid,
                       (pops.fitness * pops.spec.warray)[..., 0], -jnp.inf)
        return (jnp.max(w0),
                jnp.sum(jnp.where(pops.valid, w0, 0.0)),
                jnp.sum(pops.valid.astype(jnp.float32)))

    if plan is not None:
        if mesh is not None:
            raise ValueError("pass either mesh= (shard_map path) or "
                             "plan= (pjit path), not both")
        if plan.mode != "pjit":
            # loud, journaled degradation: the explicit shard_map ring
            # still runs the sharded program, just without the
            # partitioner-owned single-program formulation
            sharding_fallback(
                "make_island_step",
                "pjit plan unavailable; selecting the shard_map path",
                n_devices=plan.describe()["n_devices"])
            mesh, axis_name, plan = plan.mesh, plan.axis, None

    if plan is not None:
        # mesh-native path: the SAME global program as the mesh-None
        # branch (mig_ring's deme-axis roll IS the migration), with the
        # stacked-deme tensor pinned to the plan's layout so the
        # partitioner shards demes across devices and lowers the roll
        # to a collective-permute. No hand-written collectives remain.
        def pjit_epoch(key, pops):
            pops = plan.constrain(pops)
            out = epoch(key, pops, partial(_migrate_local, k=mig_k,
                                           selection=selection))
            return plan.constrain(out)

        base = pjit_epoch
        base_tel = lambda key, pops: (
            lambda out: (out, _local_stats(out)))(pjit_epoch(key, pops))
    elif mesh is None:
        base = lambda key, pops: epoch(
            key, pops, partial(_migrate_local, k=mig_k, selection=selection))
        base_tel = lambda key, pops: (
            lambda out: (out, _local_stats(out)))(base(key, pops))
    else:
        spec_sharded = P(axis_name)

        def sharded_epoch(key, pops):
            return epoch(key, pops, lambda kk, pp: _migrate_sharded(
                kk, pp, mig_k, selection, axis_name))

        def sharded_epoch_tel(key, pops):
            # meter reductions ride the same shard_map'd program as the
            # epoch itself: per-shard stats collapse to replicated
            # scalars via pmax/psum, each inside a named span so the
            # probe overhead stays attributable per collective
            pops = sharded_epoch(key, pops)
            lmax, lsum, lcnt = _local_stats(pops)
            with span("island/pmax"):
                gmax = lax.pmax(lmax, axis_name)
            with span("island/psum"):
                gsum = lax.psum(lsum, axis_name)
                gcnt = lax.psum(lcnt, axis_name)
            return pops, (gmax, gsum, gcnt)

        base = shard_map(
            sharded_epoch, mesh=mesh,
            in_specs=(P(), spec_sharded), out_specs=spec_sharded)
        base_tel = shard_map(
            sharded_epoch_tel, mesh=mesh,
            in_specs=(P(), spec_sharded),
            out_specs=(spec_sharded, (P(), P(), P())))

    if tel is None:
        if probes:
            raise ValueError("probes= requires telemetry= (a "
                             "RunTelemetry): probe state rides the "
                             "telemetry Meter carry")
        if plan is not None:
            return plan.compile(base,
                                donate_argnums=(1,) if donate else (),
                                label="island_step")
        return jax.jit(base)

    meter = tel.meter
    meter.counter("epochs")
    meter.counter("generations")
    meter.counter("migrants")
    meter.gauge("best")
    meter.gauge("mean")
    if tel.probe is not None and hasattr(tel.probe, "declare"):
        tel.probe.declare(meter)
    tel.add_probes(probes)

    def instrumented(key, pops, mstate):
        # one compiled program, no host round trips; the evolutionary
        # computation itself is byte-for-byte the uninstrumented one
        # (meter reductions read the epoch output, feed nothing back)
        pops, (gmax, gsum, gcnt) = base_tel(key, pops)
        n_islands = pops.valid.shape[0]
        mstate = meter.inc(mstate, "epochs")
        mstate = meter.inc(mstate, "generations", freq)
        mstate = meter.inc(mstate, "migrants", mig_k * n_islands)
        mstate = meter.set(mstate, "best", gmax)
        mstate = meter.set(mstate, "mean",
                           gsum / jnp.maximum(gcnt, 1.0))
        mstate = tel.apply_probe(mstate, pop=_flatten_demes(pops))
        return pops, mstate

    if plan is not None:
        return plan.compile(instrumented,
                            donate_argnums=(1, 2) if donate else (),
                            label="island_step")
    return jax.jit(instrumented)
