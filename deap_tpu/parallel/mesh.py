"""Device mesh construction and population sharding.

The reference's distribution story is "swap toolbox.map for a parallel
map" — multiprocessing.Pool (P2), SCOOP network futures (P3)
(SURVEY.md §2.3). The TPU-native equivalent is data placement: the
population tensor is sharded over a `jax.sharding.Mesh` and every
compiled generation step runs SPMD, XLA inserting ICI/DCN collectives
where the program needs them. Multi-host (the SCOOP analog) is the same
program under `jax.distributed` initialisation — no code change.

Axes convention:
- ``"pop"``   — data-parallel population sharding (P2/P3): selection is
  kept device-local or global depending on the operator's needs.
- ``"island"``— one sub-population per mesh slice (P4/P5/P6), migration
  via `lax.ppermute` ring (see migration.py).
- ``"genome"``— genome-axis (SP/CP-shaped) sharding for very large
  genomes, e.g. neuroevolution weight vectors (SURVEY.md §5.7).
"""

from __future__ import annotations

import inspect
from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deap_tpu.support.profiling import span


# ------------------------------------------------- plan-mode selection ----
#
# The NamedSharding/pjit sharding *plan* (deap_tpu.parallel.plan) needs
# three capabilities from the installed jax: NamedSharding itself,
# jit-level buffer donation (``donate_argnums``), and an in-jit layout
# pin (``with_sharding_constraint``). All three exist on the pinned
# jax 0.4.37; on a jax where any is missing the plan must fall back to
# the explicit shard_map path — LOUDLY (a journaled ``sharding_fallback``
# event), never by silently computing the unsharded program.

#: cached mode — [None] until first probe; tests pin e.g. ["shard_map"]
#: to exercise the fallback selection without faking a jax install
_MODE_CACHE: list = [None]


def _detect_sharding_mode() -> str:
    try:
        from jax.sharding import NamedSharding as _NS  # noqa: F401
    except Exception:
        return "shard_map"
    if not hasattr(jax.lax, "with_sharding_constraint"):
        return "shard_map"
    try:
        if "donate_argnums" not in inspect.signature(jax.jit).parameters:
            return "shard_map"
    except (TypeError, ValueError):
        pass  # builtins without signatures: assume the documented API
    return "pjit"


def sharding_mode() -> str:
    """``'pjit'`` when the installed jax can run the NamedSharding plan
    (the preferred path — one global program, the XLA partitioner owns
    the collectives, ``donate_argnums`` honoured); ``'shard_map'`` when
    it cannot and plan consumers must select their explicit
    shard_map/ppermute formulation instead."""
    if _MODE_CACHE[0] is None:
        _MODE_CACHE[0] = _detect_sharding_mode()
    return _MODE_CACHE[0]


_FALLBACK_SEEN: set = set()


def sharding_fallback(where: str, reason: str, **ctx) -> None:
    """Journal a loud ``sharding_fallback`` event: a plan consumer could
    not take the pjit path and selected a degraded formulation instead.
    Deduplicated per (where, reason) so a fallback taken inside a loop
    does not flood the journal — but never silent: the first occurrence
    always lands in every open journal."""
    key = (where, reason)
    if key in _FALLBACK_SEEN:
        return
    _FALLBACK_SEEN.add(key)
    from deap_tpu.telemetry.journal import broadcast

    broadcast("sharding_fallback", where=where, reason=reason,
              mode=sharding_mode(), **ctx)


def axis_size(axis_name: str):
    """Size of a named mesh axis, from inside ``shard_map``/``pmap``.

    ``lax.axis_size`` only exists on newer jax; on 0.4.x the standard
    spelling is ``psum(1)`` over the axis, which constant-folds to the
    axis size at trace time (no runtime collective).
    """
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def shard_map(f: Callable, mesh: Mesh, in_specs, out_specs) -> Callable:
    """``jax.shard_map`` across the API move.

    Newer jax exposes ``jax.shard_map(..., check_vma=...)``; 0.4.x only
    has ``jax.experimental.shard_map.shard_map(..., check_rep=...)``.
    Every shard_map in this package goes through here (replication
    checking off in both spellings — the collectives are explicit) so
    the version split lives in exactly one place.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)


def population_mesh(n_devices: Optional[int] = None,
                    axis_names: Sequence[str] = ("pop",),
                    shape: Optional[Sequence[int]] = None) -> Mesh:
    """Build a mesh over the first ``n_devices`` devices.

    Default is a 1-D ``("pop",)`` mesh; pass ``axis_names=("island",)``
    for island runs or ``("island", "genome")`` with ``shape`` for 2-D
    layouts.
    """
    devices = jax.devices()
    if n_devices is None:
        n_devices = len(devices)
    devices = devices[:n_devices]
    if shape is None:
        shape = (n_devices,) + (1,) * (len(axis_names) - 1)
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names)


def shard_population(pop, mesh: Mesh, axis: str = "pop"):
    """Place a Population with its individual axis sharded over ``axis``.

    All leaves share leading axis n; fitness/valid/extras follow the same
    partitioning so a generation step touches only local rows until a
    collective is explicitly requested.
    """
    sharding = NamedSharding(mesh, P(axis))

    def place(x):
        with span("mesh/reshard"):
            return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(place, pop)
