"""Ring migration between demes.

Counterpart of /root/reference/deap/tools/migration.py:4-51 (``migRing``)
and the pipe-ring of examples/ga/onemax_island.py:45-75. Two layouts:

- :func:`mig_ring` — demes stacked in one tensor ``[n_demes, deme, ...]``
  on one device (P6, multi-demic in-process): pure ``jnp.roll`` of the
  emigrant block.
- :func:`mig_ring_collective` — inside ``shard_map`` with one deme per
  mesh slice (P4/P5): the emigrant block rides a ``lax.ppermute`` ring
  over ICI; SPMD lockstep gives the blocking send/recv semantics of the
  reference's ``migPipe`` for free (SURVEY.md §2.3).

Selection semantics mirror the reference: ``selection`` picks the k
emigrants of each deme; ``replacement`` picks which k rows of the
*destination* deme are overwritten (default: the same rows the
destination's own emigrants came from, i.e. emigrants are replaced —
migration.py:23-27).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.core.population import Population, gather
from deap_tpu.ops.selection import sel_best, sel_worst
from deap_tpu.parallel.mesh import axis_size
from deap_tpu.support.profiling import span


def _emigrant_idx(key, pop, k, selection):
    return selection(key, pop.wvalues, k)


def mig_ring(key: jax.Array, pops: Population, k: int,
             selection: Callable = sel_best,
             replacement: Optional[Callable] = None,
             migarray: Optional[jnp.ndarray] = None) -> Population:
    """Ring migration over stacked demes ``[n_demes, deme_size, ...]``.

    Deme i's emigrants overwrite the replaced rows of deme
    ``migarray[i]`` (default: ``i+1 mod n`` — the serial ring).
    ``migarray`` follows the reference contract (migration.py:29-30):
    each deme index appears exactly once (a permutation), so every deme
    sends and receives one emigrant block.
    """
    n_demes = pops.valid.shape[0]
    keys = jax.random.split(key, 2 * n_demes)
    sel_keys, rep_keys = keys[:n_demes], keys[n_demes:]

    def per_deme_idx(key, w):
        return selection(key, w, k)

    w = pops.fitness * pops.spec.warray
    w = jnp.where(pops.valid[..., None], w, -jnp.inf)
    emi_idx = jax.vmap(per_deme_idx)(sel_keys, w)  # [n_demes, k]
    if replacement is None:
        rep_idx = emi_idx
    else:
        rep_idx = jax.vmap(lambda kk, ww: replacement(kk, ww, k))(rep_keys, w)

    def take_rows(a):
        # a: [n_demes, deme, ...] → emigrant rows [n_demes, k, ...]
        return jax.vmap(lambda x, i: jnp.take(x, i, axis=0))(a, emi_idx)

    def put_rows(a, rows):
        return jax.vmap(lambda x, i, r: x.at[i].set(r))(a, rep_idx, rows)

    if migarray is None:
        # deme i → deme i+1: destination j receives from j-1
        route = lambda r: jnp.roll(r, shift=1, axis=0)
    else:
        import numpy as np

        dest_host = np.asarray(migarray, np.int32)
        if sorted(dest_host.tolist()) != list(range(n_demes)):
            raise ValueError(
                "migarray must be a permutation of deme indices "
                f"0..{n_demes - 1} (each exactly once, the reference's "
                f"contract, migration.py:29-30); got {dest_host.tolist()}")
        dest = jnp.asarray(dest_host)
        # incoming[j] = emigrants[inv[j]] where dest[inv[j]] == j
        inv = jnp.zeros(n_demes, jnp.int32).at[dest].set(
            jnp.arange(n_demes, dtype=jnp.int32), unique_indices=True)
        route = lambda r: jnp.take(r, inv, axis=0)

    genomes = jax.tree_util.tree_map(
        lambda a: put_rows(a, route(take_rows(a))), pops.genomes)
    extras = jax.tree_util.tree_map(
        lambda a: put_rows(a, route(take_rows(a))), pops.extras)
    fitness = put_rows(pops.fitness, route(take_rows(pops.fitness)))
    valid_rows = jax.vmap(lambda v, i: jnp.take(v, i))(pops.valid, emi_idx)
    valid = put_rows(pops.valid, route(valid_rows))
    return pops.replace(genomes=genomes, extras=extras, fitness=fitness,
                        valid=valid)


def mig_ring_collective(key: jax.Array, pop: Population, k: int,
                        axis_name: str,
                        selection: Callable = sel_best,
                        replacement: Optional[Callable] = None,
                        migarray: Optional[Sequence[int]] = None
                        ) -> Population:
    """Ring migration across mesh slices, for use inside ``shard_map``.

    ``pop`` is the device-local deme; emigrants travel along
    ``axis_name`` via ``lax.ppermute`` (P4/P5 over ICI) — one hop by
    default, or to ``migarray[i]`` per source slice ``i`` (a static
    permutation, the reference's migarray contract).
    """
    ksel, krep = jax.random.split(jax.random.fold_in(key, lax.axis_index(axis_name)))
    w = pop.wvalues
    emi_idx = selection(ksel, w, k)
    rep_idx = emi_idx if replacement is None else replacement(krep, w, k)

    emigrants = gather(pop, emi_idx)
    n = axis_size(axis_name)
    if migarray is None:
        perm = [(i, (i + 1) % n) for i in range(n)]
    else:
        dests = [int(d) for d in migarray]
        if sorted(dests) != list(range(n)):
            # fail loudly: a slice with no sender would silently
            # receive zeros from ppermute, corrupting its deme
            raise ValueError(
                "migarray must be a permutation of slice indices "
                f"0..{n - 1} (each exactly once); got {dests}")
        perm = list(enumerate(dests))
    with span("migration/ppermute"):
        incoming = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), emigrants)

    genomes = jax.tree_util.tree_map(
        lambda a, r: a.at[rep_idx].set(r), pop.genomes, incoming.genomes)
    extras = jax.tree_util.tree_map(
        lambda a, r: a.at[rep_idx].set(r), pop.extras, incoming.extras)
    return pop.replace(
        genomes=genomes,
        extras=extras,
        fitness=pop.fitness.at[rep_idx].set(incoming.fitness),
        valid=pop.valid.at[rep_idx].set(incoming.valid),
    )


# DEAP-style alias
migRing = mig_ring
