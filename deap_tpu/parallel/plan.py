"""ShardingPlan — the one mesh-native sharding plan every loop consumes.

The pmap/shard_map-era layout of this package made each loop family
hand-roll its own collectives: ``genome_shard`` wrapped its evaluator in
``shard_map`` + ``psum``, ``island`` choreographed ``ppermute`` rings,
and checkpoints were welded to the mesh they were written on. This
module replaces that with the idiom peer JAX systems converged on
(SNIPPETS.md [2]/[3]): a single *plan* object that owns

- **Mesh construction** — one :class:`jax.sharding.Mesh` with named
  axes (``"pop"`` for data-parallel populations, ``"island"`` for
  deme-per-slice island runs, ``"genome"`` for feature-axis sharding);
- **PartitionSpec helpers** — per-leaf :class:`NamedSharding` built by
  a divisibility rule (leading axis sharded over the plan axis when it
  divides evenly; scalars, PRNG keys and odd-sized leaves replicated),
  so a whole carry pytree (population + hall of fame + meter state)
  gets a consistent layout from one call;
- **a pjit-preferred compile wrapper** — :meth:`compile` is
  ``jax.jit`` with ``donate_argnums``: the generation-step buffers are
  *donated* instead of copied (XLA aliases the carry in and out — the
  per-step population copy disappears, see ``bench.py --mesh``), and
  the XLA partitioner — not hand-written collectives — inserts
  whatever communication the global program needs. On a jax without
  NamedSharding/jit-donation support the plan degrades to the
  explicit shard_map formulations, journaled loudly as
  ``sharding_fallback`` (see :func:`deap_tpu.parallel.mesh
  .sharding_mode`).

Because a plan-compiled program is a *global* program (sharding is
layout, not semantics), its results are bit-identical across mesh
sizes — the property that makes **elastic resume** cheap: a checkpoint
written on an n=8 mesh (per-shard leaf layout, checkpoint format v3)
restores onto an n=4 or n=1 plan through one :meth:`place` reshard step
and the run continues bit-exactly (``tests/test_sharding_plan.py``).
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deap_tpu.parallel.mesh import (population_mesh, sharding_fallback,
                                    sharding_mode)
from deap_tpu.support.profiling import span

__all__ = ["ShardingPlan"]


def _is_prng_key(leaf: Any) -> bool:
    try:
        return isinstance(leaf, jax.Array) and jnp.issubdtype(
            leaf.dtype, jax.dtypes.prng_key)
    except Exception:
        return False


class ShardingPlan:
    """One sharding plan: mesh + spec helpers + compile wrapper.

    :param mesh: a prebuilt :class:`~jax.sharding.Mesh`; default is a
        1-D mesh over all local devices named ``axis``.
    :param axis: the mesh axis the *leading data axis* (population rows
        or stacked demes) shards over — ``"pop"`` for the scan loops,
        ``"island"`` for island runs.
    :param donate: honour ``donate_argnums`` in :meth:`compile`
        (default True). Callers that must re-read an argument after the
        call (parity oracles, retries from in-memory state) pass
        ``donate=False`` or compile without donation.

    Typical use::

        plan = ShardingPlan.for_population()        # all devices
        pop, logbook, hof = ea_simple(key, pop, tb, .5, .2, 100,
                                      plan=plan)
        # or: ResilientRun(dir, plan=plan).ea_simple(...)
    """

    def __init__(self, mesh: Optional[Mesh] = None, *, axis: str = "pop",
                 axis2: Optional[str] = None, donate: bool = True):
        if mesh is None:
            mesh = population_mesh(
                axis_names=(axis,) if axis2 is None else (axis, axis2))
        if axis not in mesh.axis_names:
            raise ValueError(f"plan axis {axis!r} not in mesh axes "
                             f"{mesh.axis_names}")
        if axis2 is not None and axis2 not in mesh.axis_names:
            raise ValueError(f"plan axis2 {axis2!r} not in mesh axes "
                             f"{mesh.axis_names}")
        self.mesh = mesh
        self.axis = axis
        #: optional second data axis: rank>=2 leaves whose first two
        #: dims divide the (axis, axis2) mesh tile shard over BOTH —
        #: the ("run", "island") layout for batched island serving
        self.axis2 = axis2
        self.donate = bool(donate)
        self.mode = sharding_mode()
        if self.mode != "pjit":
            sharding_fallback(
                "ShardingPlan", "jax lacks NamedSharding/jit-donation "
                "support; plan consumers select their shard_map paths")

    # ------------------------------------------------------ constructors ----

    @classmethod
    def for_population(cls, n_devices: Optional[int] = None,
                       **kwargs) -> "ShardingPlan":
        """1-D ``("pop",)`` plan over the first ``n_devices`` devices
        (default: all)."""
        return cls(population_mesh(n_devices, axis_names=("pop",)),
                   axis="pop", **kwargs)

    @classmethod
    def for_islands(cls, n_devices: Optional[int] = None,
                    **kwargs) -> "ShardingPlan":
        """1-D ``("island",)`` plan: stacked demes, one slice per
        device."""
        return cls(population_mesh(n_devices, axis_names=("island",)),
                   axis="island", **kwargs)

    @classmethod
    def for_island_runs(cls, n_runs: Optional[int] = None,
                        n_devices: Optional[int] = None,
                        **kwargs) -> "ShardingPlan":
        """2-D ``("run", "island")`` plan for the batched island engine:
        the run axis of :class:`deap_tpu.serving.gp_multirun.
        IslandMultiRunEngine` shards over ``"run"``, each run's stacked
        demes over ``"island"``. ``n_runs`` is the run-axis mesh extent
        (must divide the device count; default: all devices on the run
        axis, islands replicated per device). The layout rule stays
        value-free — a lane's epoch program is the same global program
        whatever the tile shape."""
        devices = jax.devices()
        total = len(devices) if n_devices is None else int(n_devices)
        r = total if n_runs is None else int(n_runs)
        if r < 1 or total % r != 0:
            raise ValueError(f"n_runs={r} must divide the device "
                             f"count {total}")
        mesh = population_mesh(total,
                               axis_names=("run", "island"),
                               shape=(r, total // r))
        return cls(mesh, axis="run", axis2="island", **kwargs)

    @property
    def n_shards(self) -> int:
        return self.mesh.shape[self.axis]

    @property
    def n_shards2(self) -> int:
        return (self.mesh.shape[self.axis2]
                if self.axis2 is not None else 1)

    # ------------------------------------------------------ spec helpers ----

    def spec(self, *axes: Optional[str]) -> P:
        """A :class:`PartitionSpec` over this plan's mesh axes."""
        return P(*axes)

    @property
    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, P())

    @property
    def row_sharding(self) -> NamedSharding:
        """Leading-axis sharding over the plan axis — the population /
        stacked-deme layout."""
        return NamedSharding(self.mesh, P(self.axis))

    def leaf_sharding(self, leaf: Any) -> NamedSharding:
        """The plan's layout for one leaf: leading axis sharded over
        the plan axis when it divides evenly, replicated otherwise
        (scalars, PRNG key arrays, hall-of-fame rows smaller than the
        mesh, strategy-state vectors). With ``axis2`` set (the 2-D
        ``("run", "island")`` preset), rank>=2 leaves whose first TWO
        dims divide the mesh tile shard over both axes. The rule is
        deliberately value-free — layout can never change what a
        global program computes, only where it computes it."""
        shape = getattr(leaf, "shape", None)
        if (shape is None or len(shape) == 0 or _is_prng_key(leaf)
                or shape[0] == 0 or shape[0] % self.n_shards != 0):
            return self.replicated
        if (self.axis2 is not None and len(shape) >= 2
                and shape[1] > 0 and shape[1] % self.n_shards2 == 0):
            return NamedSharding(self.mesh, P(self.axis, self.axis2))
        return self.row_sharding

    def tree_shardings(self, tree: Any) -> Any:
        """Per-leaf :class:`NamedSharding` pytree for ``tree`` (the
        ``in_shardings`` shape of the plan, SNIPPETS.md [2])."""
        return jax.tree_util.tree_map(self.leaf_sharding, tree)

    # --------------------------------------------------------- placement ----

    def place(self, tree: Any, fresh: Optional[bool] = None) -> Any:
        """Reshard ``tree`` onto this plan — the elastic-resume step: a
        restored (or caller-supplied) state pytree is committed to this
        plan's mesh leaf-by-leaf per :meth:`leaf_sharding`.

        ``fresh`` (default: ``self.donate``) guarantees the returned
        leaves are *new* buffers even when ``device_put`` would have
        aliased an already-correctly-placed input — required before
        handing the tree to a donating :meth:`compile` call, which
        deletes its argument buffers (the caller's array must survive).
        """
        if self.mode != "pjit":
            sharding_fallback("ShardingPlan.place",
                              "no NamedSharding support: placement "
                              "skipped, arrays stay where they are")
            return tree
        if fresh is None:
            fresh = self.donate

        def put(leaf):
            if not isinstance(leaf, (jax.Array, np.ndarray, jnp.ndarray)):
                return leaf
            with span("plan/reshard"):
                out = jax.device_put(leaf, self.leaf_sharding(leaf))
                if fresh and isinstance(leaf, jax.Array):
                    # device_put may ALIAS the source buffer even when
                    # it returns a new Array object (e.g. the device-0
                    # replica of a replicated placement reuses the
                    # committed input buffer) — a later donation would
                    # then delete the caller's array out from under
                    # them. One explicit copy per run entry buys the
                    # guarantee; ``fresh=False`` skips it.
                    out = jnp.copy(out)
            return out

        return jax.tree_util.tree_map(put, tree)

    # alias: a Population is just a state pytree to the plan
    shard_population = place
    place_state = place

    def constrain(self, tree: Any) -> Any:
        """In-jit layout pin: ``with_sharding_constraint`` per leaf (the
        same divisibility rule as :meth:`place`), used by the step
        factories to keep the population sharded across generation
        boundaries instead of letting the partitioner replicate it
        after a gather. No-op (journaled) on the fallback path."""
        if self.mode != "pjit":
            sharding_fallback("ShardingPlan.constrain",
                              "no with_sharding_constraint: layout "
                              "left to the partitioner")
            return tree

        def pin(leaf):
            if not isinstance(leaf, (jax.Array, jnp.ndarray)) and not (
                    hasattr(leaf, "shape") and hasattr(leaf, "dtype")):
                return leaf
            with span("plan/constrain"):
                return jax.lax.with_sharding_constraint(
                    leaf, self.leaf_sharding(leaf))

        return jax.tree_util.tree_map(pin, tree)

    # ------------------------------------------------------------ compile ----

    def compile(self, fn: Callable, *, donate_argnums: Tuple[int, ...] = (),
                static_argnums=(), static_argnames=None,
                label: str = "plan") -> Callable:
        """The pjit-preferred compile wrapper (SNIPPETS.md [3]): on the
        pjit path this is ``jax.jit`` with ``donate_argnums`` — the
        partitioner owns the collectives (sharding flows in from the
        :meth:`place`-committed arguments) and donated generation-step
        buffers alias in-place instead of being copied. On the fallback
        path the function still compiles, but without donation and
        without sharding — journaled as ``sharding_fallback`` so the
        degradation is never silent."""
        from deap_tpu.telemetry import costs

        kwargs = {}
        if static_argnums:
            kwargs["static_argnums"] = static_argnums
        if static_argnames is not None:
            kwargs["static_argnames"] = static_argnames
        donating = False
        if self.mode != "pjit":
            sharding_fallback(f"ShardingPlan.compile[{label}]",
                              "pjit path unavailable: compiling "
                              "without sharding or donation")
        elif donate_argnums and self.donate:
            kwargs["donate_argnums"] = donate_argnums
            donating = True
        # the AOT seam: with a ProgramObservatory active, every program
        # this plan compiles is profiled (cost/memory analysis, compile
        # time, HLO fingerprint → `program_profile` journal events, the
        # donation contract proven per program) — a no-op None check
        # per call otherwise
        return costs.instrument(
            jax.jit(fn, **kwargs), label=f"plan/{label}",
            static_argnums=tuple(static_argnums or ()),
            static_argnames=tuple(static_argnames or ()),
            donating=donating)

    # --------------------------------------------------------- metadata ----

    def describe(self) -> dict:
        """Mesh metadata stamped into checkpoint ``meta`` so a restore
        can tell (and journal) when it is an *elastic* resume onto a
        different mesh than the one the checkpoint was written on."""
        return {"axes": list(self.mesh.axis_names),
                "shape": [int(s) for s in self.mesh.devices.shape],
                "axis": self.axis, "axis2": self.axis2,
                "n_devices": int(self.mesh.devices.size)}

    def __repr__(self) -> str:
        shape = dict(zip(self.mesh.axis_names,
                         self.mesh.devices.shape))
        return (f"ShardingPlan(axis={self.axis!r}, mesh={shape}, "
                f"mode={self.mode!r}, donate={self.donate})")
