from deap_tpu.parallel.mesh import (
    population_mesh,
    shard_population,
    sharding_fallback,
    sharding_mode,
)
from deap_tpu.parallel.plan import ShardingPlan
from deap_tpu.parallel.migration import mig_ring, mig_ring_collective, migRing
from deap_tpu.parallel.island import IslandState, island_init, make_island_step
from deap_tpu.parallel.multihost import (
    global_population_mesh,
    initialize,
    is_distributed,
    process_count,
    process_index,
)
from deap_tpu.parallel.genome_shard import (
    genome_mesh,
    make_sharded_evaluator,
    shard_genomes,
)

__all__ = [
    "ShardingPlan",
    "sharding_mode",
    "sharding_fallback",
    "initialize",
    "is_distributed",
    "global_population_mesh",
    "process_count",
    "process_index",
    "population_mesh",
    "shard_population",
    "mig_ring",
    "mig_ring_collective",
    "migRing",
    "IslandState",
    "island_init",
    "genome_mesh",
    "make_sharded_evaluator",
    "shard_genomes",
    "make_island_step",
]
