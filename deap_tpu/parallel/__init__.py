from deap_tpu.parallel.mesh import population_mesh, shard_population
from deap_tpu.parallel.migration import mig_ring, migRing
from deap_tpu.parallel.island import IslandState, island_init, make_island_step

__all__ = [
    "population_mesh",
    "shard_population",
    "mig_ring",
    "migRing",
    "IslandState",
    "island_init",
    "make_island_step",
]
