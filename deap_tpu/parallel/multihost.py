"""Multi-host distribution — the SCOOP/network-futures analog.

The reference scales past one machine by registering SCOOP's network
``futures.map`` as ``toolbox.map`` (examples/ga/onemax_island_scoop.py:28,
doc/tutorials/basic/part4.rst:14-44; SURVEY.md §2.3 P3, §5.8). Payloads
are pickles over TCP and the programming model is master/worker.

The TPU-native replacement is SPMD over DCN: every host runs the *same*
compiled program, `jax.distributed` forms the runtime mesh, and XLA
inserts cross-host collectives wherever the sharded program needs them
— there is no master, no pickling, and population state never funnels
through one process. Concretely, the single-host examples scale out by
calling :func:`initialize` first and building meshes over
``jax.devices()`` (global) instead of ``jax.local_devices()``; nothing
else changes, which is this module's whole point.

Run one process per host, e.g.::

    # host 0                                 # host 1
    initialize("10.0.0.1:8476", 2, 0)        initialize("10.0.0.1:8476", 2, 1)
    mesh = global_population_mesh()          mesh = global_population_mesh()
    ... identical program on both hosts ...

On TPU pods the coordinator/process arguments are discovered from the
environment and may be omitted entirely.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from deap_tpu.parallel.mesh import population_mesh

__all__ = [
    "initialize",
    "is_distributed",
    "global_population_mesh",
    "process_count",
    "process_index",
]


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None,
               **kwargs) -> None:
    """Join (or form) the multi-host runtime.

    Thin wrapper over ``jax.distributed.initialize`` that is safe to
    call unconditionally: a single-process run (all arguments None and
    no cluster environment) is a no-op, so the same ``main()`` works on
    a laptop, one TPU host, or a pod slice — the moral equivalent of
    the reference's "works serially, add `-m scoop` to distribute".
    """
    # decide BEFORE touching any jax API: jax.distributed.initialize
    # must run before the XLA backend initialises, and even
    # jax.process_count() would initialise it
    if (coordinator_address is None and num_processes is None
            and process_id is None and not _cluster_env()):
        return
    jax.distributed.initialize(coordinator_address, num_processes,
                               process_id, **kwargs)


def _cluster_env() -> bool:
    import os

    return any(os.environ.get(k) for k in (
        "COORDINATOR_ADDRESS", "JAX_COORDINATOR_ADDRESS",
        "TPU_WORKER_ID", "CLOUD_TPU_TASK_ID"))


def is_distributed() -> bool:
    return jax.process_count() > 1


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def global_population_mesh(axis_names: Sequence[str] = ("pop",),
                           shape: Optional[Sequence[int]] = None):
    """Mesh over every device of every participating host.

    Identical to :func:`deap_tpu.parallel.population_mesh` (which it
    calls), spelled separately so multi-host intent is explicit in user
    code; under `jax.distributed`, ``jax.devices()`` already enumerates
    the global device set and collectives over the resulting mesh ride
    ICI within a host/slice and DCN across hosts.
    """
    return population_mesh(None, axis_names, shape)
