"""Genome-axis sharding — the SP/CP-shaped parallelism axis.

The reference has no sequence models; SURVEY.md §5.7 identifies the
genuine analog of "scaling one individual beyond a single worker":
genomes too large for one device's memory/FLOPs (neuroevolution weight
vectors, very long feature strings). The TPU-native mechanism is the
same as sequence/context parallelism for transformers: shard the
*feature* axis of the population tensor over a mesh axis with
``shard_map``, compute partial per-individual results locally, and
reduce with a ``psum`` collective — fitness reductions ride ICI instead
of materialising the full genome anywhere.

This composes with population sharding: a 2-D ``("pop", "genome")``
mesh shards both axes, the canonical DP×SP layout.

Every collective issued here runs inside a named profiling span
(``genome_shard/<collective>``, see support.profiling.span) so an
xplane trace attributes cross-shard time to the *specific* collective
— the instrumentation needed to pin the n=8 weak-scaling cliff
(VERDICT r5: 0.87 → 0.34 efficiency) on psum vs pmean vs pmax rather
than "the sharded step".
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deap_tpu.parallel.mesh import population_mesh, shard_map
from deap_tpu.support.profiling import span


def genome_mesh(n_pop_shards: Optional[int] = None,
                n_genome_shards: Optional[int] = None) -> Mesh:
    """A 2-D ``("pop", "genome")`` mesh. Defaults: all devices on the
    genome axis (pure SP)."""
    n_dev = len(jax.devices())
    if n_genome_shards is None:
        n_genome_shards = n_dev if n_pop_shards is None else (
            n_dev // n_pop_shards)
    if n_pop_shards is None:
        n_pop_shards = n_dev // n_genome_shards
    if n_pop_shards < 1 or n_genome_shards < 1 or (
            n_pop_shards * n_genome_shards > n_dev):
        raise ValueError(
            f"requested {n_pop_shards} pop x {n_genome_shards} genome "
            f"shards but only {n_dev} devices are available")
    return population_mesh(n_pop_shards * n_genome_shards,
                           axis_names=("pop", "genome"),
                           shape=(n_pop_shards, n_genome_shards))


def _plan_mesh(mesh) -> Mesh:
    """Accept either a raw :class:`Mesh` or a
    :class:`deap_tpu.parallel.ShardingPlan` (whose mesh is used) — the
    genome axis rides the same plan object the population loops
    consume."""
    return mesh.mesh if hasattr(mesh, "mesh") else mesh


def shard_genomes(genomes: jnp.ndarray, mesh) -> jnp.ndarray:
    """Place a ``[n, L]`` genome matrix with rows over ``pop`` and the
    feature axis over ``genome``. ``mesh`` may be a
    :class:`~deap_tpu.parallel.ShardingPlan`."""
    mesh = _plan_mesh(mesh)
    with span("genome_shard/reshard"):
        return jax.device_put(genomes,
                              NamedSharding(mesh, P("pop", "genome")))


#: collective used per ``combine`` mode — one place, so the profiling
#: span names and the actual collectives can never drift apart.
_COMBINE_COLLECTIVES = {
    "sum": ("psum", jax.lax.psum),
    "mean": ("pmean", jax.lax.pmean),
    "max": ("pmax", jax.lax.pmax),
}


def make_sharded_evaluator(partial_eval: Callable, mesh,
                           combine: str = "sum") -> Callable:
    """Build ``evaluate(genomes [n, L]) -> f32[n]`` that runs
    ``partial_eval`` on each device's genome *slice* and reduces across
    the genome axis.

    :param partial_eval: ``f32/bool[n_local, L_local] -> f32[n_local]``
        computing the local partial fitness (e.g. a partial sum of
        per-gene scores, a partial squared-error).
    :param combine: ``"sum"`` | ``"mean"`` | ``"max"`` — the cross-shard
        reduction (``psum``-family collectives over ICI).

    Both the local compute and the collective run under named spans
    (``genome_shard/partial_eval``, ``genome_shard/psum`` …) so traces
    captured with :func:`deap_tpu.support.profiling.trace` break the
    sharded step down per collective.
    """
    if combine not in _COMBINE_COLLECTIVES:
        raise ValueError(combine)
    mesh = _plan_mesh(mesh)
    cname, collective = _COMBINE_COLLECTIVES[combine]

    def local(genomes):
        with span("genome_shard/partial_eval"):
            part = partial_eval(genomes)
        with span(f"genome_shard/{cname}"):
            return collective(part, "genome")

    mapped = shard_map(local, mesh=mesh,
                       in_specs=P("pop", "genome"), out_specs=P("pop"))
    return jax.jit(mapped)
