"""Population as a struct-of-arrays pytree.

The reference represents a population as a Python list of individual
objects created by ``creator.create`` (/root/reference/deap/creator.py:96-171)
each carrying a ``fitness`` attribute; variation operators mutate
individuals in place and *delete* their fitness to mark them for
re-evaluation (/root/reference/deap/algorithms.py:75-80). Here the whole
population is one pytree of device tensors:

- ``genomes``: any pytree of arrays with a shared leading population axis
  (a single ``[n, L]`` array for bitstring/real/permutation genomes, a
  full parameter pytree for neuroevolution, node/const arrays for GP).
- ``fitness``: ``f32[n, nobj]`` raw objective values.
- ``valid``: ``bool[n]`` — the functional encoding of "fitness was
  deleted"; algorithms re-evaluate exactly the invalid rows, preserving
  the reference's who-gets-re-evaluated semantics (SURVEY.md §7.3).
- ``extras``: per-individual auxiliary arrays (ES ``strategy`` vectors —
  cf. mutation.py:180; PSO ``speed``/``best``; lineage ids).
- ``spec``: static :class:`FitnessSpec` (the weights tuple).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from deap_tpu.core.fitness import FitnessSpec, lex_best_index, lex_sort_desc


@struct.dataclass
class Population:
    genomes: Any
    fitness: jnp.ndarray
    valid: jnp.ndarray
    extras: Dict[str, Any] = struct.field(default_factory=dict)
    spec: FitnessSpec = struct.field(pytree_node=False, default=FitnessSpec((1.0,)))

    @property
    def size(self) -> int:
        return self.fitness.shape[0]

    @property
    def nobj(self) -> int:
        return self.fitness.shape[-1]

    @property
    def wvalues(self) -> jnp.ndarray:
        """Weighted values, the comparison currency (base.py:187-198).

        Invalid rows are forced to -inf in every objective so they sort
        last and never dominate.
        """
        w = self.fitness * self.spec.warray
        return jnp.where(self.valid[:, None], w, -jnp.inf)

    def with_fitness(self, values: jnp.ndarray, mask: jnp.ndarray | None = None) -> "Population":
        """Assign raw objective values; ``mask`` limits which rows update.

        Rows updated become valid (the analog of ``ind.fitness.values =
        fit``, base.py:187-198).
        """
        values = jnp.asarray(values, dtype=self.fitness.dtype)
        if values.ndim == 1:
            values = values[:, None]
        if mask is None:
            return self.replace(fitness=values, valid=jnp.ones_like(self.valid))
        fit = jnp.where(mask[:, None], values, self.fitness)
        return self.replace(fitness=fit, valid=self.valid | mask)

    def invalidate(self, mask: jnp.ndarray) -> "Population":
        """Mark rows for re-evaluation (the analog of ``del ind.fitness.values``)."""
        return self.replace(valid=self.valid & ~mask)

    def best_index(self) -> jnp.ndarray:
        return lex_best_index(self.fitness * self.spec.warray, self.valid)

    def sorted_desc(self) -> "Population":
        """Population sorted best-first by lexicographic weighted fitness."""
        return gather(self, lex_sort_desc(self.wvalues))


def init_population(
    key: jax.Array,
    n: int,
    init_genome: Callable[[jax.Array], Any],
    spec: FitnessSpec,
    extras_init: Dict[str, Callable[[jax.Array], Any]] | None = None,
) -> Population:
    """Build an n-individual population by vmapping a per-genome initialiser.

    Counterpart of ``tools.initRepeat(list, toolbox.individual, n)``
    (/root/reference/deap/tools/init.py:3-25) — but the initialiser runs
    batched on device with an explicit split key per individual.
    """
    keys = jax.random.split(key, n + 1)
    genomes = jax.vmap(init_genome)(keys[:n])
    extras = {}
    if extras_init:
        for name, fn in extras_init.items():
            ek = jax.random.split(keys[n], n)
            extras[name] = jax.vmap(fn)(ek)
    return Population(
        genomes=genomes,
        fitness=jnp.zeros((n, spec.nobj), dtype=jnp.float32),
        valid=jnp.zeros((n,), dtype=bool),
        extras=extras,
        spec=spec,
    )


def gather(pop: Population, idx: jnp.ndarray) -> Population:
    """Select individuals by index — the functional ``toolbox.clone``.

    The reference's selection returns references and ``varAnd`` deepcopies
    them (algorithms.py:68); a gather is both at once, with no aliasing
    possible.
    """
    take = lambda a: jnp.take(a, idx, axis=0)
    return pop.replace(
        genomes=jax.tree_util.tree_map(take, pop.genomes),
        fitness=take(pop.fitness),
        valid=take(pop.valid),
        extras=jax.tree_util.tree_map(take, pop.extras),
    )


def concat(pops: Sequence[Population]) -> Population:
    """Concatenate populations along the individual axis (e.g. mu+lambda)."""
    cat = lambda *xs: jnp.concatenate(xs, axis=0)
    first = pops[0]
    return first.replace(
        genomes=jax.tree_util.tree_map(cat, *[p.genomes for p in pops]),
        fitness=cat(*[p.fitness for p in pops]),
        valid=cat(*[p.valid for p in pops]),
        extras=jax.tree_util.tree_map(cat, *[p.extras for p in pops]),
    )
