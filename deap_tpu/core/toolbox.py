"""Toolbox — the alias registry that is the framework's plugin boundary.

Re-creation (not a copy) of the reference's ``base.Toolbox``
(/root/reference/deap/base.py:33-122): ``register(alias, fn, *args, **kw)``
stores a partial application under ``toolbox.<alias>`` with the wrapped
function's ``__name__``/``__doc__``; ``unregister`` removes it;
``decorate`` re-wraps the underlying function with decorators while
keeping the bound arguments. The conventional aliases (``evaluate``,
``mate``, ``mutate``, ``select``, ``map``, ``clone``) are the entire
configuration surface of the reference, and replacing ``map`` is its
entire distribution story (SURVEY.md §1) — here the same seam dispatches
between the tensor (JAX) backend and the CPU/list compat backend.

In the tensor backend, registered functions are *pure*: they take a PRNG
key and arrays, return arrays, and are safe to close over inside ``jit``.
A Toolbox is therefore configuration, resolved at trace time — it never
appears inside a compiled program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable


class Toolbox:
    def __init__(self):
        # Defaults mirror the reference (base.py:48-50): clone and map.
        # In the tensor backend clone is a no-op (values are immutable);
        # the compat backend re-registers deepcopy.
        self.register("map", map)
        self.register("clone", lambda x: x)

    def register(self, alias: str, function: Callable, *args: Any, **kwargs: Any) -> None:
        """Bind ``function`` with default args under ``self.<alias>``.

        Later positional/keyword arguments at call time are appended /
        override, exactly like ``functools.partial`` (base.py:81-91).
        """
        pfunc = functools.partial(function, *args, **kwargs)
        pfunc.__name__ = getattr(function, "__name__", alias)
        pfunc.__doc__ = getattr(function, "__doc__", None)
        if hasattr(function, "__dict__") and not isinstance(function, type):
            pfunc.__dict__.update(function.__dict__.copy())
        setattr(self, alias, pfunc)

    def unregister(self, alias: str) -> None:
        """Remove an alias (base.py:93-98) — e.g. to strip unpicklable
        closures before shipping the toolbox to workers."""
        delattr(self, alias)

    def decorate(self, alias: str, *decorators: Callable) -> None:
        """Re-register ``alias`` with its function wrapped by ``decorators``
        (applied in order), preserving bound default arguments
        (base.py:100-122). Used for staticLimit, penalty wrappers,
        History tracking, benchmark transforms.
        """
        pfunc = getattr(self, alias)
        function, args, kwargs = pfunc.func, pfunc.args, pfunc.keywords
        for decorator in decorators:
            function = decorator(function)
        self.register(alias, function, *args, **kwargs)
