from deap_tpu.core.fitness import (
    FitnessSpec,
    dominates,
    lex_gt,
    lex_ge,
    lex_sort_desc,
    wvalues,
)
from deap_tpu.core.population import Population, gather, concat
from deap_tpu.core.toolbox import Toolbox

__all__ = [
    "FitnessSpec",
    "Population",
    "Toolbox",
    "dominates",
    "lex_gt",
    "lex_ge",
    "lex_sort_desc",
    "wvalues",
    "gather",
    "concat",
]
