"""Fitness semantics as pure array functions.

TPU-native counterpart of the reference's ``base.Fitness``
(/root/reference/deap/base.py:125-270). The reference stores
``wvalues = values * weights`` at assignment time and implements all
comparisons (lexicographic rich-compare at base.py:234-250, Pareto
``dominates`` at base.py:209-224, ``valid`` at base.py:226-229) on the
weighted values, so minimisation/maximisation is uniform "bigger is
better". Here fitness is a ``f32[n, nobj]`` tensor of *raw* objective
values plus a static weights tuple; all comparison helpers take weighted
values and are batched array ops usable inside ``jit``/``vmap``.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class FitnessSpec:
    """Static description of a fitness: objective weights.

    Negative weight = minimise, positive = maximise, exactly like the
    reference's class-level ``weights`` tuple (base.py:148-161). The
    spec is hashable so it can be a static argument to jit'd functions.
    """

    weights: Tuple[float, ...]

    def __init__(self, weights: Sequence[float]):
        object.__setattr__(self, "weights", tuple(float(w) for w in weights))

    @property
    def nobj(self) -> int:
        return len(self.weights)

    @property
    def warray(self) -> jnp.ndarray:
        return jnp.asarray(self.weights, dtype=jnp.float32)

    def wvalues(self, values: jnp.ndarray) -> jnp.ndarray:
        """Weighted values: ``values * weights`` (base.py:187-198)."""
        return jnp.asarray(values, dtype=jnp.float32) * self.warray


# Module-level helpers operate on *weighted* values (maximisation convention).

def wvalues(values: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    return values * weights


def dominates(wa: jnp.ndarray, wb: jnp.ndarray) -> jnp.ndarray:
    """Pareto dominance of weighted values ``wa`` over ``wb``.

    ``a`` dominates ``b`` iff a is no worse in every objective and
    strictly better in at least one (base.py:209-224). Broadcasts over
    leading axes: ``dominates(w[:, None], w[None, :])`` yields the full
    pairwise [n, n] dominance matrix in one fused op — the TPU-friendly
    formulation of the reference's per-pair Python loop.
    """
    return jnp.all(wa >= wb, axis=-1) & jnp.any(wa > wb, axis=-1)


def lex_gt(wa: jnp.ndarray, wb: jnp.ndarray) -> jnp.ndarray:
    """Lexicographic tuple compare ``wa > wb`` (base.py:234-250).

    The reference compares wvalues tuples with Python's ``>``; this is
    the broadcasting array equivalent: the first differing objective
    decides.
    """
    neq = wa != wb
    first = jnp.argmax(neq, axis=-1)
    a = jnp.take_along_axis(wa, first[..., None], axis=-1)[..., 0]
    b = jnp.take_along_axis(wb, first[..., None], axis=-1)[..., 0]
    return jnp.any(neq, axis=-1) & (a > b)


def lex_ge(wa: jnp.ndarray, wb: jnp.ndarray) -> jnp.ndarray:
    return ~lex_gt(wb, wa)


def lex_sort_desc(w: jnp.ndarray) -> jnp.ndarray:
    """Indices sorting rows of ``w`` lexicographically descending.

    Matches Python's ``sorted(..., key=attrgetter("fitness"), reverse=True)``
    over Fitness objects (e.g. HallOfFame insertion order,
    support.py:517-543): objective 0 is the primary key. Stable.
    """
    # jnp.lexsort treats the *last* key as primary and sorts ascending,
    # so feed negated columns in reverse objective order.
    keys = tuple(-w[..., j] for j in range(w.shape[-1] - 1, -1, -1))
    return jnp.lexsort(keys)


def lex_best_index(w: jnp.ndarray, valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """Index of the lexicographically-largest row (single best individual)."""
    if valid is not None:
        w = jnp.where(valid[..., None], w, -jnp.inf)
    return lex_sort_desc(w)[..., 0]
