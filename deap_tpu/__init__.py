"""deap_tpu — a TPU-native evolutionary-computation framework.

A from-scratch JAX/XLA framework with the capabilities of DEAP
(reference: /root/reference): genetic algorithms over tensor populations,
genetic programming via a batched prefix-tree interpreter, evolution
strategies (CMA-ES and friends), multi-objective selection (NSGA-II/III,
SPEA2), island-model and multi-host distribution over device meshes, and
DEAP-style support tooling (toolbox registry, statistics/logbook,
hall-of-fame/Pareto archives, checkpointing, benchmark suite, and a
run-journal telemetry subsystem — in-scan metrics, JSONL host events
with retrace tracking, span wall-time aggregation; see
`deap_tpu.telemetry`).

Design stance (see SURVEY.md §7): populations are struct-of-arrays pytrees,
operators are pure functions `(key, ...) -> ...`, algorithms are `lax.scan`
loops compiled as a single XLA program per generation, and distribution is
`shard_map`/`pjit` over a `jax.sharding.Mesh` — not per-individual Python
dispatch.
"""

__version__ = "0.1.0"

from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import Population
from deap_tpu.core.toolbox import Toolbox

__all__ = ["FitnessSpec", "Population", "Toolbox", "__version__"]
