"""Algorithms — the reference's generational loops as compiled scan steps.

Counterpart of /root/reference/deap/algorithms.py (varAnd :33-82, eaSimple
:85-189, varOr :192-245, eaMuPlusLambda :248-337, eaMuCommaLambda
:340-437, eaGenerateUpdate :440-503). Where the reference runs serial
Python per generation with ``toolbox.map`` as the only parallel seam
(SURVEY.md §3.1), each loop here is one jit-compiled ``lax.scan`` whose
step does selection → variation → masked re-evaluation → archive/stats
entirely on device. The toolbox alias convention is preserved:

- ``toolbox.evaluate``: ``genomes -> values [n] | [n, nobj]`` (batched)
- ``toolbox.mate``:     ``(key, g1, g2) -> (c1, c2)`` per pair
- ``toolbox.mutate``:   ``(key, g) -> g`` per genome
- ``toolbox.select``:   ``(key, wvalues, k) -> int32[k]``

The reference's "delete fitness on variation, re-evaluate only invalid"
protocol (algorithms.py:75-80) is encoded as the population's ``valid``
mask: every row is recomputed by the batched evaluate but only invalid
rows are *written*, so stochastic evaluators keep the reference's
semantics and ``nevals`` counts exactly the reference's evaluations.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import tuning
from deap_tpu.core.population import Population, concat, gather
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.ops import variation as _variation
from deap_tpu.support.hof import HallOfFame, hof_init, hof_update
from deap_tpu.support.logbook import Logbook, logbook_from_records
from deap_tpu.support.stats import Statistics


def _check_cx_mut(cxpb, mutpb) -> None:
    """The reference's ``cxpb + mutpb <= 1.0`` guard, skipped when the
    probabilities are traced values (the multi-run engine vmaps the
    step factories with *per-run* cxpb/mutpb arrays — see
    :mod:`deap_tpu.serving.multirun`; callers there validate on the
    host before packing)."""
    if isinstance(cxpb, jax.core.Tracer) or isinstance(mutpb, jax.core.Tracer):
        return
    assert float(cxpb) + float(mutpb) <= 1.0, (
        "The sum of the crossover and mutation probabilities must be "
        "smaller or equal to 1.0.")


def _tree_where(mask: jnp.ndarray, a: Any, b: Any) -> Any:
    def w(x, y):
        m = mask.reshape(mask.shape + (1,) * (x.ndim - mask.ndim))
        return jnp.where(m, x, y)
    return jax.tree_util.tree_map(w, a, b)


def _as2d(values: jnp.ndarray) -> jnp.ndarray:
    return values[:, None] if values.ndim == 1 else values


def evaluate_invalid(pop: Population, evaluate: Callable) -> Population:
    """Batch-evaluate and write back only the invalid rows
    (the tensor form of ``toolbox.map(toolbox.evaluate, invalid)``,
    algorithms.py:149-152)."""
    values = _as2d(evaluate(pop.genomes))
    return pop.with_fitness(values, mask=~pop.valid)


# ------------------------------------------------- fused variation plane ----
#
# var_and / var_or accept a ``fused`` mode: when the toolbox's (mate,
# mutate) pair is fused-capable (ops.variation.resolve_plan) and the
# genomes are one [n, L] array, the variation plane runs as a single
# pass — masks drawn with the unfused operators' exact RNG tree, then
# one fused apply (the XLA formulation off-TPU, the Pallas
# ops.kernels.fused_variation kernel on TPU). Results are BIT-IDENTICAL
# to the unfused composition either way (tests/test_fused_variation.py
# pins populations/logbooks across all four loops), so 'auto' is the
# default everywhere. The dispatch decision is journaled as a
# ``variation_dispatch`` event (visible in bench_report.py --journal /
# --health), mirroring the GP interpreter's gp_dispatch events.

#: dtypes the Pallas kernel's f32 workspace represents exactly for
#: every mut kind (bool / f32 genomes; everything else takes the
#: equally-bit-exact fused XLA path)
_KERNEL_EXACT_DTYPES = (jnp.bool_, jnp.float32)


def _journal_dispatch(**payload) -> None:
    from deap_tpu.telemetry.journal import broadcast
    broadcast("variation_dispatch", **payload)


def _resolve_fused(fused, toolbox, genomes, op: str, probe_fns=None):
    """Resolve a ``fused=`` request to ``(mode, plan)`` where mode is
    ``None`` (unfused), ``'xla'`` or ``'kernel'``; journals the
    decision. ``'auto'`` silently falls back when the configuration is
    not fused-capable; an explicit ``'xla'``/``'kernel'`` raises
    instead of silently computing something slower than asked for.

    ``'auto'`` additionally routes through the dispatch tuner
    (:func:`deap_tpu.tuning.resolve`, knob ``fused``, candidates
    ``unfused``/``fused_xla``/``fused_kernel``): ``probe_fns`` — a
    zero-arg builder of candidate probe closures, supplied by
    var_and/var_or when the inputs are concrete — lets the tuner race
    the real variation pass and persist the winner; without a tuner
    the static pick below is today's behaviour unchanged."""
    if fused in (False, None, "off"):
        _journal_dispatch(op=op, path="unfused", reason="disabled")
        return None, None
    if fused is True:
        fused = "auto"
    if fused not in ("auto", "xla", "kernel"):
        raise ValueError(f"unknown fused mode {fused!r}")
    plan = _variation.resolve_plan(toolbox)
    leaf = _variation.single_genome_leaf(genomes)
    reason = None
    if plan is None:
        reason = "operators not fused-capable"
    elif leaf is None:
        reason = "genome pytree is not a single [n, L] array"
    if reason is not None:
        if fused != "auto":
            raise ValueError(f"fused={fused!r} requested but {reason}")
        _journal_dispatch(op=op, path="unfused", reason=reason)
        return None, None
    mode, reason = fused, "requested"
    if fused == "auto":
        if jax.default_backend() == "tpu":
            mode, reason = "kernel", "tpu backend"
        else:
            # the Pallas interpreter would be far slower than XLA: the
            # off-TPU fused path is the XLA formulation, not the
            # kernel run under interpret mode
            mode = "xla"
            reason = (f"{jax.default_backend()} backend "
                      "(interpret-mode kernel fallback declined)")
        if mode == "kernel" and leaf.dtype not in _KERNEL_EXACT_DTYPES:
            mode = "xla"
            reason = f"dtype {leaf.dtype} outside the kernel's exact set"
        names = ["unfused", "fused_xla"] + (
            ["fused_kernel"] if mode == "kernel" else [])
        candidates = dict.fromkeys(names)
        if probe_fns is not None:
            built = probe_fns()
            candidates = {name: built.get(name) for name in names}
        n, L = leaf.shape
        choice = tuning.resolve(
            "fused",
            bucket=(op, tuning.shape_bucket(n), tuning.shape_bucket(L),
                    str(leaf.dtype)),
            default=f"fused_{mode}", candidates=candidates,
            check="bitwise", program=op)
        if choice == "unfused":
            _journal_dispatch(op=op, path="unfused", reason="tuned")
            return None, None
        if choice != f"fused_{mode}":
            mode, reason = choice[len("fused_"):], "tuned"
    if mode == "kernel" and leaf.dtype not in _KERNEL_EXACT_DTYPES:
        if fused == "kernel":
            raise ValueError(
                f"fused='kernel' requested but genome dtype "
                f"{leaf.dtype} is outside the kernel's exact-f32 set")
        mode = "xla"
        reason = f"dtype {leaf.dtype} outside the kernel's exact set"
    _journal_dispatch(op=op, path=f"fused_{mode}", reason=reason,
                      mate=plan.mate_name, mutate=plan.mut_name,
                      mut_kind=plan.mut_kind)
    return mode, plan


def _variation_probe_fns(fused, key, pop, run):
    """Candidate probe-closure builder for the tuner's ``fused`` knob:
    each candidate re-runs the whole variation pass with that path
    forced (``run(f)`` recurses into var_and/var_or with an explicit
    ``fused=f``, which bypasses the tuner — no recursion). Returns
    None when probing is impossible: explicit ``fused=``, no tuner,
    or traced inputs."""
    if fused not in ("auto", True) or tuning.active_tuner() is None \
            or not tuning.is_concrete(key, pop):
        return None

    def path(f):
        return lambda: jax.tree_util.tree_leaves(run(f))

    return lambda: {"unfused": path(False), "fused_xla": path("xla"),
                    "fused_kernel": path("kernel")}


def _apply_fused(mode: str, g, src, partner, cx_row, lo, hi, mut_row,
                 mask, arg, mut_kind: str):
    if mode == "kernel":
        from deap_tpu.ops.kernels import fused_variation
        if src is None:
            src = jnp.arange(cx_row.shape[0], dtype=jnp.int32)
        return fused_variation(g, src, partner, cx_row, lo, hi,
                               mut_row, mask, arg, mut_kind=mut_kind,
                               interpret=False)
    return _variation.apply_variation(g, src, partner, cx_row, lo, hi,
                                      mut_row, mask, arg, mut_kind)


def _rebuild_genomes(template, children):
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, [children])


def var_and(key: jax.Array, pop: Population, toolbox, cxpb: float,
            mutpb: float, fused="auto",
            sel_idx: Optional[jnp.ndarray] = None) -> Population:
    """Crossover AND mutation variation (algorithms.py:33-82).

    Adjacent pairs (0,1), (2,3), ... mate with probability ``cxpb``; each
    individual then mutates with probability ``mutpb``; every touched row
    is invalidated. An odd last individual never mates, like the
    reference's pairwise zip.

    ``fused`` selects the variation-plane execution (see the module
    note above): ``'auto'`` (default — fused when the configuration
    supports it, bit-identical either way), ``'xla'`` / ``'kernel'``
    (explicit, raising when unsupported), or ``False`` (the original
    composition). ``sel_idx`` composes a selection gather into the
    plane: ``var_and(k, pop, tb, ..., sel_idx=idx)`` ==
    ``var_and(k, gather(pop, idx), tb, ...)`` with the parent gather
    fused into the variation pass instead of materialised.
    """
    probe = _variation_probe_fns(
        fused, key, pop,
        lambda f: var_and(key, pop, toolbox, cxpb, mutpb, fused=f,
                          sel_idx=sel_idx))
    mode, plan = _resolve_fused(fused, toolbox, pop.genomes, "var_and",
                                probe_fns=probe)
    if mode is None:
        if sel_idx is not None:
            pop = gather(pop, sel_idx)
        return _var_and_unfused(key, pop, toolbox, cxpb, mutpb)

    g = _variation.single_genome_leaf(pop.genomes)
    n = int(sel_idx.shape[0]) if sel_idx is not None else pop.size
    L = g.shape[1]
    cx_row, lo, hi, do_mut, mask, arg = _variation.var_and_masks(
        key, n, L, cxpb, mutpb, plan, g.dtype)
    if sel_idx is None:
        src, base = None, pop
    else:
        # fitness/valid/extras row-select only: the genome-plane gather
        # happens inside the fused apply
        src, base = sel_idx, gather(pop.replace(genomes=()), sel_idx)
    if mode == "kernel":
        # the kernel DMAs partner rows by explicit index; the XLA apply
        # derives the adjacent-pair partner view by reshape instead
        # (partner_idx=None), saving a second full gather
        partner_pos = _variation.pair_partner_positions(n)
        partner = (partner_pos if src is None
                   else jnp.take(src, partner_pos))
    else:
        partner = None
    children = _apply_fused(mode, g, src, partner, cx_row, lo, hi,
                            do_mut, mask, arg, plan.mut_kind)
    genomes = _rebuild_genomes(pop.genomes, children)
    return base.replace(genomes=genomes).invalidate(cx_row | do_mut)


def _var_and_unfused(key: jax.Array, pop: Population, toolbox,
                     cxpb: float, mutpb: float) -> Population:
    """The original compute-both-then-select composition — the parity
    oracle the fused plane is pinned against."""
    n = pop.size
    npairs = n // 2
    k_pair, k_cx, k_ind, k_mut = jax.random.split(key, 4)

    genomes = pop.genomes
    if npairs:
        even = jax.tree_util.tree_map(lambda a: a[0 : 2 * npairs : 2], genomes)
        odd = jax.tree_util.tree_map(lambda a: a[1 : 2 * npairs : 2], genomes)
        cx_keys = jax.random.split(k_cx, npairs)
        c1, c2 = jax.vmap(toolbox.mate)(cx_keys, even, odd)
        do_cx = jax.random.bernoulli(k_pair, cxpb, (npairs,))
        even = _tree_where(do_cx, c1, even)
        odd = _tree_where(do_cx, c2, odd)

        def interleave(e, o, orig):
            # stack+reshape beats two strided scatters (XLA lowers the
            # .at[::2] form to scatter; this is a plain transpose-copy)
            pair = jnp.stack([e, o], axis=1).reshape(
                (2 * npairs,) + e.shape[1:])
            if orig.shape[0] == 2 * npairs:
                return pair.astype(orig.dtype)
            return jnp.concatenate(
                [pair.astype(orig.dtype), orig[2 * npairs:]], axis=0)

        genomes = jax.tree_util.tree_map(interleave, even, odd, genomes)
        cx_touched = jnp.zeros(n, bool).at[: 2 * npairs].set(
            jnp.repeat(do_cx, 2))
    else:
        cx_touched = jnp.zeros(n, bool)

    mut_keys = jax.random.split(k_mut, n)
    mutated = jax.vmap(toolbox.mutate)(mut_keys, genomes)
    do_mut = jax.random.bernoulli(k_ind, mutpb, (n,))
    genomes = _tree_where(do_mut, mutated, genomes)

    touched = cx_touched | do_mut
    return pop.replace(genomes=genomes).invalidate(touched)


def var_or(key: jax.Array, pop: Population, toolbox, lambda_: int,
           cxpb: float, mutpb: float, fused="auto") -> Population:
    """Crossover OR mutation OR reproduction (algorithms.py:192-245).

    Each of the ``lambda_`` children independently: with prob cxpb the
    first child of a mating of two distinct random parents; elif with
    prob mutpb a mutant of a random parent; else an unchanged copy that
    *keeps* its parent's (valid) fitness, exactly like the reference.

    ``fused`` as in :func:`var_and`: the fused plane composes the
    per-child parent gathers (``i``/``j``/``m`` draws) into its
    one-pass apply — bit-identical to this composition.
    """
    _check_cx_mut(cxpb, mutpb)
    probe = _variation_probe_fns(
        fused, key, pop,
        lambda f: var_or(key, pop, toolbox, lambda_, cxpb, mutpb,
                         fused=f))
    mode, plan = _resolve_fused(fused, toolbox, pop.genomes, "var_or",
                                probe_fns=probe)
    if mode is not None:
        g = _variation.single_genome_leaf(pop.genomes)
        base_idx, j, choice_cx, lo, hi, choice_mut, mask, arg = (
            _variation.var_or_masks(key, pop.size, lambda_, g.shape[1],
                                    cxpb, mutpb, plan, g.dtype))
        children_g = _apply_fused(mode, g, base_idx, j, choice_cx, lo,
                                  hi, choice_mut, mask, arg,
                                  plan.mut_kind)
        base = gather(pop.replace(genomes=()), base_idx)
        genomes = _rebuild_genomes(pop.genomes, children_g)
        return base.replace(genomes=genomes).invalidate(
            choice_cx | choice_mut)
    n = pop.size
    k_u, k_p1, k_p2, k_pm, k_cx, k_mut = jax.random.split(key, 6)
    u = jax.random.uniform(k_u, (lambda_,))
    choice_cx = u < cxpb
    choice_mut = (u >= cxpb) & (u < cxpb + mutpb)

    # distinct parent pair per child (random.sample(population, 2))
    i = jax.random.randint(k_p1, (lambda_,), 0, n)
    j = jax.random.randint(k_p2, (lambda_,), 0, n - 1)
    j = jnp.where(j >= i, j + 1, j)
    m = jax.random.randint(k_pm, (lambda_,), 0, n)

    base_idx = jnp.where(choice_cx, i, m)
    children = gather(pop, base_idx)

    ga = lambda idx: jax.tree_util.tree_map(
        lambda a: jnp.take(a, idx, axis=0), pop.genomes)
    cx_keys = jax.random.split(k_cx, lambda_)
    c1, _ = jax.vmap(toolbox.mate)(cx_keys, ga(i), ga(j))
    mut_keys = jax.random.split(k_mut, lambda_)
    mutants = jax.vmap(toolbox.mutate)(mut_keys, ga(m))

    genomes = _tree_where(choice_cx, c1, children.genomes)
    genomes = _tree_where(choice_mut, mutants, genomes)
    return children.replace(genomes=genomes).invalidate(choice_cx | choice_mut)


# ------------------------------------------------------------------ loops ----

def _maybe_stats(stats: Optional[Statistics], pop: Population):
    return stats.compile(pop) if stats is not None else {}


# ------------------------------------------------------------- telemetry ----
#
# Every loop takes an optional ``telemetry`` (a RunTelemetry): when set,
# a Meter state dict joins the scan carry and per-generation snapshots
# ride the scan's stacked output — zero host round trips; the journal
# gets header/run_start/meter/run_end events on the host side. When
# None, the scan carry, xs and step body are *exactly* the untouched
# originals, and with telemetry enabled the computed results are
# bit-identical anyway (meter updates consume no RNG and feed nothing
# back — pinned by tests/test_telemetry.py).

def _tel_declare(meter) -> None:
    """The built-in metric set every population loop maintains."""
    meter.counter("nevals")
    meter.gauge("best")
    meter.gauge("mean")
    meter.gauge("evaluated_frac")


def _tel_measure(tel, mstate, nevals: jnp.ndarray, pop: Population,
                 gen: jnp.ndarray, sel_idx=None, sel_pool=None,
                 parent_idx=None):
    """In-scan built-in instrumentation + probes + live stream.

    ``sel_idx``/``sel_pool``/``parent_idx`` hand the probes the
    selection indices the loop already holds (selection-pressure and
    lineage probes read them — see telemetry/probes.py); the pool size
    is a static int so bincounts stay shape-static."""
    m = tel.meter
    w0 = pop.wvalues[:, 0]
    mstate = m.inc(mstate, "nevals", nevals)
    mstate = m.set(mstate, "best", jnp.max(w0))
    mstate = m.set(mstate, "mean", jnp.mean(w0))
    mstate = m.set(mstate, "evaluated_frac",
                   nevals.astype(jnp.float32) / pop.size)
    mstate = tel.apply_probe(mstate, pop=pop, gen=gen, sel_idx=sel_idx,
                             sel_pool=sel_pool, parent_idx=parent_idx)
    tel.live(mstate, gen)
    return mstate


def _check_probes(probes, telemetry):
    if probes and telemetry is None:
        raise ValueError(
            "probes= requires telemetry= (a RunTelemetry): probe state "
            "rides the telemetry Meter carry")


def _pop_loop_init(pop: Population, toolbox, halloffame_size: int,
                   stats: Optional[Statistics]):
    """The shared gen-0 protocol of the three population loops:
    evaluate the invalid founders, seed the hall of fame, build the
    gen-0 logbook record. Returns ``(pop, hof, record0)`` — also the
    entry point the segmented :mod:`deap_tpu.resilience` driver uses,
    so its gen 0 can never drift from the monolithic loops'."""
    nevals0 = jnp.sum(~pop.valid)  # like the reference's len(invalid_ind)
    pop = evaluate_invalid(pop, toolbox.evaluate)
    hof = hof_init(halloffame_size, pop) if halloffame_size else None
    if hof is not None:
        hof = hof_update(hof, pop)
    record0 = {"nevals": nevals0, **_maybe_stats(stats, pop)}
    return pop, hof, record0


# The make_*_step factories build the per-generation scan step of each
# loop family. The loop functions below scan them over all ngen
# generations in one compiled program; the resilience engine
# (deap_tpu/resilience/engine.py) scans the SAME step over key slices,
# which is what makes segmented-with-checkpoints runs bit-identical to
# monolithic ones. Carry layout: (pop, hof) — or (pop, hof, mstate)
# with telemetry, in which case xs is (key, gen) instead of key.
#
# Run axis: every factory also accepts TRACED cxpb/mutpb (a vmap lane's
# per-run scalar) — probabilities only feed bernoulli/uniform
# comparisons, never shapes, so the multi-run serving engine
# (deap_tpu/serving/multirun.py) can vmap one step over N independent
# runs with per-run hyperparameters and stay bit-identical per lane.
#
# Mesh axis: every factory also accepts a ``plan``
# (:class:`deap_tpu.parallel.ShardingPlan`): the step pins the
# outgoing population to the plan's layout (``with_sharding_constraint``
# on the ``pop`` mesh axis) so the XLA partitioner keeps the population
# sharded across generation boundaries instead of replicating it after
# the selection gather. Sharding is layout, not semantics — a
# plan-compiled loop computes bit-identical results on ANY mesh size
# (tests/test_sharding_plan.py), which is what makes elastic resume
# possible.


def _retain(plan, tree):
    """A safe-to-read-later copy of a pytree that is about to enter a
    donated carry (the gen-0 meter state feeds both the scan carry and
    the post-scan journal assembly): donation deletes the original's
    buffers, the copy survives. Free when nothing is donated."""
    if plan is None or not getattr(plan, "donate", False) or tree is None:
        return tree
    return jax.tree_util.tree_map(
        lambda a: jnp.copy(a) if isinstance(a, jax.Array) else a, tree)


def _run_scan(plan, label: str, step, carry, xs):
    """Scan ``step`` over ``xs`` — directly, or (with a plan) through
    the plan's pjit-preferred compile wrapper with the carry DONATED:
    the generation-step buffers alias in place instead of being copied
    (``bench.py --mesh`` measures the donation row). The carry handed
    in here is always internally constructed (``plan.place`` fresh
    copies / hof_init / meter.init), so donation can never delete a
    caller-owned array."""
    if plan is None:
        return lax.scan(step, carry, xs)
    runner = plan.compile(lambda c, x: lax.scan(step, c, x),
                          donate_argnums=(0,), label=label)
    return runner(carry, xs)


def make_ea_simple_step(toolbox, cxpb: float, mutpb: float,
                        stats: Optional[Statistics] = None,
                        telemetry=None, fused="auto",
                        plan=None) -> Callable:
    """The eaSimple generation step: select n → varAnd → evaluate
    invalid → replace (algorithms.py:163-181). ``fused`` (see
    :func:`var_and`) collapses select-gather + crossover + mutation
    into one pass over the genome plane — bit-identical results."""
    tel = telemetry

    def step(carry, xs):
        if tel is None:
            (pop, hof), key = carry, xs
        else:
            (pop, hof, mstate), (key, gen) = carry, xs
        k_sel, k_var = jax.random.split(key)
        idx = toolbox.select(k_sel, pop.wvalues, pop.size)
        off = var_and(k_var, pop, toolbox, cxpb, mutpb, fused=fused,
                      sel_idx=idx)
        nevals = jnp.sum(~off.valid)
        off = evaluate_invalid(off, toolbox.evaluate)
        if plan is not None:
            off = plan.constrain(off)
        if hof is not None:
            new_hof = hof_update(hof, off)
        else:
            new_hof = None
        rec = {"nevals": nevals, **_maybe_stats(stats, off)}
        if tel is None:
            return (off, new_hof), rec
        # ea_simple's selection doubles as parentage: child i descends
        # from pop[idx[i]] (plus its crossover partner) — hand probes
        # both the pressure view (sel_idx) and the lineage view
        mstate = _tel_measure(tel, mstate, nevals, off, gen,
                              sel_idx=idx, sel_pool=pop.size,
                              parent_idx=idx)
        return (off, new_hof, mstate), (rec, mstate)

    return step


def ea_simple(key: jax.Array, pop: Population, toolbox, cxpb: float,
              mutpb: float, ngen: int, stats: Optional[Statistics] = None,
              halloffame_size: int = 0, verbose: bool = False,
              telemetry=None, probes=(), fused="auto", plan=None,
              ) -> Tuple[Population, Logbook, Optional[HallOfFame]]:
    """The canonical generational GA (algorithms.py:85-189).

    select n → varAnd → evaluate invalid → replace, scanned over ``ngen``
    generations as one compiled program. ``telemetry`` (a
    :class:`deap_tpu.telemetry.RunTelemetry`) threads a Meter through
    the scan and journals the run; ``probes`` adds in-scan population
    probes (:mod:`deap_tpu.telemetry.probes`) to that meter. Results
    are unchanged either way. ``fused`` (see :func:`var_and`) picks the
    variation-plane execution — bit-identical results in every mode.
    ``plan`` (a :class:`deap_tpu.parallel.ShardingPlan`) shards the
    population over the plan's mesh and compiles the scan with the
    carry donated — same results bit-exactly, on as many devices as
    the plan holds.
    """
    tel = telemetry
    _check_probes(probes, tel)
    kscan = key
    if plan is not None:
        pop = plan.place(pop)
    pop, hof, record0 = _pop_loop_init(pop, toolbox, halloffame_size,
                                       stats)
    if tel is not None:
        tel.begin_run("ea_simple", toolbox, declare=_tel_declare,
                      probes=probes, ngen=ngen, n=pop.size, cxpb=cxpb,
                      mutpb=mutpb)
        mstate0 = _tel_measure(tel, tel.meter.init(), record0["nevals"],
                               pop, jnp.int32(0))

    step = make_ea_simple_step(toolbox, cxpb, mutpb, stats, tel,
                               fused=fused, plan=plan)

    if tel is None:
        (pop, hof), records = _run_scan(
            plan, "ea_simple", step, (pop, hof),
            jax.random.split(kscan, ngen))
    else:
        initial = _retain(plan, mstate0)
        (pop, hof, _), (records, mrows) = _run_scan(
            plan, "ea_simple", step, (pop, hof, mstate0),
            (jax.random.split(kscan, ngen), jnp.arange(1, ngen + 1)))
        tel.end_run("ea_simple", stacked_meter=mrows, initial=initial,
                    ngen=ngen)
    logbook = _build_logbook(record0, records, stats)
    if verbose:
        print(logbook.stream)
    return pop, logbook, hof


def _build_logbook(record0, records, stats) -> Logbook:
    fields = ["gen", "nevals"]
    if stats is not None:
        fields += list(stats.fields)
    logbook = Logbook()
    logbook.header = fields
    logbook.record(gen=0, **record0)
    body = logbook_from_records(records)
    merged = []
    for gen in range(len(body)):
        entry = dict(body[gen])
        for name, chapter in body.chapters.items():
            entry[name] = dict(chapter[gen])
        merged.append(entry)
    for gen, entry in enumerate(merged, start=1):
        logbook.record(gen=gen, **entry)
    return logbook


def make_ea_mu_plus_lambda_step(toolbox, mu: int, lambda_: int,
                                cxpb: float, mutpb: float,
                                stats: Optional[Statistics] = None,
                                telemetry=None, fused="auto",
                                plan=None) -> Callable:
    """The (μ + λ) generation step: varOr → evaluate invalid → select μ
    from the parent+offspring union (algorithms.py:248-337)."""
    tel = telemetry

    def step(carry, xs):
        if tel is None:
            (pop, hof), key = carry, xs
        else:
            (pop, hof, mstate), (key, gen) = carry, xs
        k_var, k_sel = jax.random.split(key)
        off = var_or(k_var, pop, toolbox, lambda_, cxpb, mutpb,
                     fused=fused)
        nevals = jnp.sum(~off.valid)
        off = evaluate_invalid(off, toolbox.evaluate)
        pool = concat([pop, off])
        idx = toolbox.select(k_sel, pool.wvalues, mu)
        new_pop = gather(pool, idx)
        if plan is not None:
            new_pop = plan.constrain(new_pop)
        new_hof = hof_update(hof, off) if hof is not None else None
        rec = {"nevals": nevals, **_maybe_stats(stats, new_pop)}
        if tel is None:
            return (new_pop, new_hof), rec
        # environmental selection over the (mu + lambda) union: probes
        # see which pool rows survived, not parentage (varOr's parents
        # are internal draws)
        mstate = _tel_measure(tel, mstate, nevals, new_pop, gen,
                              sel_idx=idx, sel_pool=pool.size)
        return (new_pop, new_hof, mstate), (rec, mstate)

    return step


def ea_mu_plus_lambda(key: jax.Array, pop: Population, toolbox, mu: int,
                      lambda_: int, cxpb: float, mutpb: float, ngen: int,
                      stats: Optional[Statistics] = None,
                      halloffame_size: int = 0, verbose: bool = False,
                      telemetry=None, probes=(), fused="auto", plan=None,
                      ) -> Tuple[Population, Logbook, Optional[HallOfFame]]:
    """(μ + λ) evolution (algorithms.py:248-337): parents survive into the
    selection pool."""
    _check_cx_mut(cxpb, mutpb)
    tel = telemetry
    _check_probes(probes, tel)
    kscan = key
    if plan is not None:
        pop = plan.place(pop)
    pop, hof, record0 = _pop_loop_init(pop, toolbox, halloffame_size,
                                       stats)
    if tel is not None:
        tel.begin_run("ea_mu_plus_lambda", toolbox, declare=_tel_declare,
                      probes=probes, ngen=ngen, mu=mu, lambda_=lambda_,
                      cxpb=cxpb, mutpb=mutpb)
        mstate0 = _tel_measure(tel, tel.meter.init(), record0["nevals"],
                               pop, jnp.int32(0))

    step = make_ea_mu_plus_lambda_step(toolbox, mu, lambda_, cxpb,
                                       mutpb, stats, tel, fused=fused,
                                       plan=plan)

    if tel is None:
        (pop, hof), records = _run_scan(
            plan, "ea_mu_plus_lambda", step, (pop, hof),
            jax.random.split(kscan, ngen))
    else:
        initial = _retain(plan, mstate0)
        (pop, hof, _), (records, mrows) = _run_scan(
            plan, "ea_mu_plus_lambda", step, (pop, hof, mstate0),
            (jax.random.split(kscan, ngen), jnp.arange(1, ngen + 1)))
        tel.end_run("ea_mu_plus_lambda", stacked_meter=mrows,
                    initial=initial, ngen=ngen)
    logbook = _build_logbook(record0, records, stats)
    if verbose:
        print(logbook.stream)
    return pop, logbook, hof


def make_ea_mu_comma_lambda_step(toolbox, mu: int, lambda_: int,
                                 cxpb: float, mutpb: float,
                                 stats: Optional[Statistics] = None,
                                 telemetry=None, fused="auto",
                                 plan=None) -> Callable:
    """The (μ, λ) generation step: varOr → evaluate invalid → select μ
    from the offspring only (algorithms.py:340-437)."""
    tel = telemetry

    def step(carry, xs):
        if tel is None:
            (pop, hof), key = carry, xs
        else:
            (pop, hof, mstate), (key, gen) = carry, xs
        k_var, k_sel = jax.random.split(key)
        off = var_or(k_var, pop, toolbox, lambda_, cxpb, mutpb,
                     fused=fused)
        nevals = jnp.sum(~off.valid)
        off = evaluate_invalid(off, toolbox.evaluate)
        idx = toolbox.select(k_sel, off.wvalues, mu)
        new_pop = gather(off, idx)
        if plan is not None:
            new_pop = plan.constrain(new_pop)
        new_hof = hof_update(hof, off) if hof is not None else None
        rec = {"nevals": nevals, **_maybe_stats(stats, new_pop)}
        if tel is None:
            return (new_pop, new_hof), rec
        mstate = _tel_measure(tel, mstate, nevals, new_pop, gen,
                              sel_idx=idx, sel_pool=off.size)
        return (new_pop, new_hof, mstate), (rec, mstate)

    return step


def ea_mu_comma_lambda(key: jax.Array, pop: Population, toolbox, mu: int,
                       lambda_: int, cxpb: float, mutpb: float, ngen: int,
                       stats: Optional[Statistics] = None,
                       halloffame_size: int = 0, verbose: bool = False,
                       telemetry=None, probes=(), fused="auto", plan=None,
                       ) -> Tuple[Population, Logbook, Optional[HallOfFame]]:
    """(μ, λ) evolution (algorithms.py:340-437): only offspring survive."""
    assert lambda_ >= mu, "lambda must be greater or equal to mu."
    _check_cx_mut(cxpb, mutpb)
    tel = telemetry
    _check_probes(probes, tel)
    kscan = key
    if plan is not None:
        pop = plan.place(pop)
    pop, hof, record0 = _pop_loop_init(pop, toolbox, halloffame_size,
                                       stats)
    if tel is not None:
        tel.begin_run("ea_mu_comma_lambda", toolbox, declare=_tel_declare,
                      probes=probes, ngen=ngen, mu=mu, lambda_=lambda_,
                      cxpb=cxpb, mutpb=mutpb)
        mstate0 = _tel_measure(tel, tel.meter.init(), record0["nevals"],
                               pop, jnp.int32(0))

    step = make_ea_mu_comma_lambda_step(toolbox, mu, lambda_, cxpb,
                                        mutpb, stats, tel, fused=fused,
                                        plan=plan)

    if tel is None:
        (pop, hof), records = _run_scan(
            plan, "ea_mu_comma_lambda", step, (pop, hof),
            jax.random.split(kscan, ngen))
    else:
        initial = _retain(plan, mstate0)
        (pop, hof, _), (records, mrows) = _run_scan(
            plan, "ea_mu_comma_lambda", step, (pop, hof, mstate0),
            (jax.random.split(kscan, ngen), jnp.arange(1, ngen + 1)))
        tel.end_run("ea_mu_comma_lambda", stacked_meter=mrows,
                    initial=initial, ngen=ngen)
    logbook = _build_logbook(record0, records, stats)
    if verbose:
        print(logbook.stream)
    return pop, logbook, hof


def _generate_update_init(toolbox, state: Any, spec: FitnessSpec,
                          halloffame_size: int):
    """Ask-tell loop setup: infer λ and build the hall of fame from a
    shape template, without running compute. Returns ``(lam, hof)`` —
    shared with the segmented resilience driver."""
    g_shape = jax.eval_shape(toolbox.generate, jax.random.key(0), state)
    lam = jax.tree_util.tree_leaves(g_shape)[0].shape[0]
    v_shape = jax.eval_shape(toolbox.evaluate, g_shape)
    nobj = 1 if len(v_shape.shape) == 1 else v_shape.shape[-1]
    template = Population(
        genomes=jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), g_shape),
        fitness=jnp.zeros((lam, nobj), jnp.float32),
        valid=jnp.zeros(lam, bool),
        spec=spec,
    )
    hof = hof_init(halloffame_size, template) if halloffame_size else None
    return lam, hof


def make_ea_generate_update_step(toolbox, spec: FitnessSpec, lam: int,
                                 stats: Optional[Statistics] = None,
                                 telemetry=None, plan=None) -> Callable:
    """The ask-tell generation step: generate → evaluate → update
    (algorithms.py:440-503); carry ``(state, hof[, mstate])``."""
    tel = telemetry

    def step(carry, xs):
        if tel is None:
            (state, hof), key = carry, xs
        else:
            (state, hof, mstate), (key, gen) = carry, xs
        genomes = toolbox.generate(key, state)
        values = _as2d(toolbox.evaluate(genomes))
        pop = Population(
            genomes=genomes, fitness=values,
            valid=jnp.ones(lam, bool), spec=spec)
        new_state = toolbox.update(state, genomes, values)
        if plan is not None:
            # strategy states are small: the leaf rule replicates them
            # (odd dims) — the pin mostly keeps the partitioner from
            # inventing a layout that churns between generations
            new_state = plan.constrain(new_state)
        new_hof = hof_update(hof, pop) if hof is not None else None
        rec = {"nevals": jnp.asarray(lam), **_maybe_stats(stats, pop)}
        if tel is None:
            return (new_state, new_hof), rec
        m = tel.meter
        w0 = pop.wvalues[:, 0]
        mstate = m.inc(mstate, "nevals", lam)
        mstate = m.set(mstate, "best", jnp.max(w0))
        mstate = m.set(mstate, "mean", jnp.mean(w0))
        mstate = m.set(mstate, "evaluated_frac", 1.0)
        mstate = tel.apply_probe(mstate, pop=pop, state=new_state, gen=gen)
        tel.live(mstate, gen)
        return (new_state, new_hof, mstate), (rec, mstate)

    return step


def _build_gu_logbook(records, stats) -> Logbook:
    """The ask-tell loop's logbook: one row per generation starting at
    gen 0 (no separate founder record)."""
    body = logbook_from_records(records)
    logbook = Logbook()
    logbook.header = ["gen", "nevals"] + (list(stats.fields) if stats else [])
    for gen in range(len(body)):
        entry = dict(body[gen])
        for name, chapter in body.chapters.items():
            entry[name] = dict(chapter[gen])
        logbook.record(gen=gen, **entry)
    return logbook


def ea_generate_update(key: jax.Array, state: Any, toolbox, ngen: int,
                       spec: FitnessSpec,
                       stats: Optional[Statistics] = None,
                       halloffame_size: int = 0, verbose: bool = False,
                       telemetry=None, probes=(), fused="auto", plan=None,
                       ) -> Tuple[Any, Logbook, Optional[HallOfFame]]:
    """Ask-tell loop (algorithms.py:440-503) driving CMA-ES/PBIL/EMNA-style
    strategies:

    - ``toolbox.generate``: ``(key, state) -> genomes``
    - ``toolbox.update``:   ``(state, genomes, values) -> state``

    The whole generate → evaluate → update cycle is one scanned step; the
    strategy state is a pytree in the carry. ``fused`` is accepted for
    signature uniformity with the other three loops but is inert here:
    this loop's variation lives inside the strategy's ``generate``
    (there is no mate/mutate plane to fuse), so every mode computes the
    same program.
    """
    del fused  # no variation plane in the ask-tell loop (see docstring)
    if plan is not None:
        state = plan.place(state)
    lam, hof = _generate_update_init(toolbox, state, spec,
                                     halloffame_size)
    tel = telemetry
    _check_probes(probes, tel)
    if tel is not None:
        tel.begin_run("ea_generate_update", toolbox, declare=_tel_declare,
                      probes=probes, ngen=ngen, lambda_=lam)
        mstate0 = tel.meter.init()

    step = make_ea_generate_update_step(toolbox, spec, lam, stats, tel,
                                        plan=plan)

    if tel is None:
        (state, hof), records = _run_scan(
            plan, "ea_generate_update", step, (state, hof),
            jax.random.split(key, ngen))
    else:
        (state, hof, _), (records, mrows) = _run_scan(
            plan, "ea_generate_update", step, (state, hof, mstate0),
            (jax.random.split(key, ngen), jnp.arange(ngen)))
        tel.end_run("ea_generate_update", stacked_meter=mrows, gen0=0,
                    ngen=ngen)
    logbook = _build_gu_logbook(records, stats)
    if verbose:
        print(logbook.stream)
    return state, logbook, hof


# DEAP-style aliases
varAnd = var_and
varOr = var_or
eaSimple = ea_simple
eaMuPlusLambda = ea_mu_plus_lambda
eaMuCommaLambda = ea_mu_comma_lambda
eaGenerateUpdate = ea_generate_update
