"""RunTelemetry — the façade the algorithm loops accept.

Bundles the three telemetry planes behind one opt-in object:

- a :class:`~deap_tpu.telemetry.meter.Meter` whose state the scanned
  loops thread as auxiliary carry (in-scan metrics, zero host round
  trips),
- a :class:`~deap_tpu.telemetry.journal.RunJournal` receiving host
  events (header, run_start/run_end, compile/retrace, meter rows,
  span aggregates, summary),
- a :class:`~deap_tpu.support.profiling.SpanRecorder` installed for
  the duration of the context, so named spans (the per-collective
  ``genome_shard/*`` instrumentation) aggregate host wall time even
  when no xplane trace can be captured.

Usage::

    from deap_tpu.telemetry import RunTelemetry

    with RunTelemetry("run.jsonl") as tel:
        pop, logbook, hof = algorithms.ea_simple(
            key, pop, toolbox, 0.5, 0.2, ngen=100, telemetry=tel)
    # run.jsonl now holds the header, one meter row per generation,
    # every compile/retrace, span aggregates and a summary.

Enabling telemetry must not change computed results: the meter rides
the scan as extra carry but feeds nothing back into the evolutionary
computation (pinned bit-identical by ``tests/test_telemetry.py``).

A ``probe`` extends the built-in instrumentation with caller metrics;
it is a callable ``probe(meter, mstate, **ctx) -> mstate`` (ctx carries
``pop=``, ``gen=``, the loop's selection indices ``sel_idx=``/
``sel_pool=``/``parent_idx=``, ``journal=`` and, for ask-tell loops,
``state=``), optionally with a ``declare(meter)`` method run before
``meter.init()`` — see :func:`strategy_probe` for the CMA-ES shaped
one and :mod:`deap_tpu.telemetry.probes` for the search-dynamics
library the loops accept via their ``probes=`` argument. A
:class:`~deap_tpu.telemetry.probes.HealthMonitor` passed as
``health=`` turns decoded meter rows into journaled ``alarm`` events.
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Optional

from deap_tpu.support.profiling import SpanRecorder, set_span_recorder
from deap_tpu.telemetry.journal import RunJournal
from deap_tpu.telemetry.meter import Meter

__all__ = ["RunTelemetry", "strategy_probe"]


class RunTelemetry:
    """One run's telemetry configuration + lifecycle.

    :param journal: path to a JSONL file, or an existing
        :class:`RunJournal` (shared journals let several runs — e.g. a
        warmup and a measured run — land in one file, which is also how
        retraces across runs become visible).
    :param meter: a pre-declared :class:`Meter`; default a fresh one
        (algorithm loops declare their built-in metrics on it).
    :param probe: extra in-scan instrumentation (see module docstring).
    :param stream: emit a live per-generation row via
        ``jax.debug.callback`` (stderr tail + ``meter_live`` journal
        events) — for watching long runs; costs a host callback per
        generation, so off by default.
    :param spans: install a :class:`SpanRecorder` while the context is
        active (default True).
    :param fsync_every: when this context owns the journal, its
        durability policy: fsync every n rows, so a killed run loses at
        most n-1 buffered rows (see :class:`RunJournal`).
    :param health: a :class:`~deap_tpu.telemetry.probes.HealthMonitor`;
        every decoded meter row (live-streamed, host-recorded or
        post-scan) runs through its tripwires and each alarm lands in
        the journal as an ``alarm`` event. Host-driven loops also poll
        ``health.stop_requested`` for early stopping.
    """

    def __init__(self, journal, meter: Optional[Meter] = None,
                 probe: Optional[Callable] = None, stream: bool = False,
                 spans: bool = True, init_backend: bool = True,
                 health=None, fsync_every: Optional[int] = None):
        if isinstance(journal, RunJournal):
            self.journal = journal
            self._owns_journal = False
        else:
            self.journal = RunJournal(journal, fsync_every=fsync_every)
            self._owns_journal = True
        self.meter = meter if meter is not None else Meter()
        self.probe = probe
        self.health = health
        self._run_probes: tuple = ()
        self.stream = bool(stream)
        self.recorder: Optional[SpanRecorder] = (
            SpanRecorder() if spans else None)
        self._init_backend = init_backend
        self._prev_recorder: Optional[SpanRecorder] = None
        self._entered = False
        self._header_written = False

    # --------------------------------------------------------- lifecycle ----

    def __enter__(self) -> "RunTelemetry":
        self._entered = True
        if self.recorder is not None:
            self._prev_recorder = set_span_recorder(self.recorder)
        return self

    def __exit__(self, *exc) -> None:
        if self.recorder is not None:
            set_span_recorder(self._prev_recorder)
            self.journal.spans(self.recorder)
        self.journal.summary()
        if self._owns_journal:
            self.journal.close()
        self._entered = False

    # ------------------------------------------------- algorithm helpers ----

    def begin_run(self, algorithm: str, toolbox: Any = None,
                  declare: Optional[Callable] = None, probes=(),
                  **params: Any) -> None:
        """Called by an instrumented loop before ``meter.init()``:
        writes the header (once) and a ``run_start`` event, and runs
        declaration hooks (the loop's built-ins arrive via ``declare``,
        probe declarations via each probe's ``declare`` method).
        ``probes`` — this run's extra probes (the loop's ``probes=``
        argument, see :mod:`deap_tpu.telemetry.probes`)."""
        if not self._header_written:
            self.journal.header(toolbox=toolbox,
                                init_backend=self._init_backend)
            self._header_written = True
        if declare is not None:
            declare(self.meter)
        self.add_probes(probes)
        if self.probe is not None and hasattr(self.probe, "declare"):
            self.probe.declare(self.meter)
        self.journal.event("run_start", algorithm=algorithm, **params)

    def add_probes(self, probes) -> None:
        """Register (and declare) extra probes for subsequent runs —
        ``begin_run`` calls this with the loop's ``probes=`` argument;
        ``make_island_step`` calls it directly (no begin_run there).
        Idempotent per probe instance; must precede ``meter.init()``."""
        for p in tuple(probes or ()):
            if any(p is q for q in self._run_probes):
                continue
            if hasattr(p, "declare"):
                p.declare(self.meter)
            self._run_probes = self._run_probes + (p,)

    def apply_probe(self, mstate, **ctx):
        """In-scan: run the user probe and this run's probes, in
        registration order, after the loop's built-ins."""
        for p in ((self.probe,) if self.probe is not None else ()) \
                + self._run_probes:
            mstate = p(self.meter, mstate, journal=self.journal, **ctx)
        return mstate

    def live(self, mstate, gen) -> None:
        """In-scan: opt-in streaming emitter (no-op unless ``stream``)."""
        if not self.stream:
            return
        self.meter.stream(mstate, gen, self._emit_live)

    def _emit_live(self, gen: int, row: dict) -> None:
        self.journal.event("meter_live", gen=gen, **row)
        self._check_health(row, gen)
        print(f"[deap_tpu] gen {gen}: " + " ".join(
            f"{k}={v}" for k, v in row.items()
            if not isinstance(v, list)), file=sys.stderr)

    def _check_health(self, row: dict, gen) -> None:
        """Run the HealthMonitor tripwires on one decoded row; every
        alarm becomes a journal ``alarm`` event."""
        if self.health is None:
            return
        for alarm in self.health.check_row(row, gen=gen):
            self.journal.event("alarm", **alarm)

    def record_row(self, mstate, gen) -> None:
        """Host-driven loops (the GP engine, island epoch drivers):
        journal one decoded ``meter`` row and run the health tripwires
        on it — the per-generation counterpart of the scanned loops'
        post-scan decode."""
        row = self.meter.row(mstate)
        self.journal.event("meter", gen=gen, **row)
        self._check_health(row, gen)

    def end_run(self, algorithm: str, stacked_meter=None, initial=None,
                gen0: int = 1, **summary: Any) -> None:
        """Called by an instrumented loop after its scan returns: decode
        and journal the per-generation meter rows (running health
        tripwires on each), write ``run_end``, and mark the journal
        steady so later compiles surface as retraces."""
        if stacked_meter is not None:
            self.journal.meter_rows(self.meter, stacked_meter, gen0=gen0,
                                    initial=initial)
            if self.health is not None:
                if initial is not None:
                    self._check_health(self.meter.row(initial), gen0 - 1)
                for i, row in enumerate(self.meter.rows(stacked_meter)):
                    self._check_health(row, gen0 + i)
        self.journal.event("run_end", algorithm=algorithm, **summary)
        self.journal.mark_steady(algorithm)


def strategy_probe(strategy: Any, prefix: str = "") -> Callable:
    """A probe publishing an ask-tell strategy's internal state as
    gauges — CMA-ES σ / condition number, (1+λ) success rate, … — for
    any strategy exposing ``metric_names`` and ``metrics(state)``
    (see ``deap_tpu.strategies.cma``)::

        strat = cma.Strategy(centroid=[0.0] * 10, sigma=0.5)
        with RunTelemetry("cma.jsonl",
                          probe=strategy_probe(strat)) as tel:
            state, logbook, _ = algorithms.ea_generate_update(
                key, strat.initial_state(), toolbox, 50,
                spec=strat.spec, telemetry=tel)
    """
    names = tuple(getattr(strategy, "metric_names", ()))
    if not names:
        raise TypeError(
            f"{type(strategy).__name__} exposes no metric_names; "
            "strategy_probe needs a telemetry-aware strategy")

    class _Probe:
        def declare(self, meter: Meter) -> None:
            for n in names:
                meter.gauge(prefix + n)

        def __call__(self, meter: Meter, mstate, state=None, **_ctx):
            if state is None:
                return mstate
            for k, v in strategy.metrics(state).items():
                mstate = meter.set(mstate, prefix + k, v)
            return mstate

    return _Probe()
