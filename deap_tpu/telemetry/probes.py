"""Search-dynamics probes — jit-safe population analytics on the Meter.

PR 2 built the telemetry *pipes* (the :class:`~deap_tpu.telemetry.meter.
Meter` carry, the JSONL journal); this module is the evolution-specific
*content*: a library of probes that turn a per-generation population
snapshot into diversity / selection-pressure / landscape / front-quality
metrics, entirely on device inside the compiled scan. The reference's
support objects answer these questions on the host between generations
(``Statistics``/``History``/``ParetoFront`` — tools/support.py); here a
whole run is one ``lax.scan``, so anything worth knowing must ride the
scan as data, like evosax/Kozax keep their ES statistics on device
(PAPERS.md).

A probe is a callable ``probe(meter, mstate, **ctx) -> mstate`` with a
``declare(meter)`` hook and a ``metric_names`` tuple naming every
journal-visible metric it maintains (the doc-drift gate in
``tests/test_probe_coverage.py`` keys on it). The context the
instrumented loops provide:

- ``pop`` — the post-step :class:`~deap_tpu.core.population.Population`
  (for island steps, the deme axis flattened away);
- ``gen`` — the generation index (``None`` for stateless island epochs);
- ``sel_idx`` / ``sel_pool`` — the selection index vector the loop just
  used and the (static) size of the pool it indexes into;
- ``parent_idx`` — per-child parent indices into the *previous*
  population, when the loop's selection doubles as parentage
  (``ea_simple``, the GP host loop);
- ``state`` — the strategy state (ask-tell loops);
- ``journal`` — the active RunJournal, for host-side sampled events;
- ``host_clone_rate`` — exact clone rate, when a host-dispatch loop
  already ran the GP interpreter's dedup (see
  :class:`TreeDiversityProbe`).

Probes read population state, consume no RNG, and feed nothing back:
enabling any of them leaves populations/logbooks/hofs bit-identical
(pinned by ``tests/test_probes.py``). Carried quantities (previous
best, stagnation age, lineage depths) live in ordinary Meter gauges, so
they need no new carry plumbing; bulky per-individual carries are
declared ``internal`` and never reach the journal.

The :class:`HealthMonitor` is the host-side layer that turns decoded
meter rows into journaled ``alarm`` events (NaN/Inf fitness, clone-rate
spike, premature convergence, zero-improvement window) with an optional
early-stop signal for host-driven loops.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "PROBE_REGISTRY",
    "register_probe",
    "Probe",
    "DiversityProbe",
    "TreeDiversityProbe",
    "FitnessProbe",
    "SelectionProbe",
    "FrontProbe",
    "HealthMonitor",
    "compose_probes",
    "exact_hypervolume",
]

#: probe-class registry — the doc-drift gate iterates this, so every
#: probe class must register (tests/test_probe_coverage.py fails on a
#: ``*Probe`` class defined here but absent from the registry)
PROBE_REGISTRY: Dict[str, type] = {}


def register_probe(cls: type) -> type:
    PROBE_REGISTRY[cls.__name__] = cls
    return cls


class Probe:
    """Base protocol. ``metric_names`` lists every journal-visible
    metric the probe declares — documentation tooling and the drift
    gate read it, so keep it exact."""

    metric_names: Tuple[str, ...] = ()

    def declare(self, meter) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def __call__(self, meter, mstate, **ctx):  # pragma: no cover
        raise NotImplementedError


# ------------------------------------------------------------ helpers ----

def _strided(n: int, k: int) -> jnp.ndarray:
    """k row indices spread evenly over [0, n) — deterministic, no RNG
    (probes must not touch the loop's key stream)."""
    k = min(int(k), int(n))
    return (jnp.arange(k) * n) // k


def _unique_count(rows: jnp.ndarray) -> jnp.ndarray:
    """Number of distinct rows of an int32 ``[n, d]`` matrix, via a
    64-bit-equivalent double hash (two independent 32-bit multiply-add
    hashes, compared lexicographically after a sort). Collision
    probability ~ n²/2⁶⁴ — negligible against the metric's purpose.
    O(nd + n log n), jit-safe."""
    v = rows.astype(jnp.uint32)
    d = v.shape[1]
    j = jnp.arange(d, dtype=jnp.uint32)
    w1 = j * jnp.uint32(2654435761) + jnp.uint32(0x9E3779B9)
    w2 = (j + jnp.uint32(0x7FEE3F)) * jnp.uint32(2246822519) + jnp.uint32(
        0x85EBCA6B)
    h1 = jnp.sum(v * w1[None, :], axis=1, dtype=jnp.uint32)
    h2 = jnp.sum(v * w2[None, :], axis=1, dtype=jnp.uint32)
    order = jnp.lexsort((h2, h1))
    s1, s2 = h1[order], h2[order]
    fresh = (s1[1:] != s1[:-1]) | (s2[1:] != s2[:-1])
    return jnp.int32(1) + jnp.sum(fresh.astype(jnp.int32))


def _genome_matrix(genomes: Any) -> jnp.ndarray:
    """Flatten any genome pytree to ``f32[n, D]`` (shared leading axis)."""
    leaves = jax.tree_util.tree_leaves(genomes)
    n = leaves[0].shape[0]
    return jnp.concatenate(
        [jnp.reshape(a, (n, -1)).astype(jnp.float32) for a in leaves],
        axis=1)


# ========================================================== diversity ====

@register_probe
class DiversityProbe(Probe):
    """Genotypic diversity of vector genomes (bitstring / real / any
    pytree, flattened).

    Every statistic is computed on a deterministic strided sample of
    ``sample`` rows (no RNG — probes must not touch the loop's key
    stream): the in-scan cost budget is a few percent of a generation
    at pop=100k, which rules out any full-population pass beyond the
    built-ins' reductions. Costs are O(K·d) gather + O(K²) pairwise
    via one Gram matmul.

    - ``div_msd`` — mean pairwise squared distance over the sample's
      ordered pairs, via the centroid identity ``2k/(k-1) · Σ_d var_d``
      (an unbiased estimator of the population quantity).
    - ``div_pdist_mean`` / ``div_pdist_std`` / ``div_pdist_min`` —
      euclidean pairwise-distance moments of the sample block.
    - ``div_unique_frac`` — fraction of genotypically distinct rows in
      the sample (double 32-bit row hash); the complement estimates
      the population clone rate. For an *exact* population clone rate
      use ``full_unique=True`` (adds an O(nd + n log n) pass — ~80 ms
      at pop=100k on one CPU core, far over the in-scan budget there,
      fine for host-driven loops and modest populations).
    """

    metric_names = ("div_msd", "div_pdist_mean", "div_pdist_std",
                    "div_pdist_min", "div_unique_frac")

    def __init__(self, sample: int = 256, full_unique: bool = False):
        self.sample = int(sample)
        self.full_unique = bool(full_unique)

    def declare(self, meter) -> None:
        for name in self.metric_names:
            meter.gauge(name)

    def __call__(self, meter, mstate, pop=None, **_ctx):
        if pop is None:
            return mstate
        leaves = jax.tree_util.tree_leaves(pop.genomes)
        n = leaves[0].shape[0]
        idx = _strided(n, self.sample)
        # gather rows BEFORE flattening to f32 — the flatten itself is
        # an O(nd) copy (~19 ms at pop=100k), over the in-scan budget
        sub = _genome_matrix(jax.tree_util.tree_map(
            lambda a: jnp.take(a, idx, axis=0), pop.genomes))
        k = sub.shape[0]

        mu = jnp.mean(sub, axis=0)
        var_sum = jnp.mean(jnp.sum((sub - mu[None, :]) ** 2, axis=1))
        msd = (2.0 * k / max(k - 1, 1)) * var_sum
        mstate = meter.set(mstate, "div_msd", msd)

        # ||a-b||² = ||a||² + ||b||² − 2a·b — one matmul instead of a
        # materialised [K, K, d] difference tensor
        sqn = jnp.sum(sub * sub, axis=1)
        sq = sqn[:, None] + sqn[None, :] - 2.0 * (sub @ sub.T)
        pd = jnp.sqrt(jnp.maximum(sq, 0.0))
        off = ~jnp.eye(k, dtype=bool)
        npair = max(k * (k - 1), 1)
        pmean = jnp.sum(jnp.where(off, pd, 0.0)) / npair
        pvar = jnp.sum(jnp.where(off, (pd - pmean) ** 2, 0.0)) / npair
        pmin = jnp.min(jnp.where(off, pd, jnp.inf)) if k > 1 else jnp.float32(0)
        mstate = meter.set(mstate, "div_pdist_mean", pmean)
        mstate = meter.set(mstate, "div_pdist_std", jnp.sqrt(pvar))
        mstate = meter.set(mstate, "div_pdist_min",
                           jnp.where(jnp.isfinite(pmin), pmin, 0.0))

        hashed = _genome_matrix(pop.genomes) if self.full_unique else sub
        rows = jax.lax.bitcast_convert_type(hashed, jnp.int32)
        uniq = _unique_count(rows)
        mstate = meter.set(mstate, "div_unique_frac",
                           uniq.astype(jnp.float32) / hashed.shape[0])
        return mstate


@register_probe
class TreeDiversityProbe(Probe):
    """Genotypic diversity of GP tree populations (prefix-linearised
    ``{"nodes", "consts", "length"}`` genomes, gp/tree.py layout).

    - ``gp_opcode_entropy`` — Shannon entropy (nats) of the live-slot
      opcode histogram: the same live-vocab signal the specialized
      interpreter masks on (gp/interpreter.py ``_used_ops``), as a
      convergence scalar. Collapsing entropy means the population is
      abandoning operators.
    - ``gp_clone_rate`` — ``1 − unique/n`` over live prefixes, padding
      normalised out exactly like the interpreter's dedup
      (``_dedup_rows``): in-scan it uses the double row hash; a
      host-dispatch loop that already deduped passes the exact count
      via ``host_clone_rate`` and the probe publishes that instead.
    - ``gp_mean_size`` — mean live prefix length.
    """

    metric_names = ("gp_opcode_entropy", "gp_clone_rate", "gp_mean_size")

    def __init__(self, pset):
        self.n_ops = int(pset.n_ops)

    def declare(self, meter) -> None:
        for name in self.metric_names:
            meter.gauge(name)

    def __call__(self, meter, mstate, pop=None, host_clone_rate=None,
                 **_ctx):
        if pop is None:
            return mstate
        g = pop.genomes
        nodes = jnp.asarray(g["nodes"], jnp.int32)
        consts = jnp.asarray(g["consts"], jnp.float32)
        length = jnp.asarray(g["length"], jnp.int32)
        n, L = nodes.shape
        live = jnp.arange(L)[None, :] < length[:, None]

        is_op = live & (nodes < self.n_ops)
        ids = jnp.where(is_op, nodes, self.n_ops)  # overflow bucket
        hist = jnp.zeros(self.n_ops + 1, jnp.float32).at[ids.ravel()].add(
            is_op.ravel().astype(jnp.float32))[: self.n_ops]
        total = jnp.maximum(jnp.sum(hist), 1.0)
        p = hist / total
        ent = -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))
        mstate = meter.set(mstate, "gp_opcode_entropy", ent)

        if host_clone_rate is not None:
            mstate = meter.set(mstate, "gp_clone_rate", host_clone_rate)
        else:
            nn = jnp.where(live, nodes, -1)
            cc = jax.lax.bitcast_convert_type(
                jnp.where(live, consts, 0.0), jnp.int32)
            uniq = _unique_count(jnp.concatenate([nn, cc], axis=1))
            mstate = meter.set(mstate, "gp_clone_rate",
                               1.0 - uniq.astype(jnp.float32) / n)
        mstate = meter.set(mstate, "gp_mean_size",
                           jnp.mean(length.astype(jnp.float32)))
        return mstate


# ================================================== fitness landscape ====

@register_probe
class FitnessProbe(Probe):
    """Fitness-landscape shape and search progress, from the first
    weighted objective.

    - ``fit_gap`` — best − median: how far the elite sits above the
      bulk (a collapsing gap with low diversity = converged). The
      median is taken over a deterministic strided ``sample`` (a full
      100k-row sort is ~25 ms on one CPU core — over the in-scan
      budget); the best is the exact full-population max.
    - ``fit_velocity`` — best-so-far improvement this generation.
    - ``stagnation_age`` — generations since best-so-far last improved
      by more than ``min_delta``.

    The previous best rides the meter as an ``internal`` gauge — it is
    carry, not a journal metric.
    """

    metric_names = ("fit_gap", "fit_velocity", "stagnation_age")

    def __init__(self, min_delta: float = 0.0, sample: int = 1024):
        self.min_delta = float(min_delta)
        self.sample = int(sample)

    def declare(self, meter) -> None:
        meter.gauge("fit_gap")
        meter.gauge("fit_velocity")
        meter.gauge("stagnation_age", dtype=jnp.int32)
        meter.gauge("fit_prev_best", internal=True)
        meter.gauge("fit_seen", dtype=jnp.int32, internal=True)

    def __call__(self, meter, mstate, pop=None, **_ctx):
        if pop is None:
            return mstate
        w0 = pop.wvalues[:, 0]
        best = jnp.max(w0)
        sub = _strided(w0.shape[0], self.sample)
        med = jnp.nanmedian(jnp.where(pop.valid[sub], w0[sub], jnp.nan))
        prev = mstate["fit_prev_best"]
        seen = mstate["fit_seen"] > 0
        improved = best > prev + self.min_delta
        vel = jnp.where(seen, best - prev, 0.0)
        stag = jnp.where(seen & ~improved,
                         mstate["stagnation_age"] + 1, 0)
        mstate = meter.set(mstate, "fit_gap", best - med)
        mstate = meter.set(mstate, "fit_velocity", vel)
        mstate = meter.set(mstate, "stagnation_age", stag)
        mstate = meter.set(mstate, "fit_prev_best",
                           jnp.where(seen, jnp.maximum(prev, best), best))
        mstate = meter.set(mstate, "fit_seen", 1)
        return mstate


# ================================================ quarantine counting ====

@register_probe
class QuarantineProbe(Probe):
    """Count fitness rows quarantined by
    :func:`deap_tpu.resilience.quarantine_non_finite` — the wrapper
    substitutes a sentinel ``penalty`` for NaN/Inf evaluations, and
    this probe counts sentinel rows in the post-step population so
    the poisoning stays visible in the journal after the substitution
    hid it from ``isfinite``.

    - ``quarantined`` — rows at the sentinel this generation (a spike
      means the evaluator is emitting non-finite fitness *now*).
    - ``quarantined_total`` — cumulative count over the run.

    A nonzero ``quarantined`` row fires the HealthMonitor's existing
    ``non_finite`` alarm (the alarm the sentinel would otherwise
    silence). ``penalty`` must match the wrapper's.
    """

    metric_names = ("quarantined", "quarantined_total")

    def __init__(self, penalty: Optional[float] = None):
        if penalty is None:
            from deap_tpu.resilience.engine import QUARANTINE_PENALTY
            penalty = QUARANTINE_PENALTY
        self.penalty = float(penalty)

    def declare(self, meter) -> None:
        meter.gauge("quarantined", dtype=jnp.int32)
        meter.counter("quarantined_total")

    def __call__(self, meter, mstate, pop=None, **_ctx):
        if pop is None:
            return mstate
        hit = jnp.any(pop.fitness == jnp.float32(self.penalty), axis=-1)
        n = jnp.sum(hit & pop.valid).astype(jnp.int32)
        mstate = meter.set(mstate, "quarantined", n)
        mstate = meter.inc(mstate, "quarantined_total", n)
        return mstate


# ================================================= selection pressure ====

@register_probe
class SelectionProbe(Probe):
    """Selection pressure, from the index vector the loop already holds
    (no extra compute touches the population).

    - ``sel_eff_parents`` — effective parent count, the inverse Simpson
      index ``1/Σ pᵢ²`` of the selection-count distribution: n means
      uniform selection, 1 means one individual swept the pool.
    - ``sel_loss_diversity`` — Blickle & Thiele's loss of diversity:
      the fraction of the selection pool never picked.
    - ``lineage_depth_mean`` / ``lineage_depth_max`` — generations of
      ancestry per individual, the scalarised form of
      ``support.history.Lineage``: the per-individual depth array rides
      the meter as an ``internal`` gauge and advances by
      ``depth[parent_idx] + 1`` exactly as :func:`~deap_tpu.support.
      history.lineage_step` advances ids. Only loops whose selection
      doubles as parentage provide ``parent_idx`` (``ea_simple``, the
      GP host loop); elsewhere the lineage gauges hold their last
      value.

    ``every=k`` decimates the pressure statistics to every k-th
    generation (``lax.cond`` — the gauges hold their last value in
    between): the count pass is one scatter-add over the pool, which
    XLA's CPU backend executes serially (~5 ms at pool=100k), and
    selection pressure moves slowly enough that sampling it is free
    accuracy. Lineage depths always advance every generation (a gather,
    cheap; skipping one would corrupt the depths for good).
    """

    metric_names = ("sel_eff_parents", "sel_loss_diversity",
                    "lineage_depth_mean", "lineage_depth_max")

    def __init__(self, n: Optional[int] = None, lineage: bool = True,
                 every: int = 1):
        """``n`` — population size, required when ``lineage`` is on
        (the internal depth gauge is declared with that shape)."""
        if lineage and n is None:
            raise ValueError("SelectionProbe(lineage=True) needs n= "
                             "(the per-individual depth gauge's shape)")
        self.n = None if n is None else int(n)
        self.lineage = bool(lineage)
        self.every = max(int(every), 1)

    def declare(self, meter) -> None:
        meter.gauge("sel_eff_parents")
        meter.gauge("sel_loss_diversity")
        if self.lineage:
            meter.gauge("lineage_depth_mean")
            meter.gauge("lineage_depth_max", dtype=jnp.int32)
            meter.gauge("lineage_depth", shape=(self.n,),
                        dtype=jnp.int32, internal=True)

    def __call__(self, meter, mstate, sel_idx=None, sel_pool=None,
                 parent_idx=None, gen=None, **_ctx):
        if sel_idx is not None and sel_pool:
            k = sel_idx.shape[0]

            def pressure(ms):
                counts = jnp.zeros(int(sel_pool),
                                   jnp.float32).at[sel_idx].add(1.0)
                p = counts / k
                eff = 1.0 / jnp.maximum(jnp.sum(p * p), 1e-12)
                ms = meter.set(ms, "sel_eff_parents", eff)
                return meter.set(ms, "sel_loss_diversity", jnp.mean(
                    (counts == 0).astype(jnp.float32)))

            if self.every > 1 and gen is not None:
                mstate = jax.lax.cond(
                    jnp.mod(jnp.asarray(gen), self.every) == 0,
                    pressure, lambda ms: ms, mstate)
            else:
                mstate = pressure(mstate)
        if self.lineage and parent_idx is not None:
            depth = mstate["lineage_depth"]
            nd = jnp.take(depth, parent_idx, axis=0) + 1
            mstate = meter.set(mstate, "lineage_depth", nd)
            mstate = meter.set(mstate, "lineage_depth_mean",
                               jnp.mean(nd.astype(jnp.float32)))
            mstate = meter.set(mstate, "lineage_depth_max", jnp.max(nd))
        return mstate


# ====================================================== front quality ====

def _hv_slab(P: jnp.ndarray, ref: jnp.ndarray) -> jnp.ndarray:
    """Exact hypervolume of the union of boxes ``[ref, p]`` for M in
    {1, 2, 3}, maximisation, ``P`` pre-clipped to ``>= ref``.

    M=2 is the classic staircase after an x-descending sort (O(K log
    K)); M=3 is the slab decomposition — sweep z descending, each slab
    ``(z_i − z_next)`` times the 2-D staircase area of the points above
    it — vectorised as one K×K membership matrix + row-wise cummax
    (O(K²) memory and time, which is the probe's documented budget).
    Dominated points never change a union, so no front filter is
    needed."""
    m = P.shape[1]
    if m == 1:
        return jnp.max(P[:, 0]) - ref[0]
    xo = jnp.argsort(-P[:, 0])
    xs, ys = P[xo, 0], P[xo, 1]
    widths = xs - jnp.concatenate([xs[1:], ref[None, 0]])
    if m == 2:
        ymax = jax.lax.cummax(ys)
        return jnp.sum(widths * (ymax - ref[1]))
    zo = jnp.argsort(-P[:, 2])
    zs = P[zo, 2]
    slabs = zs - jnp.concatenate([zs[1:], ref[None, 2]])
    # rank of each x-sorted point in the z order: zrank[i] = position
    # of point i (original index) in the z-descending sweep
    k = P.shape[0]
    zrank = jnp.zeros(k, jnp.int32).at[zo].set(jnp.arange(k, dtype=jnp.int32))
    member = zrank[xo][None, :] <= jnp.arange(k)[:, None]  # [slab, xpos]
    ymax = jax.lax.cummax(jnp.where(member, ys[None, :], ref[1]), axis=1)
    areas = jnp.sum(widths[None, :] * (ymax - ref[1]), axis=1)
    return jnp.sum(slabs * areas)


def exact_hypervolume(wvalues, ref) -> float:
    """Host-side exact hypervolume (native WFG / pure-python fallback,
    deap_tpu.native) of the points strictly dominating ``ref``, in the
    package's maximisation convention. The sampled ground truth the
    in-scan ``hv_proxy`` is checked against."""
    from deap_tpu.native import hypervolume

    w = np.asarray(wvalues, np.float64)
    r = np.asarray(ref, np.float64)
    keep = np.all(w > r[None, :], axis=1) & np.all(np.isfinite(w), axis=1)
    if not keep.any():
        return 0.0
    return float(hypervolume(-w[keep], -r))


@register_probe
class FrontProbe(Probe):
    """Per-generation multi-objective front quality, M ≤ 3.

    Works on a deterministic strided sample of ``max_points`` rows
    (the O(K²) parts are the documented budget; K defaults to 512):

    - ``front_frac`` — non-dominated fraction of the sample (O(K²)
      dominance check).
    - ``front_spread`` — euclidean norm of the front's per-objective
      extents (is the front covering, or a point?).
    - ``front_spacing`` — Schott's spacing: std of each front point's
      nearest-front-neighbour distance (uniformity of coverage).
    - ``hv_proxy`` — **exact** hypervolume of the sampled points
      w.r.t. ``ref`` (staircase for M=2, slab decomposition for M=3):
      a proxy only in that it sees the sample, not the population.

    With ``exact_every=k`` the sampled points also ship to the host
    every k generations (one small ``jax.debug.callback`` transfer) and
    the native exact hypervolume lands in the journal as ``hv_exact``
    events — the cross-check against ``hv_proxy`` costs nothing
    in-scan.
    """

    metric_names = ("front_frac", "front_spread", "front_spacing",
                    "hv_proxy")

    def __init__(self, ref: Sequence[float], max_points: int = 512,
                 exact_every: int = 0):
        self.ref = tuple(float(r) for r in ref)
        self.max_points = int(max_points)
        self.exact_every = int(exact_every)

    def declare(self, meter) -> None:
        for name in self.metric_names:
            meter.gauge(name)

    def _host_exact(self, journal, gen, pts):
        gen = int(gen)
        if self.exact_every and gen % self.exact_every == 0:
            journal.event("hv_exact", gen=gen,
                          value=exact_hypervolume(pts, self.ref),
                          n_points=int(pts.shape[0]))

    def __call__(self, meter, mstate, pop=None, gen=None, journal=None,
                 **_ctx):
        if pop is None:
            return mstate
        W = pop.wvalues
        m = W.shape[1]
        if m != len(self.ref):
            raise ValueError(f"FrontProbe ref has {len(self.ref)} "
                             f"objectives, population has {m}")
        if m > 3:
            raise ValueError("FrontProbe supports M <= 3 (in-scan "
                             "hypervolume); use exact_hypervolume on "
                             "the host for higher M")
        ref = jnp.asarray(self.ref, jnp.float32)
        idx = _strided(W.shape[0], self.max_points)
        S = W[idx]
        P = jnp.maximum(S, ref[None, :])  # invalid (-inf) rows collapse
        k = P.shape[0]

        ge = jnp.all(P[None, :, :] >= P[:, None, :], axis=-1)
        gt = jnp.any(P[None, :, :] > P[:, None, :], axis=-1)
        dominated = jnp.any(ge & gt, axis=1)
        front = ~dominated
        nfront = jnp.maximum(jnp.sum(front.astype(jnp.float32)), 1.0)
        mstate = meter.set(mstate, "front_frac",
                           jnp.mean(front.astype(jnp.float32)))

        lo = jnp.min(jnp.where(front[:, None], P, jnp.inf), axis=0)
        hi = jnp.max(jnp.where(front[:, None], P, -jnp.inf), axis=0)
        ext = jnp.where(jnp.isfinite(hi - lo), hi - lo, 0.0)
        mstate = meter.set(mstate, "front_spread",
                           jnp.sqrt(jnp.sum(ext ** 2)))

        sq = jnp.sum((P[:, None, :] - P[None, :, :]) ** 2, axis=-1)
        pairs = front[:, None] & front[None, :] & ~jnp.eye(k, dtype=bool)
        nn = jnp.min(jnp.where(pairs, jnp.sqrt(sq), jnp.inf), axis=1)
        nn = jnp.where(jnp.isfinite(nn), nn, 0.0)
        nn_mean = jnp.sum(jnp.where(front, nn, 0.0)) / nfront
        spacing = jnp.sqrt(
            jnp.sum(jnp.where(front, (nn - nn_mean) ** 2, 0.0)) / nfront)
        mstate = meter.set(mstate, "front_spacing", spacing)

        mstate = meter.set(mstate, "hv_proxy", _hv_slab(P, ref))

        if self.exact_every and journal is not None and gen is not None:
            jax.debug.callback(
                lambda g, pts: self._host_exact(journal, g, pts), gen, S)
        return mstate


# ----------------------------------------------------------- compose ----

def compose_probes(*probes: Callable) -> Probe:
    """One probe that declares and applies several in order (the shape
    the loops build internally from their ``probes=`` argument)."""

    class _Composite(Probe):
        metric_names = tuple(
            n for p in probes for n in getattr(p, "metric_names", ()))

        def declare(self, meter) -> None:
            for p in probes:
                if hasattr(p, "declare"):
                    p.declare(meter)

        def __call__(self, meter, mstate, **ctx):
            for p in probes:
                mstate = p(meter, mstate, **ctx)
            return mstate

    return _Composite()


# ======================================================= host tripwires ====

class HealthMonitor:
    """Host-side run-health tripwires over decoded meter rows.

    Feed it rows (via :class:`~deap_tpu.telemetry.run.RunTelemetry`
    ``health=``, which wires it into live streaming, host-driven
    ``record_row`` and the post-scan decode) and it emits ``alarm``
    dicts; the telemetry layer journals each as an ``alarm`` event.

    Tripwires (each armed only when its threshold is configured):

    - ``non_finite`` — any scalar metric in the row is NaN/Inf
      (``nan_check``, on by default: a NaN fitness silently poisons
      max/argmax selection).
    - ``clone_spike`` — clone rate above ``clone_rate_max``; reads
      ``clone_key`` (default ``gp_clone_rate``) and falls back to
      ``1 − div_unique_frac``.
    - ``premature_convergence`` — ``diversity_key`` fell below
      ``diversity_floor`` (optionally only before ``premature_min_gen``
      — collapse late in a run may just be convergence). Re-arms when
      diversity recovers.
    - ``zero_improvement`` — no ``best`` improvement beyond
      ``improvement_eps`` for ``stagnation_window`` consecutive rows
      (uses the row's ``stagnation_age`` when a FitnessProbe provides
      it, otherwise tracks ``best`` itself). Re-arms after improvement.
    - ``hlo_drift`` — not row-driven: the
      :class:`~deap_tpu.telemetry.costs.ProgramObservatory` calls
      :meth:`program_drift` when the same (program label, input
      signature) recompiles to a different HLO hash or cost — the
      silent-retrace regression class, promoted to an alarm.

    ``early_stop`` names alarm kinds (or ``True`` for all) that set
    :attr:`stop_requested` — host-driven loops (the GP engine, island
    epoch drivers) poll it between generations; scanned loops cannot
    stop mid-scan, their alarms land in the journal post-hoc.
    ``on_alarm`` is called with each alarm dict as it fires.
    """

    #: every alarm kind this monitor can emit (report/tests key on it)
    ALARM_KINDS = ("non_finite", "clone_spike", "premature_convergence",
                   "zero_improvement", "hlo_drift", "driver_stall",
                   "canary")

    def __init__(self, *, nan_check: bool = True,
                 clone_rate_max: Optional[float] = None,
                 clone_key: str = "gp_clone_rate",
                 diversity_floor: Optional[float] = None,
                 diversity_key: str = "div_msd",
                 premature_min_gen: Optional[int] = None,
                 stagnation_window: Optional[int] = None,
                 improvement_eps: float = 0.0,
                 early_stop=(), on_alarm: Optional[Callable] = None):
        self.nan_check = bool(nan_check)
        self.clone_rate_max = clone_rate_max
        self.clone_key = clone_key
        self.diversity_floor = diversity_floor
        self.diversity_key = diversity_key
        self.premature_min_gen = premature_min_gen
        self.stagnation_window = stagnation_window
        self.improvement_eps = float(improvement_eps)
        self.early_stop = (set(self.ALARM_KINDS) if early_stop is True
                           else set(early_stop))
        self.on_alarm = on_alarm
        self.alarms: List[dict] = []
        self._best: Optional[float] = None
        self._stag = 0
        self._stag_fired = False
        self._div_fired = False
        self._stop = False

    @property
    def stop_requested(self) -> bool:
        return self._stop

    def _fire(self, kind: str, gen, **detail) -> dict:
        alarm = {"alarm": kind, "gen": gen, **detail}
        self.alarms.append(alarm)
        if kind in self.early_stop:
            self._stop = True
        if self.on_alarm is not None:
            self.on_alarm(alarm)
        return alarm

    def program_drift(self, gen=None, **detail) -> dict:
        """Fire the ``hlo_drift`` alarm — called by the
        :class:`~deap_tpu.telemetry.costs.ProgramObservatory` when a
        (program, signature) pair recompiles to a different HLO hash
        or cost. Not a row tripwire: compile events, not meter rows,
        drive it. Honours ``early_stop``/``on_alarm`` like every other
        kind."""
        return self._fire("hlo_drift", gen, **detail)

    def driver_stall(self, gen=None, **detail) -> dict:
        """Fire the ``driver_stall`` alarm — called by the
        :class:`~deap_tpu.serving.service.EvolutionService` watchdog
        when the driver thread produced no progress heartbeat within
        its budget (a hung segment / wedged backend). Like
        ``hlo_drift``, host-event-driven rather than row-driven;
        honours ``early_stop``/``on_alarm``."""
        return self._fire("driver_stall", gen, **detail)

    def canary(self, gen=None, **detail) -> dict:
        """Fire the ``canary`` alarm — called by the
        :class:`~deap_tpu.serving.canary.CanaryRunner` when a
        known-answer canary tenant's wire digest mismatches its
        reference (or the canary cannot complete): the silent
        wrong-answer failure class nothing row-driven can see. Like
        ``driver_stall``, host-event-driven; honours
        ``early_stop``/``on_alarm``."""
        return self._fire("canary", gen, **detail)

    def _clone_rate(self, row) -> Optional[float]:
        v = row.get(self.clone_key)
        if v is None and "div_unique_frac" in row:
            v = 1.0 - row["div_unique_frac"]
        return v

    def check_row(self, row: Dict[str, Any],
                  gen: Optional[int] = None) -> List[dict]:
        """Run every armed tripwire on one decoded meter row; returns
        (and records) the alarms it fired."""
        if gen is None:
            gen = row.get("gen")
        fired: List[dict] = []

        if self.nan_check:
            bad = [k for k, v in row.items()
                   if isinstance(v, float) and not math.isfinite(v)]
            # quarantined evaluations were substituted with a finite
            # sentinel (resilience.quarantine_non_finite) — the probe's
            # count keeps the non-finite origin visible to this alarm
            nq = row.get("quarantined", 0)
            if isinstance(nq, (int, float)) and nq > 0:
                bad = bad + ["quarantined"]
            if bad:
                fired.append(self._fire(
                    "non_finite", gen, metrics=bad,
                    **({"quarantined": int(nq)} if nq else {})))

        if self.clone_rate_max is not None:
            cr = self._clone_rate(row)
            if cr is not None and cr > self.clone_rate_max:
                fired.append(self._fire(
                    "clone_spike", gen, value=round(float(cr), 6),
                    threshold=self.clone_rate_max))

        if self.diversity_floor is not None:
            div = row.get(self.diversity_key)
            if div is not None and math.isfinite(div):
                early = (self.premature_min_gen is None
                         or gen is None or gen < self.premature_min_gen)
                if div < self.diversity_floor and early:
                    if not self._div_fired:
                        self._div_fired = True
                        fired.append(self._fire(
                            "premature_convergence", gen,
                            metric=self.diversity_key,
                            value=round(float(div), 6),
                            floor=self.diversity_floor))
                elif div >= self.diversity_floor:
                    self._div_fired = False  # re-arm on recovery

        if self.stagnation_window is not None:
            age = row.get("stagnation_age")
            if age is None:
                best = row.get("best")
                if best is not None and math.isfinite(best):
                    if (self._best is None
                            or best > self._best + self.improvement_eps):
                        self._best, self._stag = best, 0
                    else:
                        self._stag += 1
                age = self._stag
            if age >= self.stagnation_window:
                if not self._stag_fired:
                    self._stag_fired = True
                    fired.append(self._fire(
                        "zero_improvement", gen, age=int(age),
                        window=self.stagnation_window))
            else:
                self._stag_fired = False  # improvement re-arms
        return fired
