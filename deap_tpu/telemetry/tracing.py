"""Distributed tracing plane — span-structured request waterfalls.

The fourth observability layer (after journal rows, Prometheus
instruments, and the flight recorder), and the one that makes the
other three composable: one ``trace_id`` threads a request from the
client socket through the HTTP front end, the WAL fsync, the command
queue, scheduler admission, AOT compile, device segments, checkpoint
flushes, and the wire encode — each phase a ``trace_span`` journal
row that `report.py --trace` renders as a terminal waterfall and
:func:`write_perfetto` exports as Chrome/Perfetto trace-event JSON.

Design constraints this module answers:

* **Stdlib only, no package imports at module scope.** The client and
  ``report.py`` load this file standalone by path (no ``deap_tpu`` —
  and therefore no jax — in the process); the lazy ``broadcast``
  lookup in :func:`emit_current` is guarded for exactly that case.
* **Deterministic ids.** ``trace_id`` and the root span id derive
  from the request id by hashing (:func:`trace_id_for`,
  :func:`span_id_for`), so the client, the service, and a
  kill-9-restarted service that recovered the request id from its WAL
  all agree on the same trace without any coordination — that is the
  entire cross-restart stitching mechanism.
* **Lifecycle spans are always on.** The sampling knob
  (``trace_sample``) gates high-volume detail spans; the tenant
  lifecycle (queue wait → admission → segment[i] → checkpoint →
  finished) is emitted whenever tracing is enabled at all, so the
  waterfall is never missing its spine.

W3C trace-context interop: :func:`format_traceparent` /
:func:`parse_traceparent` speak the ``00-<trace>-<span>-<flags>``
header format, so an external frontend's traceparent is honoured
(its trace id wins; its span becomes the root span's parent).
"""

from __future__ import annotations

import contextlib
import contextvars
import hashlib
import json
import os
import re
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "PHASES", "TraceContext", "Tracer",
    "trace_id_for", "span_id_for", "new_span_id",
    "format_traceparent", "parse_traceparent",
    "current", "use", "current_ids", "emit_current",
    "assemble_trace", "perfetto_events", "write_perfetto",
]

#: Canonical phase labels — the buckets of the per-phase latency
#: decomposition (and the ``phase`` label values of the
#: ``deap_service_phase_seconds`` histogram in telemetry/metrics.py).
PHASES = ("queue_wait", "wal_fsync", "admission", "compile",
          "device", "checkpoint", "wire_encode", "replay", "build")

_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


# ------------------------------------------------------------- ids ----

def trace_id_for(request_id: str) -> str:
    """The deterministic 32-hex trace id for a request id.

    Every process that knows the request id — the submitting client,
    the serving process, a restarted-after-kill-9 serving process that
    replayed the id out of its WAL — derives the identical trace id,
    which is what stitches one waterfall across restarts."""
    h = hashlib.sha256(b"deap-tpu-trace:" + str(request_id).encode())
    return h.hexdigest()[:32]


def span_id_for(request_id: str, name: str) -> str:
    """A deterministic 16-hex span id for a (request, span-name)
    pair. Used for the root ``request`` span so resume spans emitted
    after a restart can parent onto it without the original row."""
    h = hashlib.sha256(
        b"deap-tpu-span:" + str(request_id).encode() + b":"
        + str(name).encode())
    return h.hexdigest()[:16]


def new_span_id() -> str:
    """A random 16-hex span id for ordinary child spans."""
    return os.urandom(8).hex()


def root_span_id(request_id: str) -> str:
    """The deterministic id of the request's root span."""
    return span_id_for(request_id, "request")


# ----------------------------------------------------- traceparent ----

def format_traceparent(trace_id: str, span_id: str,
                       sampled: bool = True) -> str:
    """Render a W3C ``traceparent`` header value (version 00)."""
    return "00-%s-%s-%s" % (trace_id, span_id,
                            "01" if sampled else "00")


def parse_traceparent(header: Optional[str]
                      ) -> Optional[Tuple[str, str, bool]]:
    """``(trace_id, span_id, sampled)`` from a ``traceparent`` header,
    or ``None`` when absent/malformed (all-zero ids are malformed per
    the W3C spec and rejected here too)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    _, trace_id, span_id, flags = m.groups()
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id, bool(int(flags, 16) & 0x01)


# --------------------------------------------------------- context ----

@dataclass(frozen=True)
class TraceContext:
    """The ambient identity of the request currently being served.

    ``sampled`` is the tracer's per-trace decision for *detail* spans;
    lifecycle spans (``always=True``) ignore it."""
    trace_id: str
    span_id: str
    request_id: Optional[str] = None
    sampled: bool = True

    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id,
                                  self.sampled)

    def child(self, span_id: Optional[str] = None) -> "TraceContext":
        return TraceContext(self.trace_id, span_id or new_span_id(),
                            self.request_id, self.sampled)


_CURRENT: contextvars.ContextVar[Optional[TraceContext]] = \
    contextvars.ContextVar("deap_tpu_trace_context", default=None)


def current() -> Optional[TraceContext]:
    """The ambient :class:`TraceContext`, or ``None`` outside a
    request."""
    return _CURRENT.get()


@contextlib.contextmanager
def use(ctx: Optional[TraceContext]):
    """Install ``ctx`` as the ambient trace context for the block.
    ``None`` is a no-op (so call sites need no conditional)."""
    if ctx is None:
        yield
        return
    token = _CURRENT.set(ctx)
    try:
        yield
    finally:
        _CURRENT.reset(token)


def current_ids() -> Dict[str, Any]:
    """``{trace_id, span_id, request_id}`` of the ambient context for
    stamping onto foreign journal rows (e.g. ``program_profile``), or
    ``{}`` outside a request."""
    ctx = _CURRENT.get()
    if ctx is None:
        return {}
    out: Dict[str, Any] = {"trace_id": ctx.trace_id,
                           "span_id": ctx.span_id}
    if ctx.request_id is not None:
        out["request_id"] = ctx.request_id
    return out


def emit_current(name: str, dur_s: float, phase: Optional[str] = None,
                 always: bool = False,
                 links: Optional[List[Dict[str, Any]]] = None,
                 **attrs: Any) -> None:
    """Emit a ``trace_span`` row against the *ambient* context via
    journal broadcast — for layers that hold no tracer reference
    (costs observatory, checkpoint writer, the profiling bridge).
    No ambient context, or a context sampled out (unless ``always``),
    means no row. Safe under standalone load: when the journal module
    is unimportable the call is a silent no-op."""
    ctx = _CURRENT.get()
    if ctx is None or not (always or ctx.sampled):
        return
    try:
        from deap_tpu.telemetry.journal import broadcast
    except Exception:
        return
    row: Dict[str, Any] = dict(
        name=name, phase=phase, dur_s=round(float(dur_s), 6),
        trace_id=ctx.trace_id, span_id=new_span_id(),
        parent_id=ctx.span_id)
    if ctx.request_id is not None:
        row["request_id"] = ctx.request_id
    if links:
        row["links"] = links
    row.update(attrs)
    broadcast("trace_span", **row)


# ---------------------------------------------------------- tracer ----

class Tracer:
    """Span factory bound to a journal and a sampling rate.

    ``sample`` is the ``trace_sample`` knob: a float in [0, 1]
    deciding *per trace* (deterministically, from the trace id's
    leading bits) whether detail spans are recorded. Lifecycle spans
    pass ``always=True`` and are emitted regardless. ``phase_observe``
    — when set — receives ``(phase, dur_s)`` for every emitted span
    with a phase, feeding the ``deap_service_phase_seconds``
    histogram."""

    def __init__(self, journal: Any = None, sample: float = 1.0,
                 phase_observe: Optional[
                     Callable[[str, float], None]] = None):
        self.journal = journal
        self.sample = float(sample)
        self.phase_observe = phase_observe

    # -- context -------------------------------------------------------

    def sampled(self, trace_id: str) -> bool:
        """Deterministic per-trace sampling decision: the trace id's
        leading 32 bits as a uniform draw in [0, 1)."""
        if self.sample >= 1.0:
            return True
        if self.sample <= 0.0:
            return False
        return (int(trace_id[:8], 16) / 0x100000000) < self.sample

    def context_for(self, request_id: str,
                    traceparent: Optional[str] = None
                    ) -> TraceContext:
        """The trace context for an incoming request: a valid
        ``traceparent`` header wins (its trace continues, its span
        becomes the parent); otherwise both ids derive from the
        request id."""
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, span_id, flag = parsed
            return TraceContext(trace_id, span_id, request_id,
                                flag and self.sampled(trace_id))
        trace_id = trace_id_for(request_id)
        return TraceContext(trace_id, root_span_id(request_id),
                            request_id, self.sampled(trace_id))

    # -- emission ------------------------------------------------------

    def emit(self, name: str, dur_s: float,
             ctx: Optional[TraceContext] = None,
             phase: Optional[str] = None, always: bool = False,
             span_id: Optional[str] = None,
             parent_id: Optional[str] = None,
             links: Optional[List[Dict[str, Any]]] = None,
             **attrs: Any) -> None:
        """Record one finished span (duration measured by the caller).
        ``ctx`` defaults to the ambient context; no context → no row.
        The phase histogram observes every phase-carrying span the
        moment a context exists — sampling gates only the journal
        row, so ``deap_service_phase_seconds`` stays complete at any
        sample rate while the per-trace waterfall detail is paid for
        by the sampled minority."""
        ctx = ctx if ctx is not None else _CURRENT.get()
        if ctx is None:
            return
        if phase is not None and self.phase_observe is not None:
            self.phase_observe(phase, float(dur_s))
        if not (always or ctx.sampled):
            return
        row: Dict[str, Any] = dict(
            name=name, phase=phase,
            dur_s=round(float(dur_s), 6),
            trace_id=ctx.trace_id,
            span_id=span_id or new_span_id(),
            parent_id=(parent_id if parent_id is not None
                       else ctx.span_id))
        if row["parent_id"] == row["span_id"]:
            row["parent_id"] = None  # a root span has no parent
        if ctx.request_id is not None:
            row.setdefault("request_id", ctx.request_id)
        if links:
            row["links"] = links
        row.update(attrs)
        if self.journal is not None:
            self.journal.event("trace_span", **row)
        else:
            try:
                from deap_tpu.telemetry.journal import broadcast
            except Exception:
                return
            broadcast("trace_span", **row)

    @contextlib.contextmanager
    def span(self, name: str, ctx: Optional[TraceContext] = None,
             phase: Optional[str] = None, always: bool = False,
             span_id: Optional[str] = None,
             parent_id: Optional[str] = None,
             links: Optional[List[Dict[str, Any]]] = None,
             **attrs: Any):
        """Time the block and emit it as one span. The block runs with
        the (child) context ambient, so spans opened inside it parent
        correctly and :func:`current_ids` stamps foreign rows."""
        ctx = ctx if ctx is not None else _CURRENT.get()
        if ctx is None:
            yield None
            return
        sid = span_id or new_span_id()
        child = TraceContext(ctx.trace_id, sid, ctx.request_id,
                             ctx.sampled)
        t0 = time.perf_counter()
        token = _CURRENT.set(child)
        try:
            yield child
        finally:
            _CURRENT.reset(token)
            self.emit(name, time.perf_counter() - t0, ctx=ctx,
                      phase=phase, always=always, span_id=sid,
                      parent_id=parent_id, links=links, **attrs)


# -------------------------------------------------------- assembly ----

def assemble_trace(row_groups: Iterable[Tuple[Optional[dict],
                                              Iterable[dict]]],
                   trace_id: str) -> Dict[str, Any]:
    """Stitch one trace out of (possibly several, possibly rotated)
    journals.

    ``row_groups`` is an iterable of ``(header_row_or_None, rows)``
    pairs — one pair per journal file, oldest first. Journal ``t``
    values are monotonic offsets from each file's own epoch; the
    header's ``wall_start`` rebases them onto one wall-clock axis so
    pre-kill and post-restart spans order correctly.

    Returns ``{"trace_id", "spans", "orphans", "root"}`` where each
    span dict gains ``start`` (absolute seconds; span rows carry their
    *end* time) and ``orphans`` lists span ids whose ``parent_id``
    resolves neither to a span in the trace nor to the deterministic
    root. A missing root span (e.g. only the post-restart journal
    survived and the root row was in the rotated file that got lost)
    is synthesized and marked ``synthetic: True``."""
    spans: List[Dict[str, Any]] = []
    for header, rows in row_groups:
        wall = float((header or {}).get("wall_start", 0.0))
        for row in rows:
            if row.get("kind") != "trace_span":
                continue
            if row.get("trace_id") != trace_id:
                continue
            s = dict(row)
            end = wall + float(row.get("t", 0.0))
            s["start"] = end - float(row.get("dur_s", 0.0) or 0.0)
            s["end"] = end
            spans.append(s)
    spans.sort(key=lambda s: s["start"])

    ids = {s["span_id"] for s in spans}
    root = next((s for s in spans
                 if s.get("parent_id") is None
                 or s["parent_id"] not in ids), None)
    rid = next((s.get("request_id") for s in spans
                if s.get("request_id")), None)
    det_root = root_span_id(rid) if rid is not None else None
    have_root = det_root is not None and det_root in ids
    if not have_root and det_root is not None:
        lo = min((s["start"] for s in spans), default=0.0)
        hi = max((s["end"] for s in spans), default=0.0)
        spans.insert(0, {
            "kind": "trace_span", "name": "request", "phase": None,
            "trace_id": trace_id, "span_id": det_root,
            "parent_id": None, "request_id": rid,
            "start": lo, "end": hi,
            "dur_s": round(hi - lo, 6), "synthetic": True,
        })
        ids.add(det_root)
        root = spans[0]
    elif have_root:
        root = next(s for s in spans if s["span_id"] == det_root)

    # orphan check by span id, not object identity: a retried request
    # re-handled server-side emits the deterministic root row once per
    # attempt — every copy is the root, none is an orphan
    root_sid = root["span_id"] if root is not None else None
    orphans = [s["span_id"] for s in spans
               if s.get("parent_id") is not None
               and s["parent_id"] not in ids
               and s["span_id"] != root_sid]
    return {"trace_id": trace_id, "spans": spans,
            "orphans": orphans, "root": root}


# -------------------------------------------------------- perfetto ----

def perfetto_events(spans: Iterable[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """Chrome/Perfetto trace-event JSON events for assembled spans
    (``"ph": "X"`` complete events; zero-duration spans become
    instants). Load the output at ``ui.perfetto.dev`` or
    ``chrome://tracing``."""
    events: List[Dict[str, Any]] = []
    for s in spans:
        dur_us = float(s.get("dur_s", 0.0) or 0.0) * 1e6
        args = {k: v for k, v in s.items()
                if k not in ("kind", "t", "name", "start", "end",
                             "dur_s")
                and v is not None}
        base = dict(name=s.get("name", "?"), pid=1,
                    tid=s.get("tenant_id") or s.get("request_id")
                    or "trace",
                    ts=round(float(s.get("start", 0.0)) * 1e6, 3),
                    args=args)
        if dur_us <= 0.0:
            events.append(dict(base, ph="i", s="t"))
        else:
            events.append(dict(base, ph="X",
                               dur=round(dur_us, 3)))
    return events


def write_perfetto(path: str,
                   spans: Iterable[Dict[str, Any]]) -> str:
    """Write assembled spans as a Perfetto-loadable trace-event file;
    returns ``path``."""
    payload = {"traceEvents": perfetto_events(spans),
               "displayTimeUnit": "ms"}
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path
