"""Run journal — structured JSONL host events for a whole run.

The host-events plane of the telemetry subsystem: one append-only JSONL
file per run, each line ``{"t": <secs since open>, "kind": ..., ...}``.
``t`` deltas are measured on the **monotonic** clock (an NTP step
mid-run can never yield backwards timestamps); the wall-clock epoch of
the open lands in the header as ``wall_start``, so ``wall_start + t``
dates any row. Kinds written by this module and the algorithm
integrations:

- ``header`` — backend / device / jax-version / process fingerprint,
  plus an optional toolbox fingerprint (which operators, bound args).
- ``run_start`` / ``run_end`` — one per algorithm invocation.
- ``compile`` / ``retrace`` — every XLA backend compile observed via
  ``jax.monitoring`` listeners. Compiles after :meth:`RunJournal.
  mark_steady` are journaled as ``retrace``: the silent-recompile
  failure mode (a shape or closure change re-triggering compilation
  mid-run) becomes a visible, machine-readable event instead of an
  unexplained wall-time cliff.
- ``meter`` — per-generation metric rows decoded from a
  :class:`~deap_tpu.telemetry.meter.Meter`'s stacked scan output.
- ``span`` — per-name wall-time aggregates from a
  :class:`~deap_tpu.support.profiling.SpanRecorder`.
- ``event`` kinds from subsystems (checkpoint, migration, eval-batch,
  GP interpreter cache misses) via :meth:`RunJournal.event` or the
  module-level :func:`broadcast` (which reaches every open journal —
  used by code that must not hold a journal reference).
- ``summary`` — final roll-up written on close.

``jax.monitoring`` only supports registering listeners (there is no
unregister), so one process-wide listener pair is installed lazily and
dispatches to the set of currently-open journals.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["RunJournal", "JournalRows", "read_journal", "broadcast",
           "toolbox_fingerprint", "environment_fingerprint"]

_LOCK = threading.Lock()
_ACTIVE: List["RunJournal"] = []
_LISTENERS_INSTALLED = [False]

#: the jax.monitoring duration event that marks one XLA backend compile
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _on_duration(event: str, duration: float, **_kw) -> None:
    if event != _COMPILE_EVENT:
        return
    with _LOCK:
        journals = list(_ACTIVE)
    for j in journals:
        j._compile_observed(duration)


def _install_listeners() -> bool:
    if _LISTENERS_INSTALLED[0]:
        return True
    try:
        import jax.monitoring
        jax.monitoring.register_event_duration_secs_listener(_on_duration)
    except Exception:
        return False
    _LISTENERS_INSTALLED[0] = True
    return True


def broadcast(kind: str, **payload: Any) -> None:
    """Write an event into every currently-open journal. For subsystem
    code (GP interpreter cache, checkpointing) that should surface
    events when a journal happens to be active but must not depend on
    one being passed in."""
    with _LOCK:
        journals = list(_ACTIVE)
    for j in journals:
        j.event(kind, **payload)


def toolbox_fingerprint(toolbox: Any) -> Dict[str, Any]:
    """Which operators a toolbox binds, and a stable digest of the
    configuration — so journals from different runs are comparable
    ("same toolbox, different wall time" vs "different toolbox")."""
    aliases: Dict[str, str] = {}
    for name, val in sorted(vars(toolbox).items()):
        func = getattr(val, "func", val)
        bound = ""
        args = getattr(val, "args", ())
        kwargs = getattr(val, "keywords", {}) or {}
        if args or kwargs:
            bound = repr((args, tuple(sorted(kwargs.items()))))
        aliases[name] = "%s.%s%s" % (
            getattr(func, "__module__", "?"),
            getattr(func, "__name__", "?"), bound)
    digest = hashlib.sha1(
        json.dumps(aliases, sort_keys=True).encode()).hexdigest()[:12]
    return {"aliases": aliases, "digest": digest}


def environment_fingerprint(init_backend: bool = True) -> Dict[str, Any]:
    """jax version / backend / device kind+count — the row fingerprint
    that distinguishes cached-replay from fresh-capture benchmark rows.
    ``init_backend=False`` skips anything that would initialise the XLA
    client (single-client TPU runtimes must not be attached twice)."""
    import jax

    fp: Dict[str, Any] = {"jax": jax.__version__}
    if not init_backend:
        return fp
    try:
        devices = jax.devices()
        fp["backend"] = jax.default_backend()
        fp["device_kind"] = devices[0].device_kind
        fp["n_devices"] = len(devices)
        fp["process_count"] = jax.process_count()
    except Exception as e:  # backend failed to initialise: still a journal
        fp["backend_error"] = repr(e)[:200]
    return fp


class RunJournal:
    """Append-only JSONL journal for one run. Usable directly or (more
    commonly) through :class:`deap_tpu.telemetry.RunTelemetry`::

        with RunJournal("run.jsonl") as journal:
            journal.header(toolbox=tb)
            ... run ...
            journal.summary(gens=100)
    """

    def __init__(self, path: str, run_id: Optional[str] = None,
                 fsync_every: Optional[int] = None):
        """``fsync_every=n`` opts into durability: every n-th row the
        file is fsync'd, so a killed run loses at most n-1 rows (the
        default flush-only policy can lose the whole OS-buffered tail).
        The torn-tail tolerance of :func:`read_journal` composes with
        it — a kill mid-``write`` still tears at most the final line."""
        self.path = str(path)
        self.run_id = run_id or hex(int(time.time() * 1e6))[2:]
        self.fsync_every = int(fsync_every) if fsync_every else None
        self._rows_since_sync = 0
        # row `t` deltas come from the monotonic clock: an NTP step
        # mid-run must never produce backwards/negative timestamps.
        # The wall-clock epoch at open is kept separately and written
        # into the header (`wall_start`) so rows remain datable.
        self._t0 = time.monotonic()
        self.wall_start = time.time()
        # rows arrive from the main thread AND background writers (the
        # async checkpoint worker broadcasts checkpoint events): one
        # lock keeps lines whole
        self._write_lock = threading.Lock()
        # opening "w" truncates: a restart over the same root (WAL
        # replay after kill -9) must not destroy the previous journal
        # — the pre-kill half of a request's trace lives there. Rotate
        # a non-empty predecessor to `<path>.N` (next free integer);
        # fixed-path readers still see the newest journal, and the
        # trace assembler reads the rotated siblings to stitch one
        # waterfall across the restart.
        self.rotated_from: Optional[str] = None
        try:
            if os.path.getsize(self.path) > 0:
                n = 1
                while os.path.exists("%s.%d" % (self.path, n)):
                    n += 1
                self.rotated_from = "%s.%d" % (self.path, n)
                os.replace(self.path, self.rotated_from)
        except OSError:
            pass
        self._fh = open(self.path, "w")
        self._steady: Optional[str] = None
        self.n_compiles = 0
        self.n_retraces = 0
        self._closed = False
        self._monitoring = _install_listeners()
        with _LOCK:
            _ACTIVE.append(self)

    # --------------------------------------------------------- plumbing ----

    def _write(self, kind: str, payload: Dict[str, Any]) -> None:
        if self._closed:
            return
        line = {"t": round(time.monotonic() - self._t0, 6), "kind": kind}
        line.update(payload)
        with self._write_lock:
            if self._closed:
                return
            self._fh.write(json.dumps(line) + "\n")
            self._fh.flush()
            if self.fsync_every:
                self._rows_since_sync += 1
                if self._rows_since_sync >= self.fsync_every:
                    os.fsync(self._fh.fileno())
                    self._rows_since_sync = 0

    # ----------------------------------------------------------- events ----

    def header(self, toolbox: Any = None, init_backend: bool = True,
               **extra: Any) -> None:
        payload: Dict[str, Any] = {
            "run_id": self.run_id,
            "wall_start": round(self.wall_start, 6),
            "env": environment_fingerprint(init_backend),
            "monitoring": self._monitoring,
        }
        if toolbox is not None:
            payload["toolbox"] = toolbox_fingerprint(toolbox)
        payload.update(extra)
        self._write("header", payload)

    def event(self, kind: str, **payload: Any) -> None:
        self._write(kind, payload)

    def _compile_observed(self, duration: float) -> None:
        self.n_compiles += 1
        if self._steady is None:
            self._write("compile", {"dur_s": round(duration, 6),
                                    "seq": self.n_compiles})
        else:
            self.n_retraces += 1
            self._write("retrace", {"dur_s": round(duration, 6),
                                    "seq": self.n_compiles,
                                    "after": self._steady})

    def mark_steady(self, label: str = "") -> None:
        """Declare compilation finished: every backend compile observed
        after this point is journaled as a ``retrace`` — the silent
        recompile the in-scan design is supposed to make impossible.
        Algorithm integrations call this when their first instrumented
        run completes."""
        if self._steady is None:
            self._steady = label or "steady"
            self._write("steady", {"label": self._steady,
                                   "n_compiles": self.n_compiles})

    def meter_rows(self, meter: Any, stacked: Any, gen0: int = 1,
                   initial: Any = None) -> None:
        """Write per-generation ``meter`` rows from a scan's stacked
        meter output; ``initial`` (the pre-scan state) becomes the
        ``gen0 - 1`` row."""
        if initial is not None:
            self._write("meter", {"gen": gen0 - 1, **meter.row(initial)})
        for i, row in enumerate(meter.rows(stacked)):
            self._write("meter", {"gen": gen0 + i, **row})

    def spans(self, recorder: Any) -> None:
        """Write one ``span`` aggregate row per span name recorded by a
        :class:`~deap_tpu.support.profiling.SpanRecorder`."""
        for name, agg in sorted(recorder.aggregates().items()):
            self._write("span", {"name": name, **{
                k: (round(v, 9) if isinstance(v, float) else v)
                for k, v in agg.items()}})

    def summary(self, **payload: Any) -> None:
        payload.setdefault("n_compiles", self.n_compiles)
        payload.setdefault("n_retraces", self.n_retraces)
        self._write("summary", payload)

    # ---------------------------------------------------------- closing ----

    def close(self) -> None:
        if self._closed:
            return
        with _LOCK:
            if self in _ACTIVE:
                _ACTIVE.remove(self)
        with self._write_lock:  # never close the fh under a writer
            self._closed = True
            if self.fsync_every and self._rows_since_sync:
                try:
                    os.fsync(self._fh.fileno())
                except OSError:
                    pass
            self._fh.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class JournalRows(List[Dict[str, Any]]):
    """``read_journal``'s result: a plain list of event dicts, plus
    where the file stopped being parseable.

    - ``tear_offset`` — byte offset of a torn *tail* (a final line a
      killed writer never finished — truncated JSON or missing its
      newline), or ``None`` when the journal ends cleanly.
    - ``skipped_offsets`` — byte offsets of malformed *interior* lines
      (newline-terminated but unparseable: a crashed writer mid-file,
      interleaved garbage).
    """

    def __init__(self, *args):
        super().__init__(*args)
        self.tear_offset: Optional[int] = None
        self.skipped_offsets: List[int] = []


def read_journal(path: str, strict: bool = False) -> JournalRows:
    """Parse a journal back into a list of event dicts.

    A journal from a killed run usually ends in a torn line (the
    writer died mid-``write``); by default (``strict=False``) the
    complete rows are returned and the tear's byte offset is reported
    on the result (:class:`JournalRows` ``.tear_offset`` — resume
    tooling can truncate there and append). Malformed interior lines
    are skipped with their offsets recorded. ``strict=True`` raises
    ``ValueError`` naming the first bad byte offset instead.
    """
    out = JournalRows()
    with open(path, "rb") as fh:
        data = fh.read()
    offset = 0
    for raw in data.split(b"\n"):
        terminated = offset + len(raw) < len(data)
        line = raw.strip()
        if line:
            try:
                out.append(json.loads(line.decode("utf-8")))
            except (json.JSONDecodeError, UnicodeDecodeError):
                if strict:
                    raise ValueError(
                        f"{path}: unparseable journal line at byte "
                        f"{offset}" + ("" if terminated else
                                       " (torn tail — writer killed "
                                       "mid-write?)"))
                if terminated:
                    out.skipped_offsets.append(offset)
                else:
                    out.tear_offset = offset
        offset += len(raw) + 1
    return out


def journal_generations(path: str) -> List[str]:
    """All generations of a journal path, oldest first: the rotated
    predecessors ``<path>.1``, ``<path>.2``, … (created by
    :class:`RunJournal` when a restart reopened the same path), then
    the live file itself. Only paths that exist are returned — the
    common single-generation case yields ``[path]``."""
    out: List[str] = []
    n = 1
    while os.path.exists("%s.%d" % (path, n)):
        out.append("%s.%d" % (path, n))
        n += 1
    if os.path.exists(path):
        out.append(path)
    return out
