"""Terminal run report for any telemetry journal — **no jax import**.

Renders a JSONL :class:`~deap_tpu.telemetry.journal.RunJournal` into a
human-readable run-health report: header fingerprint, per-probe
sparklines over the meter rows, the alarm timeline, retrace summary and
the span p50/p99 table. This is the triage tool for a box that cannot
(or must not) initialise a backend — summarising a TPU run's journal on
a laptop, or inside CI where attaching the single-client runtime is
forbidden — so the module imports nothing but the standard library.

To keep that guarantee it loads ``journal.py``'s parser by file path
(the ``deap_tpu`` package ``__init__`` imports jax; ``journal.py``
itself does not), and ``tests/test_probes.py`` pins "renders a journal
without jax in ``sys.modules``" in a subprocess.

Usage::

    python bench_report.py --health run.jsonl      # the wired-up entry
    python -m deap_tpu.telemetry.report run.jsonl  # jax already loaded
"""

from __future__ import annotations

import importlib.util
import math
import os
import sys
from typing import Any, Dict, List, Optional

__all__ = ["render_attribution", "render_fleet", "render_report",
           "render_slo", "render_trace", "sparkline", "main"]

_SPARK = "▁▂▃▄▅▆▇█"
_MAX_SPARK = 48  # terminal budget per series

_journal_mod = None
_tracing_mod = None
_slo_mod = None
_federation_mod = None


def _journal():
    """journal.py loaded standalone (not via the package, which would
    drag in jax) — shares the exact parser, including the torn-tail
    handling."""
    global _journal_mod
    if _journal_mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "journal.py")
        spec = importlib.util.spec_from_file_location(
            "_deap_tpu_journal_standalone", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _journal_mod = mod
    return _journal_mod


def _tracing():
    """tracing.py loaded standalone — same no-jax guarantee as
    :func:`_journal` (tracing.py is pure stdlib)."""
    global _tracing_mod
    if _tracing_mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "tracing.py")
        spec = importlib.util.spec_from_file_location(
            "_deap_tpu_tracing_standalone", path)
        mod = importlib.util.module_from_spec(spec)
        # dataclass processing resolves cls.__module__ through
        # sys.modules — register before exec (stdlib-only, so this
        # pulls nothing else in)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _tracing_mod = mod
    return _tracing_mod


def _slo():
    """slo.py loaded standalone — same no-jax guarantee as
    :func:`_journal` (slo.py is pure stdlib)."""
    global _slo_mod
    if _slo_mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "slo.py")
        spec = importlib.util.spec_from_file_location(
            "_deap_tpu_slo_standalone", path)
        mod = importlib.util.module_from_spec(spec)
        # dataclass processing resolves cls.__module__ through
        # sys.modules — register before exec
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _slo_mod = mod
    return _slo_mod


def _federation():
    """federation.py loaded standalone — same no-jax guarantee as
    :func:`_journal` (federation.py is pure stdlib and loads its own
    siblings by path)."""
    global _federation_mod
    if _federation_mod is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "federation.py")
        spec = importlib.util.spec_from_file_location(
            "_deap_tpu_federation_standalone", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _federation_mod = mod
    return _federation_mod


def sparkline(values: List[float], width: int = _MAX_SPARK) -> str:
    """Unicode sparkline of a numeric series; non-finite points render
    as ``·``. Longer series are strided down to ``width`` points."""
    vals = list(values)
    if not vals:
        return ""
    if len(vals) > width:
        vals = [vals[(i * len(vals)) // width] for i in range(width)]
    finite = [v for v in vals if isinstance(v, (int, float))
              and math.isfinite(v)]
    if not finite:
        return "·" * len(vals)
    lo, hi = min(finite), max(finite)
    span = hi - lo
    out = []
    for v in vals:
        if not (isinstance(v, (int, float)) and math.isfinite(v)):
            out.append("·")
        elif span == 0:
            out.append(_SPARK[3])
        else:
            out.append(_SPARK[min(int((v - lo) / span * 8), 7)])
    return "".join(out)


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        if not math.isfinite(v):
            return str(v)
        return f"{v:.6g}"
    return str(v)


def _meter_series(events: List[Dict[str, Any]]):
    """meter rows → {metric: [(gen, value), ...]} for scalar numerics
    (histogram lists are skipped — sparklines are per-scalar)."""
    series: Dict[str, List] = {}
    for e in events:
        if e.get("kind") != "meter":
            continue
        gen = e.get("gen")
        for k, v in e.items():
            if k in ("kind", "t", "gen", "tenant_id"):
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            series.setdefault(k, []).append((gen, v))
    return series


def _tenant_sections(events: List[Dict[str, Any]], out: List[str]
                     ) -> bool:
    """Multi-tenant serving journals: group meter/alarm/lifecycle rows
    by ``tenant_id`` and render one per-tenant block (metric
    sparklines + that tenant's alarm timeline), plus the scheduler's
    admission/eviction ledger. Tenant blocks are grouped by loop
    **family** (from the ``job_submitted`` rows) so GP / island /
    scan-family lanes read as separate cohorts. Returns True when the
    journal was multi-tenant (the caller then skips the single-run
    sections that would interleave tenants)."""
    tenants: Dict[str, List[Dict[str, Any]]] = {}
    families: Dict[str, str] = {}
    for e in events:
        tid = e.get("tenant_id")
        if tid is not None:
            tenants.setdefault(str(tid), []).append(e)
            if e.get("kind") == "job_submitted" and "family" in e:
                families[str(tid)] = str(e["family"])
    if not tenants:
        return False

    prewarms = [e for e in events if e.get("kind") == "prewarm"]
    if prewarms:
        total = sum(e.get("compile_s", 0.0) for e in prewarms)
        out.append(f"- prewarm: {len(prewarms)} bucket program(s), "
                   f"{total:.3f}s compiling")
    segs = [e for e in events if e.get("kind") == "segment"
            and "tenant_id" not in e]
    if segs:
        out.append(f"- {len(segs)} scheduler segment(s)")

    out.append("")
    out.append(f"## Tenants ({len(tenants)})")
    by_family: Dict[str, List[str]] = {}
    for tid in sorted(tenants):
        by_family.setdefault(families.get(tid, "?"), []).append(tid)
    for family in sorted(by_family):
        if len(by_family) > 1 or family != "?":
            out.append("")
            out.append(f"### family {family} "
                       f"({len(by_family[family])} tenant(s))")
        for tid in by_family[family]:
            rows = tenants[tid]
            out.append("")
            out.append(f"#### tenant {tid}")
            life = {k: sum(1 for e in rows if e.get("kind") == k)
                    for k in ("tenant_admitted", "tenant_evicted",
                              "tenant_resumed", "tenant_finished")}
            fin = next((e for e in rows
                        if e.get("kind") == "tenant_finished"), None)
            bits = [f"evicted×{life['tenant_evicted']}"
                    if life["tenant_evicted"] else None,
                    f"resumed×{life['tenant_resumed']}"
                    if life["tenant_resumed"] else None]
            status = (f"{fin.get('status', 'finished')} at gen "
                      f"{fin.get('gen')}" if fin else "in flight")
            out.append("- " + ", ".join(
                [status] + [b for b in bits if b]))
            series = _meter_series(rows)
            if series:
                width = max(len(k) for k in series)
                for name in sorted(series):
                    vals = [v for _, v in series[name]]
                    out.append(
                        f"{name.ljust(width)}  {sparkline(vals)}  "
                        f"min={_fmt(min(vals))} "
                        f"max={_fmt(max(vals))} "
                        f"last={_fmt(vals[-1])}")
            alarms = [e for e in rows if e.get("kind") == "alarm"]
            for a in alarms:
                detail = ", ".join(
                    f"{k}={_fmt(v)}" for k, v in a.items()
                    if k not in ("kind", "t", "alarm", "gen",
                                 "tenant_id"))
                out.append(
                    f"- gen {a.get('gen')} ▲ **{a.get('alarm')}**"
                    + (f" ({detail})" if detail else ""))
    return True


def _fmt_bytes(n: Any) -> str:
    if not isinstance(n, (int, float)):
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return (f"{n:.0f} {unit}" if unit == "B"
                    else f"{n:.2f} {unit}")
        n /= 1024
    return "?"


def _program_table(events: List[Dict[str, Any]], out: List[str]
                   ) -> None:
    """The program-observatory plane: one row per ``program_profile``
    event — what XLA actually built (flops / bytes / compile time) and
    whether the donation contract held (aliased bytes)."""
    profiles = [e for e in events if e.get("kind") == "program_profile"]
    if not profiles:
        return
    out.append("")
    out.append(f"## Programs ({len(profiles)} compiled)")
    out.append("")
    out.append("| program | hlo | flops | bytes accessed | "
               "aliased (donated) | compile s |")
    out.append("|---|---|---|---|---|---|")
    for p in profiles:
        flops = p.get("flops")
        byt = p.get("bytes_accessed")
        aliased = p.get("aliased_bytes")
        don = " ▲ donating but 0 aliased" if (
            p.get("donating") and not aliased) else ""
        out.append(
            f"| {p.get('label')} | {str(p.get('hlo_hash'))[:8]} | "
            f"{_fmt(flops) if flops is not None else '?'} | "
            f"{_fmt_bytes(byt)} | {_fmt_bytes(aliased)}{don} | "
            f"{_fmt(p.get('compile_s'))} |")
    errors = [e for e in events
              if e.get("kind") == "program_profile_error"]
    for e in errors:
        out.append(f"- ▲ profile failed for {e.get('label')}: "
                   f"{e.get('error')}")
    drift = [e for e in events if e.get("kind") == "alarm"
             and e.get("alarm") == "hlo_drift"]
    for e in drift:
        out.append(f"- ▲ **hlo_drift**: {e.get('program')} recompiled "
                   f"{e.get('prev_hlo_hash')} → {e.get('hlo_hash')} "
                   "(same input signature — silent retrace regression)")


def _slo_section(events: List[Dict[str, Any]], out: List[str]) -> None:
    """Scheduler SLO timeline from the per-boundary ``slo`` samples:
    queue depth / occupancy / gens-per-sec sparklines per bucket plus
    the eviction ledger."""
    slos = [e for e in events if e.get("kind") == "slo"]
    if not slos:
        return
    buckets: Dict[str, List[Dict[str, Any]]] = {}
    for e in slos:
        buckets.setdefault(str(e.get("bucket", "?")), []).append(e)
    out.append("")
    out.append("## Scheduler SLO (per segment boundary)")
    for name in sorted(buckets):
        rows = buckets[name]
        out.append("")
        out.append(f"### bucket {name} ({len(rows)} segments)")
        for metric, label in (("queue_depth", "queue depth"),
                              ("occupancy", "occupancy"),
                              ("gens_per_sec", "gens/s")):
            vals = [e.get(metric) for e in rows
                    if isinstance(e.get(metric), (int, float))]
            if vals:
                out.append(f"{label.ljust(12)} {sparkline(vals)}  "
                           f"min={_fmt(min(vals))} "
                           f"max={_fmt(max(vals))} "
                           f"last={_fmt(vals[-1])}")
        waits = [e.get("segment_s") for e in rows
                 if isinstance(e.get("segment_s"), (int, float))]
        if waits:
            s = sorted(waits)
            out.append(
                f"segment wall  p50={_fmt(s[(len(s) - 1) // 2])}s "
                f"p99={_fmt(s[min(len(s) - 1, int(0.99 * (len(s) - 1)))])}s"
                f" max={_fmt(s[-1])}s")
    evicted = [e for e in events if e.get("kind") == "tenant_evicted"]
    resumed = [e for e in events if e.get("kind") == "tenant_resumed"]
    if evicted or resumed:
        out.append("")
        out.append(f"- swap ledger: {len(evicted)} eviction(s), "
                   f"{len(resumed)} resume(s)")
        for e in evicted[:10]:
            out.append(f"  - gen {e.get('gen')}: {e.get('tenant_id')} "
                       "evicted (checkpoint swap unit)")


def _loadgen_section(events: List[Dict[str, Any]], out: List[str]
                     ) -> None:
    """Load-observatory evidence: the ``loadgen_run`` rows (one per
    generated traffic run) and the ``slo_gate`` verdict table the run
    journaled next to them."""
    runs = [e for e in events if e.get("kind") == "loadgen_run"]
    gates = [e for e in events if e.get("kind") == "slo_gate"]
    if not (runs or gates):
        return
    out.append("")
    out.append("## Load observatory")
    _restart_keys = ("restart_t", "restart_ready_t",
                     "time_to_first_result_after_restart_s")
    for e in runs:
        tallies = ", ".join(
            f"{k}×{v}" for k, v in sorted(e.items())
            if k not in ("kind", "t", "model", "seed", "speed",
                         "n_arrivals", "planned_s", "wall_s")
            and k not in _restart_keys)
        out.append(f"- loadgen {e.get('model')} (seed "
                   f"{e.get('seed')}, ×{_fmt(e.get('speed', 1.0))}): "
                   f"{e.get('n_arrivals')} arrival(s) over "
                   f"{_fmt(e.get('wall_s'))}s "
                   f"(planned {_fmt(e.get('planned_s'))}s)"
                   + (f" — {tallies}" if tallies else ""))
        if e.get("restart_t") is not None:
            rt, ready = e.get("restart_t"), e.get("restart_ready_t")
            first = e.get("time_to_first_result_after_restart_s")
            outage = (_fmt(ready - rt)
                      if isinstance(ready, (int, float))
                      and isinstance(rt, (int, float)) else "?")
            out.append(
                f"  - restart drill: killed at t={_fmt(rt)}s, "
                f"serving again at t={_fmt(ready)}s "
                f"(outage {outage}s), first result "
                + (f"+{_fmt(first)}s after the kill"
                   if first is not None else
                   "never landed after the kill ▲"))
    if gates:
        bad = [g for g in gates if not g.get("ok")]
        out.append(f"- SLO gates: {len(gates) - len(bad)}/{len(gates)} "
                   "green" + (" — **breaches:**" if bad else ""))
        for g in bad:
            out.append(f"  - ▲ {g.get('slo')}: worst "
                       f"{_fmt(g.get('worst'))} > threshold "
                       f"{_fmt(g.get('threshold'))}")


def _startup_section(events: List[Dict[str, Any]], out: List[str]
                     ) -> None:
    """Startup ledger: the ``startup_phase`` waterfall a restarted
    service journals (wal_replay → restore → prewarm → first_result)
    plus the artifact-store hit/miss tally — together they answer
    "where did the cold start go" without attaching a profiler."""
    phases = [e for e in events if e.get("kind") == "startup_phase"]
    hits = [e for e in events if e.get("kind") == "artifact_hit"]
    misses = [e for e in events if e.get("kind") == "artifact_miss"]
    if not (phases or hits or misses):
        return
    out.append("")
    out.append("## Startup ledger")
    if phases:
        # journal order IS wall order (each phase notes its duration
        # as it completes); a bar per phase scaled to the longest
        longest = max(float(e.get("seconds", 0.0)) for e in phases)
        total = 0.0
        for e in phases:
            s = float(e.get("seconds", 0.0))
            total += s
            width = (int(round(s / longest * 24))
                     if longest > 0 else 0)
            out.append(f"- {str(e.get('phase', '?')).ljust(14)} "
                       f"{_fmt(s)}s {'█' * max(width, 1)}")
        out.append(f"- startup phases total: {_fmt(total)}s "
                   "(traffic was held until prewarm finished — "
                   "`/healthz` served 503 `warming`)")
    if hits or misses:
        n = len(hits) + len(misses)
        saved = sum(float(e.get("deserialize_s", 0.0)) for e in hits)
        out.append(f"- executable artifact store: {len(hits)}/{n} "
                   f"hit(s) ({_fmt(saved)}s deserializing instead of "
                   "compiling)")
        reasons: Dict[str, int] = {}
        for e in misses:
            r = str(e.get("reason", "?"))
            reasons[r] = reasons.get(r, 0) + 1
        if reasons:
            out.append("  - misses: " + ", ".join(
                f"{k}×{v}" for k, v in sorted(reasons.items())))


def _service_section(events: List[Dict[str, Any]], out: List[str]
                     ) -> None:
    """Service-plane timeline: the autoscaler's applied decisions
    (lane moves, prewarms, spills), the auth-rejection tally, the
    graceful-drain ledger — and the ISSUE 12 fault plane: WAL
    replays, idempotent-retry hits, deadline drops, load sheds,
    driver stalls and the request-id trace index."""
    decisions = [e for e in events
                 if e.get("kind") == "autoscale_decision"]
    rejections = [e for e in events
                  if e.get("kind") == "auth_rejected"]
    drains = [e for e in events if e.get("kind") == "service_drain"]
    wal = [e for e in events if e.get("kind") == "wal_replay"]
    idem = [e for e in events
            if e.get("kind") == "idempotent_replay"]
    deads = [e for e in events
             if e.get("kind") == "deadline_exceeded"]
    sheds = [e for e in events if e.get("kind") == "load_shed"]
    stalls = [e for e in events if e.get("kind") == "driver_stall"]
    traced = [e for e in events if e.get("request_id")]
    if not (decisions or rejections or drains or wal or idem
            or deads or sheds or stalls):
        return
    out.append("")
    out.append("## Service plane")
    if decisions:
        lanes = [e for e in decisions if e.get("action") == "lanes"]
        pw = [e for e in decisions if e.get("action") == "prewarm"]
        sp = [e for e in decisions if e.get("action") == "spill"]
        out.append(f"- autoscaler: {len(lanes)} lane move(s), "
                   f"{len(pw)} prewarm(s), {len(sp)} spill(s)")
        for e in lanes[:10]:
            out.append(f"  - t={e.get('t')}s {e.get('bucket')}: "
                       f"{e.get('lanes_from')} → {e.get('lanes_to')} "
                       f"lanes (queue={e.get('queue_depth')}, "
                       f"wait_p99={_fmt(e.get('queue_wait_p99'))})")
    if rejections:
        reasons: Dict[str, int] = {}
        for e in rejections:
            r = str(e.get("reason", "?"))
            reasons[r] = reasons.get(r, 0) + 1
        out.append("- auth rejections: " + ", ".join(
            f"{k}×{v}" for k, v in sorted(reasons.items())))
    for e in drains:
        out.append(f"- drain at t={e.get('t')}s: "
                   f"{len(e.get('checkpointed', []))} tenant(s) "
                   f"checkpointed, "
                   f"{len(e.get('open_tenants', []))} stream(s) "
                   "notified")
    for e in wal:
        out.append(f"- WAL replay at t={e.get('t')}s: "
                   f"{len(e.get('replayed', []))} tenant(s) replayed "
                   f"of {e.get('records', '?')} record(s)"
                   + (", torn tail healed"
                      if e.get("torn_tail") else "")
                   + (f", {len(e['failed'])} failed"
                      if e.get("failed") else ""))
    if idem or deads or sheds:
        out.append(f"- fault plane: {len(idem)} idempotent "
                   f"replay(s), {len(deads)} deadline drop(s), "
                   f"{len(sheds)} load shed(s)")
    if stalls:
        fired = [e for e in stalls if "stalled_s" in e]
        rec = [e for e in stalls if e.get("recovered")]
        worst = max((e["stalled_s"] for e in fired), default=None)
        out.append(f"- driver stalls: {len(fired)} fired / "
                   f"{len(rec)} recovered"
                   + (f" (worst {_fmt(worst)}s)" if worst else ""))
        for e in fired[:3]:
            tail = [ln for ln in str(e.get("stack", ""))
                    .strip().splitlines() if ln.strip()]
            out.append(f"  - t={e.get('t')}s stalled "
                       f"{_fmt(e.get('stalled_s'))}s at step "
                       f"{e.get('steps')}: "
                       f"{tail[-1].strip() if tail else '?'}")
    if traced:
        rids: Dict[str, int] = {}
        for e in traced:
            r = str(e.get("request_id"))
            rids[r] = rids.get(r, 0) + 1
        sample = next((r for r, n in rids.items() if n > 1),
                      next(iter(rids)))
        path = [str(e.get("kind")) for e in traced
                if str(e.get("request_id")) == sample]
        out.append(f"- request tracing: {len(traced)} row(s) across "
                   f"{len(rids)} request id(s); e.g. {sample}: "
                   + " → ".join(path[:8]))


def _memory_section(events: List[Dict[str, Any]], out: List[str]
                    ) -> None:
    """Flight-recorder device-memory trajectory: live device bytes per
    boundary as a sparkline, plus the captured trace/pprof artifact
    paths."""
    mems = [e for e in events if e.get("kind") == "device_memory"]
    traces = [e for e in events if e.get("kind") == "flight_trace"]
    if not mems and not traces:
        return
    out.append("")
    out.append("## Flight recorder")
    if mems:
        vals, steps = [], []
        for e in mems:
            live = e.get("live_bytes")
            if isinstance(live, dict):
                vals.append(sum(v for v in live.values()
                                if isinstance(v, (int, float))))
                steps.append(e.get("step"))
        if vals:
            out.append(
                f"device memory  {sparkline(vals)}  "
                f"min={_fmt_bytes(min(vals))} "
                f"max={_fmt_bytes(max(vals))} "
                f"last={_fmt_bytes(vals[-1])} "
                f"({len(vals)} boundary snapshots, steps "
                f"{steps[0]}–{steps[-1]})")
        pprofs = [e.get("profile_path") for e in mems
                  if e.get("profile_path")]
        if pprofs:
            out.append(f"- {len(pprofs)} pprof snapshot(s), first: "
                       f"{pprofs[0]}")
    for e in traces:
        out.append(f"- xplane trace of segment [{e.get('lo')}, "
                   f"{e.get('hi')}): {e.get('dir')}")


def _tuning_section(events: List[Dict[str, Any]], out: List[str]
                    ) -> None:
    """Tuning ledger — the dispatch tuner's journaled decisions
    (``tuning_decision``: per-key winner, decision source, probe cost,
    cache hits) and any drift evictions (``tuning_invalidation`` — a
    program recompiled to a different HLO, so its measured winners
    were discarded). Rendered for solo and multi-tenant journals
    alike: a stale or identity-failed dispatch choice is a
    whole-process property."""
    decisions = [e for e in events if e.get("kind") == "tuning_decision"]
    evictions = [e for e in events
                 if e.get("kind") == "tuning_invalidation"]
    if not decisions and not evictions:
        return
    out.append("")
    out.append("## Tuning ledger")
    out.append("")
    last: Dict[tuple, Dict[str, Any]] = {}
    hits: Dict[tuple, int] = {}
    for e in decisions:
        key = (str(e.get("knob", "?")), str(e.get("bucket", "")))
        last[key] = e
        if e.get("cache_hit"):
            hits[key] = hits.get(key, 0) + 1
    out.append("| knob | bucket | winner | source | probe s "
               "| cache hits |")
    out.append("|---|---|---|---|---|---|")
    for key in sorted(last):
        e = last[key]
        probe = e.get("probe_s")
        out.append(f"| {key[0]} | {key[1] or '—'} "
                   f"| {e.get('winner', '?')} | {e.get('source', '?')} "
                   f"| {_fmt(probe) if probe is not None else '—'} "
                   f"| {hits.get(key, 0)} |")
    failed = [e for e in decisions if e.get("identity") == "failed"]
    if failed:
        out.append(f"- ▲ {len(failed)} probe(s) failed the candidate "
                   "identity check — static default kept")
    for e in evictions:
        out.append(f"- drift eviction: {e.get('key')} (program "
                   f"{e.get('program')}, {e.get('reason')})")


def render_report(path: str, lines: Optional[List[str]] = None) -> str:
    """The full report as one string (also returned line-by-line into
    ``lines`` when given — bench_report prints as it renders)."""
    out: List[str] = [] if lines is None else lines
    events = _journal().read_journal(path)

    out.append(f"# Run report: {os.path.basename(path)}")
    out.append("")
    if getattr(events, "tear_offset", None) is not None:
        out.append(f"**torn tail** at byte {events.tear_offset} — the "
                   "writer was killed mid-line; rows below are the "
                   "complete prefix")
    if getattr(events, "skipped_offsets", None):
        out.append(f"{len(events.skipped_offsets)} malformed interior "
                   f"line(s) skipped (byte offsets "
                   f"{events.skipped_offsets[:5]}…)")

    header = next((e for e in events if e.get("kind") == "header"), None)
    if header is not None:
        env = header.get("env", {})
        out.append("- env: " + ", ".join(
            f"{k}={v}" for k, v in env.items()))
        if "toolbox" in header:
            out.append("- toolbox digest: "
                       f"{header['toolbox'].get('digest')}")
    runs = [e for e in events if e.get("kind") == "run_start"]
    if runs:
        out.append("- runs: " + ", ".join(
            str(e.get("algorithm", "?")) for e in runs))

    retraces = [e for e in events if e.get("kind") == "retrace"]
    compiles = [e for e in events if e.get("kind") == "compile"]
    line = (f"- compiles: {len(compiles)}"
            f", retraces after steady: {len(retraces)}")
    if retraces:
        line += (f" (**{sum(e.get('dur_s', 0.0) for e in retraces):.3f}s"
                 " recompiling — investigate shape/closure churn**)")
    out.append(line)

    # which execution the variation plane resolved to (fused kernel /
    # fused XLA / unfused composition; GP compaction device vs host) —
    # a fallback here is the run silently not using the fast path
    dispatches = [e for e in events
                  if e.get("kind") == "variation_dispatch"]
    if dispatches:
        counts: dict = {}
        for e in dispatches:
            key = (str(e.get("op", "?")), str(e.get("path", "?")))
            counts[key] = counts.get(key, 0) + 1
        out.append("- variation dispatch: " + ", ".join(
            f"{op}→{path}×{c}"
            for (op, path), c in sorted(counts.items())))
        fallbacks = [e for e in dispatches if e.get("path") == "unfused"
                     and e.get("reason") not in (None, "disabled")]
        if fallbacks:
            out.append(f"  - ▲ {len(fallbacks)} fused-plane fallback(s):"
                       f" {fallbacks[0].get('reason')}")

    # ------------------------------------------------- tuning ledger ----
    _tuning_section(events, out)

    # ----------------------------------------- multi-tenant journals ----
    if _tenant_sections(events, out):
        # per-tenant blocks replace the single-run meter/alarm
        # sections (which would interleave tenants); the scheduler-
        # wide planes (SLO timeline, compiled programs, flight
        # recorder) and the summary still apply to the process
        _slo_section(events, out)
        _loadgen_section(events, out)
        _startup_section(events, out)
        _service_section(events, out)
        _program_table(events, out)
        _memory_section(events, out)
        summary = next((e for e in reversed(events)
                        if e.get("kind") == "summary"), None)
        if summary is not None:
            out.append("")
            out.append("## Summary")
            out.append("- " + ", ".join(
                f"{k}={_fmt(v)}" for k, v in summary.items()
                if k not in ("kind", "t")))
        return "\n".join(out)

    # ------------------------------------------------ probe sparklines ----
    series = _meter_series(events)
    if series:
        out.append("")
        out.append("## Metrics (per generation)")
        out.append("")
        width = max(len(k) for k in series)
        for name in sorted(series):
            pts = series[name]
            vals = [v for _, v in pts]
            out.append(f"{name.ljust(width)}  {sparkline(vals)}  "
                       f"min={_fmt(min(vals))} max={_fmt(max(vals))} "
                       f"last={_fmt(vals[-1])}")

    # ------------------------------------------- resilience timeline ----
    segs = [e for e in events if e.get("kind") == "segment"]
    resumed = [e for e in events if e.get("kind") == "resumed"]
    preempted = [e for e in events if e.get("kind") == "preempted"]
    degraded = [e for e in events if e.get("kind") == "degraded"]
    corrupt = [e for e in events
               if e.get("kind") == "checkpoint_corrupt"]
    quarantine = [e for e in events if e.get("kind") == "quarantine"]
    if segs or resumed or preempted or degraded or corrupt:
        out.append("")
        out.append("## Resilience (segments / recoveries)")
        out.append("")
        if resumed:
            # run-id chaining: each resume names the run it continues,
            # so a preempted run's journals stitch into one timeline
            chain = " → ".join(
                [str(resumed[0].get("resumed_from"))]
                + [str(e.get("run_id")) for e in resumed])
            out.append(f"- run chain: {chain}")
            for e in resumed:
                out.append(f"- resumed at gen {e.get('step')} from run "
                           f"{e.get('resumed_from')}")
        if segs:
            lo = min(e.get("lo", 0) for e in segs)
            hi = max(e.get("hi", 0) for e in segs)
            out.append(f"- {len(segs)} segment(s) covering gens "
                       f"[{lo}, {hi}]")
        for e in preempted:
            out.append(f"- ▲ **preempted** at gen {e.get('step')} "
                       f"(signal {e.get('signum')}) — checkpoint saved, "
                       "clean exit")
        for e in degraded:
            out.append(
                f"- ▲ **degraded** segment [{e.get('lo')}, "
                f"{e.get('hi')}): {e.get('error_kind')} attempt "
                f"{e.get('attempt')}, backoff {e.get('backoff_s')}s"
                + (f", action: {e['action']}" if e.get("action") else ""))
        for e in corrupt:
            out.append(f"- ▲ **corrupt checkpoint** skipped: "
                       f"{os.path.basename(str(e.get('path', '?')))}")
        if quarantine:
            total = sum(e.get("n", 0) for e in quarantine)
            out.append(f"- {total} non-finite evaluation(s) quarantined "
                       f"across {len(quarantine)} event(s)")

    hv = [e for e in events if e.get("kind") == "hv_exact"]
    if hv:
        out.append("")
        out.append("## Exact hypervolume samples (host, native)")
        for e in hv:
            out.append(f"- gen {e.get('gen')}: {_fmt(e.get('value'))} "
                       f"({e.get('n_points')} sampled points)")

    # ----------------------------------------------------- alarm timeline ----
    alarms = [e for e in events if e.get("kind") == "alarm"]
    out.append("")
    out.append(f"## Alarms ({len(alarms)})")
    out.append("")
    if alarms:
        for a in alarms:
            detail = ", ".join(
                f"{k}={_fmt(v)}" for k, v in a.items()
                if k not in ("kind", "t", "alarm", "gen"))
            out.append(f"- gen {a.get('gen')} ▲ **{a.get('alarm')}**"
                       + (f" ({detail})" if detail else ""))
    else:
        out.append("- none — no tripwire fired (or no HealthMonitor "
                   "was attached)")

    # --------------------------------------------------------- span table ----
    spans = [e for e in events if e.get("kind") == "span"]
    if spans:
        out.append("")
        out.append("## Spans (host wall time)")
        out.append("")
        out.append("| span | count | total s | p50 s | p99 s |")
        out.append("|---|---|---|---|---|")
        for s in sorted(spans, key=lambda s: -s.get("total_s", 0)):
            out.append(
                f"| {s.get('name')} | {s.get('count')} | "
                f"{s.get('total_s', 0):.6f} | {s.get('p50_s', 0):.6f} | "
                f"{s.get('p99_s', 0):.6f} |")

    summary = next((e for e in reversed(events)
                    if e.get("kind") == "summary"), None)
    if summary is not None:
        out.append("")
        out.append("## Summary")
        out.append("- " + ", ".join(
            f"{k}={_fmt(v)}" for k, v in summary.items()
            if k not in ("kind", "t")))
    return "\n".join(out)


# ------------------------------------------------- trace waterfall ----

_BAR_WIDTH = 40  # terminal budget for the waterfall gutter


def _trace_groups(path: str):
    """All generations of the journal at ``path`` (rotated ``.N``
    predecessors from kill-9 restarts, oldest first, then the live
    file) parsed into ``(header_row_or_None, rows)`` pairs — the
    shape :func:`tracing.assemble_trace` stitches across."""
    jm = _journal()
    groups = []
    for p in jm.journal_generations(path):
        rows = jm.read_journal(p, strict=False)
        header = next((e for e in rows
                       if e.get("kind") == "header"), None)
        groups.append((header, rows))
    return groups


def _resolve_request_id(groups, ident: str) -> Optional[str]:
    """``--trace`` accepts either a request id or a tenant id; tenant
    ids resolve through the ``job_submitted``/``trace_span`` rows that
    carry both."""
    for _, rows in groups:
        for e in rows:
            if e.get("request_id") == ident:
                return ident
    for _, rows in groups:
        for e in rows:
            if (e.get("tenant_id") == ident and e.get("request_id")):
                return str(e["request_id"])
    return None


def _waterfall(spans: List[Dict[str, Any]], out: List[str]) -> None:
    lo = min(s["start"] for s in spans)
    hi = max(s["end"] for s in spans)
    total = max(hi - lo, 1e-9)
    name_w = max(len(str(s.get("name", "?"))) for s in spans)
    for s in spans:
        a = int((s["start"] - lo) / total * _BAR_WIDTH)
        b = int((s["end"] - lo) / total * _BAR_WIDTH)
        b = max(b, a + 1)
        bar = " " * a + "█" * (b - a) + " " * (_BAR_WIDTH - b)
        extra = []
        if s.get("phase"):
            extra.append(str(s["phase"]))
        if s.get("tenant_id"):
            extra.append(f"tenant={s['tenant_id']}")
        if s.get("hlo_hash"):
            extra.append(f"hlo={str(s['hlo_hash'])[:8]}")
        if s.get("gen") is not None:
            extra.append(f"gen={s['gen']}")
        if s.get("synthetic"):
            extra.append("synthetic")
        for link in s.get("links") or []:
            if isinstance(link, dict) and link.get("xplane_dir"):
                extra.append(f"xplane={link['xplane_dir']}")
        out.append(
            f"{str(s.get('name', '?')).ljust(name_w)} |{bar}| "
            f"+{s['start'] - lo:8.3f}s {s.get('dur_s', 0.0):9.4f}s"
            + (f"  ({', '.join(extra)})" if extra else ""))


def render_trace(path: str, ident: str,
                 perfetto_out: Optional[str] = None) -> str:
    """The span waterfall for one request (or tenant) id, stitched
    across every generation of the journal at ``path`` — the
    ``report.py --trace`` view. With ``perfetto_out`` the assembled
    spans are also written as Chrome/Perfetto trace-event JSON."""
    tr = _tracing()
    if os.path.isdir(path):
        path = os.path.join(path, "journal.jsonl")
    groups = _trace_groups(path)
    out: List[str] = []
    rid = _resolve_request_id(groups, ident)
    if rid is None:
        return (f"no journal row carries request or tenant id "
                f"{ident!r} under {path} — was the service started "
                "with trace_sample set?")
    trace = tr.assemble_trace(groups, tr.trace_id_for(rid))
    spans = trace["spans"]
    if not spans:
        return (f"request {rid}: no trace_span rows for trace "
                f"{trace['trace_id']} — was trace_sample set?")

    out.append(f"# Trace {trace['trace_id']}")
    out.append("")
    out.append(f"- request id: {rid}")
    if ident != rid:
        out.append(f"- resolved from tenant id: {ident}")
    if len(groups) > 1:
        out.append(f"- stitched across {len(groups)} journal "
                   "generation(s) (restart/rotation)")
    lo = min(s["start"] for s in spans)
    hi = max(s["end"] for s in spans)
    out.append(f"- {len(spans)} span(s), {hi - lo:.3f}s end to end")
    if trace["orphans"]:
        out.append(f"- ▲ {len(trace['orphans'])} orphan span(s) "
                   "(parent row missing — lost journal generation?)")
    out.append("")
    out.append("## Waterfall")
    out.append("")
    _waterfall(spans, out)

    # per-phase latency decomposition: where the request's wall time
    # actually went (phases overlap the root span, so the column sums
    # against the end-to-end wall, not to it)
    phases: Dict[str, List[float]] = {}
    for s in spans:
        if s.get("phase"):
            phases.setdefault(str(s["phase"]), []).append(
                float(s.get("dur_s", 0.0) or 0.0))
    if phases:
        out.append("")
        out.append("## Phase latency")
        out.append("")
        out.append("| phase | spans | total s | % of wall |")
        out.append("|---|---|---|---|")
        order = list(getattr(tr, "PHASES", ())) + sorted(
            k for k in phases if k not in getattr(tr, "PHASES", ()))
        wall = max(hi - lo, 1e-9)
        for ph in order:
            if ph not in phases:
                continue
            tot = sum(phases[ph])
            out.append(f"| {ph} | {len(phases[ph])} | {tot:.4f} | "
                       f"{100.0 * tot / wall:.1f}% |")

    if perfetto_out:
        tr.write_perfetto(perfetto_out, spans)
        out.append("")
        out.append(f"- perfetto export: {perfetto_out} "
                   "(open at ui.perfetto.dev)")
    return "\n".join(out)


def _fmt_opt(v: Any) -> str:
    return "—" if v is None else _fmt(v)


def render_slo(path: str, window_s: float = 1.0) -> str:
    """The windowed SLO-curve table + gate verdicts for one journal —
    the ``report.py --slo`` view (stdlib-only, like the health
    report)."""
    sl = _slo()
    if os.path.isdir(path):
        path = os.path.join(path, "journal.jsonl")
    events = _journal().read_journal(path)
    curve = sl.windowed_curve(events, window_s=window_s)
    out: List[str] = []
    out.append(f"# SLO curves: {os.path.basename(path)}")
    out.append("")
    if not curve:
        out.append("- no timestamped rows — nothing to window")
        return "\n".join(out)
    out.append(f"- {len(curve)} window(s) of {_fmt(window_s)}s")
    out.append("")
    out.append("| window | arrivals/s | shed | ddl miss | adm p99 s "
               "| wait p99 s | seg p99 s |")
    out.append("|---|---|---|---|---|---|---|")
    for w in curve:
        out.append(
            f"| {_fmt(w['t0'])}–{_fmt(w['t1'])} "
            f"| {_fmt(w['arrival_rate'])} "
            f"| {_fmt(w['shed_rate'])} "
            f"| {_fmt(w['deadline_miss_rate'])} "
            f"| {_fmt_opt(w['admission_p99'])} "
            f"| {_fmt_opt(w['queue_wait_p99'])} "
            f"| {_fmt_opt(w['segment_p99'])} |")
    out.append("")
    out.append("## Gates (worst window vs threshold)")
    out.append("")
    out.append("| gate | metric | threshold | worst | verdict |")
    out.append("|---|---|---|---|---|")
    for g in sl.evaluate_gates(curve):
        out.append(f"| {g['slo']} | {g['metric']} "
                   f"| {_fmt(g['threshold'])} | {_fmt_opt(g['worst'])} "
                   f"| {'ok' if g['ok'] else '**FAIL**'} |")
    drills = [e for e in events if e.get("kind") == "loadgen_run"
              and e.get("restart_t") is not None]
    if drills:
        out.append("")
        out.append("## Restart drill")
        out.append("")
        for e in drills:
            first = e.get("time_to_first_result_after_restart_s")
            out.append(
                f"- {e.get('model')}: service killed at "
                f"t={_fmt(e.get('restart_t'))}s, serving again at "
                f"t={_fmt_opt(e.get('restart_ready_t'))}s; first "
                "result landed "
                + (f"{_fmt(first)}s after the kill"
                   if first is not None
                   else "**never** after the kill"))
    return "\n".join(out)


def render_attribution(base_path: str, probe_path: str,
                       q: float = 0.99) -> str:
    """Per-phase regression attribution between two journals (base,
    probe) — the two-journal form of ``report.py --slo``."""
    sl = _slo()
    jm = _journal()
    paths = []
    for p in (base_path, probe_path):
        if os.path.isdir(p):
            p = os.path.join(p, "journal.jsonl")
        paths.append(p)
    base = jm.read_journal(paths[0])
    probe = jm.read_journal(paths[1])
    att = sl.attribute_regression(base, probe, q=q)
    out: List[str] = []
    out.append(f"# Regression attribution (p{int(q * 100)}): "
               f"{os.path.basename(paths[0])} → "
               f"{os.path.basename(paths[1])}")
    out.append("")
    out.append(f"- end to end: {_fmt_opt(att['end_to_end_base'])}s → "
               f"{_fmt_opt(att['end_to_end_probe'])}s "
               f"(Δ {_fmt_opt(att['end_to_end_delta'])}s)")
    if att["top_phase"]:
        out.append(f"- **top regressing phase: {att['top_phase']} "
                   f"+{_fmt(att['top_delta_s'])}s**")
    else:
        out.append("- no phase regressed")
    if att["phases"]:
        out.append("")
        out.append("| phase | base s | probe s | Δ s | n base "
                   "| n probe |")
        out.append("|---|---|---|---|---|---|")
        for row in att["phases"]:
            out.append(f"| {row['phase']} | {_fmt_opt(row['base_q'])} "
                       f"| {_fmt_opt(row['probe_q'])} "
                       f"| {_fmt(row['delta_s'])} | {row['n_base']} "
                       f"| {row['n_probe']} |")
    else:
        out.append("- no trace_span rows in either journal — run the "
                   "service with trace_sample set")
    return "\n".join(out)


def render_fleet(root: str, window_s: float = 1.0) -> str:
    """The fleet observatory view (``report.py --fleet``): every
    registered process's journal generations merged into one
    monotonic-rebased timeline, with per-process health columns, the
    fleet-wide SLO curve, and the traces that crossed a process
    boundary (stdlib-only, like every other view)."""
    fed = _federation()
    summary = fed.fleet_summary(root, window_s=window_s)
    procs: Dict[str, Any] = summary["processes"]
    rows = summary["rows"]
    out: List[str] = []
    out.append(f"# Fleet: {os.path.abspath(root)}")
    out.append("")
    if not procs:
        out.append("- no registered processes under this root "
                   "(expected <root>/<process_id>/journal.jsonl)")
        return "\n".join(out)
    timed = [r for r in rows if r.get("wall") is not None]
    span = ((max(r["wall"] for r in timed)
             - min(r["wall"] for r in timed)) if timed else 0.0)
    out.append(f"- {len(procs)} process(es), {len(rows)} merged "
               f"rows, {_fmt(span)}s of fleet timeline")
    out.append("")
    out.append("## Processes")
    out.append("")
    out.append("| process | gens | rows | tears | alarms | stalls "
               "| canary ok/fail | sheds | ddl miss | firing alerts |")
    out.append("|---|---|---|---|---|---|---|---|---|---|")
    for pid in sorted(procs):
        h = procs[pid]
        flags = []
        if h["missing_headers"]:
            flags.append(f"▲{h['missing_headers']} headerless")
        alarm_n = sum(h["alarms"].values())
        firing = ", ".join(h["firing_alerts"]) if h["firing_alerts"] \
            else "—"
        out.append(
            f"| {pid}{' ' + ' '.join(flags) if flags else ''} "
            f"| {h['generations']} | {h['rows']} | {h['torn_tails']} "
            f"| {alarm_n} | {h['driver_stalls']} "
            f"| {h['canary_ok']}/{h['canary_failed']} "
            f"| {h['load_sheds']} | {h['deadline_misses']} "
            f"| {firing} |")
    alarm_kinds: Dict[str, int] = {}
    for h in procs.values():
        for k, n in h["alarms"].items():
            alarm_kinds[k] = alarm_kinds.get(k, 0) + n
    if alarm_kinds:
        out.append("")
        out.append("- fleet alarms: " + ", ".join(
            f"{k}×{n}" for k, n in sorted(alarm_kinds.items())))

    curve = summary["curve"]
    if curve:
        out.append("")
        out.append("## Fleet SLO curve")
        out.append("")
        out.append(f"- {len(curve)} window(s) of {_fmt(window_s)}s "
                   "over the merged timeline")
        out.append("")
        out.append("| window | arrivals/s | shed | ddl miss "
                   "| adm p99 s | wait p99 s | seg p99 s |")
        out.append("|---|---|---|---|---|---|---|")
        for w in curve:
            out.append(
                f"| {_fmt(w['t0'])}–{_fmt(w['t1'])} "
                f"| {_fmt(w['arrival_rate'])} "
                f"| {_fmt(w['shed_rate'])} "
                f"| {_fmt(w['deadline_miss_rate'])} "
                f"| {_fmt_opt(w['admission_p99'])} "
                f"| {_fmt_opt(w['queue_wait_p99'])} "
                f"| {_fmt_opt(w['segment_p99'])} |")
        out.append("")
        out.append("## Fleet gates (worst window vs threshold)")
        out.append("")
        out.append("| gate | metric | threshold | worst | verdict |")
        out.append("|---|---|---|---|---|")
        for g in _slo().evaluate_gates(curve):
            out.append(
                f"| {g['slo']} | {g['metric']} "
                f"| {_fmt(g['threshold'])} | {_fmt_opt(g['worst'])} "
                f"| {'ok' if g['ok'] else '**FAIL**'} |")

    xt = summary["cross_traces"]
    out.append("")
    out.append("## Cross-process traces")
    out.append("")
    if not xt:
        out.append("- none (no trace id spans more than one member — "
                   "single process, or trace_sample unset)")
    else:
        for rec in xt[:10]:
            rid = rec.get("request_id")
            out.append(
                f"- `{rec['trace_id']}`: {rec['spans']} span(s) "
                f"across {', '.join(rec['processes'])}"
                + (f" (request {rid})" if rid else ""))
        if len(xt) > 10:
            out.append(f"- … and {len(xt) - 10} more")
        top = xt[0]
        ident = top.get("request_id")
        if ident:
            trace = fed.fleet_trace(root, ident)
            if trace and trace["spans"]:
                out.append("")
                out.append(f"### Waterfall: request {ident} "
                           f"({', '.join(trace['processes'])})")
                out.append("")
                _waterfall(trace["spans"], out)
    return "\n".join(out)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    trace_id = perfetto = None
    if "--trace" in argv:
        i = argv.index("--trace")
        if i + 1 >= len(argv):
            print("usage: report.py --trace <request-id|tenant-id> "
                  "[--perfetto out.json] <journal.jsonl|run-dir>",
                  file=sys.stderr)
            return 2
        trace_id = argv[i + 1]
        del argv[i:i + 2]
    if "--perfetto" in argv:
        i = argv.index("--perfetto")
        if i + 1 >= len(argv):
            print("--perfetto needs an output path", file=sys.stderr)
            return 2
        perfetto = argv[i + 1]
        del argv[i:i + 2]
    slo_view = "--slo" in argv
    if slo_view:
        argv.remove("--slo")
    fleet_view = "--fleet" in argv
    if fleet_view:
        argv.remove("--fleet")
    watch_s = None
    if "--watch" in argv:
        i = argv.index("--watch")
        # optional interval value; defaults to 2 s
        if i + 1 < len(argv) and not argv[i + 1].startswith("-"):
            try:
                watch_s = float(argv[i + 1])
                del argv[i:i + 2]
            except ValueError:
                watch_s = 2.0
                del argv[i:i + 1]
        else:
            watch_s = 2.0
            del argv[i:i + 1]
    window_s = 1.0
    if "--window" in argv:
        i = argv.index("--window")
        if i + 1 >= len(argv):
            print("--window needs a seconds value", file=sys.stderr)
            return 2
        window_s = float(argv[i + 1])
        del argv[i:i + 2]
    paths = [a for a in argv if not a.startswith("-")]
    if not paths:
        print("usage: report.py [--trace <request-id|tenant-id> "
              "[--perfetto out.json]] [--slo [--window s]] "
              "[--fleet [--watch [s]]] "
              "<journal.jsonl|fleet-root> [...]",
              file=sys.stderr)
        return 2
    if fleet_view:
        import time as _time
        while True:
            text = "\n\n".join(render_fleet(p, window_s=window_s)
                               for p in paths)
            if watch_s is not None:
                # live refresh: clear screen + home, rerender
                sys.stdout.write("\x1b[2J\x1b[H")
            print(text)
            if watch_s is None:
                return 0
            sys.stdout.flush()
            try:
                _time.sleep(watch_s)
            except KeyboardInterrupt:
                return 0
    if slo_view:
        # one journal: windowed curves + gates; two journals:
        # curves for each, then base → probe attribution
        for p in paths:
            print(render_slo(p, window_s=window_s))
        if len(paths) == 2:
            print()
            print(render_attribution(paths[0], paths[1]))
        return 0
    for p in paths:
        if trace_id is not None:
            print(render_trace(p, trace_id, perfetto_out=perfetto))
        else:
            print(render_report(p))
    return 0


if __name__ == "__main__":
    sys.exit(main())
