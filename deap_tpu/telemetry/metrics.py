"""Host-side serving metrics registry + Prometheus text exposition.

The third observability plane's *external* surface: while the journal
(:mod:`~deap_tpu.telemetry.journal`) is one run's append-only history,
this registry is the **current state** of a serving process — queue
depths, lane occupancy, per-tenant throughput, segment/checkpoint
latency distributions — exported in the Prometheus text exposition
format (``metrics_text``) and optionally served over HTTP
(:func:`serve_metrics`, a stdlib-only ``/metrics`` endpoint). This is
the first externally scrapeable surface of the stack and the opening
move toward the RPC front end (ROADMAP item 1): an operator pointing
Prometheus at a :class:`~deap_tpu.serving.scheduler.Scheduler` gets
per-bucket SLO series with zero extra wiring.

Like :mod:`~deap_tpu.telemetry.report`, this module imports **nothing
but the standard library** — scraping a metrics snapshot must never
initialise an XLA backend (``tests/test_metrics.py`` pins the no-jax
guarantee by loading the file standalone in a subprocess).

Three instrument kinds, the Prometheus trio:

- :class:`Counter` — monotone totals (evictions, resumes, retries);
- :class:`Gauge` — set-to-current values (queue depth, occupancy,
  per-tenant gens/s);
- :class:`Histogram` — cumulative-bucket latency distributions
  (queue-wait, segment and checkpoint seconds) with exact
  ``_sum``/``_count`` series, so p50/p99 are recoverable by any
  Prometheus-compatible consumer.

All instruments take label sets at observation time::

    reg = MetricsRegistry()
    depth = reg.gauge("deap_serving_queue_depth",
                      "jobs waiting per bucket", labels=("bucket",))
    depth.set(3, bucket="onemax/16")
    print(reg.metrics_text())

Thread safety: one lock per registry — the scheduler's driver thread
and the HTTP server thread share instruments safely.
"""

from __future__ import annotations

import http.server
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "HistogramSnapshot",
           "MetricsRegistry", "MetricsServer", "SERVING_PHASE_BUCKETS",
           "SERVING_SEGMENT_BUCKETS", "SERVING_WAIT_BUCKETS",
           "alarms_total", "alert_state_gauge", "get_registry",
           "metrics_text", "phase_histogram", "serve_metrics",
           "startup_phase_histogram"]

#: default histogram bucket bounds (seconds) — spans sub-ms host work
#: to multi-minute compiles; ``+Inf`` is implicit
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                   1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

# ---- per-metric serving bucket overrides (ISSUE 17 satellite) ----
# BENCH_SERVICE.json measured queue-wait p99 at 14.2 s under the
# bursty pair — with DEFAULT_BUCKETS every observation past 10 s
# collapses into the 30 s bucket and a windowed p99 reads "30.0" for
# anything between 10.001 and 30 s. These tuples keep bucket-
# resolution percentiles finite and useful across the measured burst
# range (and well past it: abandoned-tenant waits can reach minutes
# before the autoscaler spills them).

#: queue-wait / admission latency (seconds): dense through the
#: measured 10–60 s burst range, finite out to 10 minutes
SERVING_WAIT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                        1.0, 2.5, 5.0, 10.0, 15.0, 20.0, 30.0, 45.0,
                        60.0, 90.0, 120.0, 300.0, 600.0)

#: scheduler segment wall seconds: sub-ms device steps through
#: fault-injected multi-second stalls (DelaySegment) without
#: saturating
SERVING_SEGMENT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                           0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 20.0,
                           30.0, 60.0, 120.0, 300.0)

#: per-phase request latency (tracing plane): spans sub-ms WAL
#: fsyncs to multi-minute compiles and burst queue waits
SERVING_PHASE_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
                         0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
                         15.0, 30.0, 60.0, 120.0, 300.0, 600.0)

_NAME_OK = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape_label(value: str) -> str:
    """Prometheus label-value escaping: backslash, quote, newline."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_value(v: float) -> str:
    """Exposition-format float: integers render bare, specials render
    as +Inf/-Inf/NaN per the text format."""
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if math.isnan(v):
            return "NaN"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)
    return str(v)


def _labels_key(declared: Tuple[str, ...], given: Dict[str, str]
                ) -> Tuple[str, ...]:
    extra = set(given) - set(declared)
    missing = set(declared) - set(given)
    if extra or missing:
        raise ValueError(
            f"label mismatch: declared {declared}, got {tuple(given)}")
    return tuple(str(given[k]) for k in declared)


def _render_labels(declared: Sequence[str], key: Sequence[str],
                   extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(declared, key)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Instrument:
    """Shared plumbing: name/help/type, declared label names, one
    child per observed label-value tuple."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labels: Sequence[str],
                 lock: threading.Lock):
        self.name = _check_name(name)
        self.help = str(help)
        self.labels = tuple(str(label) for label in labels)
        self._lock = lock
        self._children: Dict[Tuple[str, ...], object] = {}

    def _child(self, given: Dict[str, str], default):
        key = _labels_key(self.labels, given)
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = default()
        return child

    def samples(self) -> Iterable[Tuple[str, str, float]]:
        """``(suffix, label-block, value)`` rows — exposition order."""
        raise NotImplementedError

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}",
               f"# TYPE {self.name} {self.kind}"]
        for suffix, block, value in self.samples():
            out.append(f"{self.name}{suffix}{block} {_fmt_value(value)}")
        return out


class Counter(_Instrument):
    """Monotone total. ``inc`` only — decreasing a counter is a bug the
    registry refuses to allow."""

    kind = "counter"

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        if amount < 0:
            raise ValueError("counters only increase")
        with self._lock:
            child = self._child(labels, lambda: [0.0])
            child[0] += amount

    def value(self, **labels: str) -> float:
        with self._lock:
            key = _labels_key(self.labels, labels)
            child = self._children.get(key)
            return float(child[0]) if child else 0.0

    def samples(self):
        # copy under the lock, render outside it: a concurrent inc()
        # creating a new label child must not blow up ("dictionary
        # changed size during iteration") mid-scrape — the exposition
        # path used to iterate _children unlocked (ISSUE 19 satellite;
        # hammer-tested by tests/test_alerts.py)
        with self._lock:
            items = [(key, self._children[key][0])
                     for key in sorted(self._children)]
        for key, value in items:
            yield "", _render_labels(self.labels, key), value


class Gauge(_Instrument):
    """Set-to-current value (queue depth, occupancy, gens/s)."""

    kind = "gauge"

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._child(labels, lambda: [0.0])[0] = float(value)

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        with self._lock:
            self._child(labels, lambda: [0.0])[0] += amount

    def dec(self, amount: float = 1.0, **labels: str) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            key = _labels_key(self.labels, labels)
            child = self._children.get(key)
            return float(child[0]) if child else 0.0

    def samples(self):
        with self._lock:   # see Counter.samples
            items = [(key, self._children[key][0])
                     for key in sorted(self._children)]
        for key, value in items:
            yield "", _render_labels(self.labels, key), value


class _HistChild:
    __slots__ = ("counts", "total", "n")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.total = 0.0
        self.n = 0


class HistogramSnapshot:
    """A point-in-time copy of one histogram child's cumulative state
    — the windowed-percentile primitive (ISSUE 17).

    Prometheus histograms are cumulative: ``counts``/``total``/``n``
    only ever grow, so a quantile over the raw child mixes every
    observation since process start. Subtracting two snapshots
    (:meth:`delta`) yields the distribution of exactly the
    observations that landed *between* them, and :meth:`quantile` on
    the delta is the windowed percentile that SLO curves
    (:mod:`deap_tpu.telemetry.slo`) gate on."""

    __slots__ = ("buckets", "counts", "total", "n")

    def __init__(self, buckets: Tuple[float, ...],
                 counts: Sequence[int], total: float, n: int):
        self.buckets = tuple(buckets)
        self.counts = tuple(counts)
        self.total = float(total)
        self.n = int(n)

    def delta(self, earlier: "HistogramSnapshot") -> "HistogramSnapshot":
        """The observations between ``earlier`` and ``self`` (both
        snapshots of the same histogram child, ``earlier`` taken
        first)."""
        if self.buckets != earlier.buckets:
            raise ValueError("snapshot bucket bounds differ — not the "
                             "same histogram")
        return HistogramSnapshot(
            self.buckets,
            [a - b for a, b in zip(self.counts, earlier.counts)],
            self.total - earlier.total, self.n - earlier.n)

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile over this snapshot's (or
        delta's) observations; ``None`` when empty, ``+Inf`` past the
        top finite bucket — same contract as
        :meth:`Histogram.quantile`."""
        if self.n <= 0:
            return None
        rank = q * self.n
        for bound, c in zip(self.buckets, self.counts):
            if c >= rank:
                return bound
        return float("inf")

    def mean(self) -> Optional[float]:
        return self.total / self.n if self.n > 0 else None


class Histogram(_Instrument):
    """Cumulative-bucket distribution with exact sum/count. Buckets are
    upper bounds (``le``); the ``+Inf`` bucket is implicit and always
    equals ``_count``, per the exposition format."""

    kind = "histogram"

    def __init__(self, name, help, labels, lock,
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labels, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")

    def observe(self, value: float, **labels: str) -> None:
        value = float(value)
        with self._lock:
            child = self._child(
                labels, lambda: _HistChild(len(self.buckets)))
            for i, bound in enumerate(self.buckets):
                if value <= bound:
                    child.counts[i] += 1
            child.total += value
            child.n += 1

    def quantile(self, q: float, **labels: str) -> Optional[float]:
        """Bucket-resolution quantile (the upper bound of the bucket
        the q-th observation falls in) — the host-side twin of the
        PromQL ``histogram_quantile`` the exported series feed."""
        with self._lock:
            key = _labels_key(self.labels, labels)
            child = self._children.get(key)
            if child is None or child.n == 0:
                return None
            rank = q * child.n
            for bound, c in zip(self.buckets, child.counts):
                if c >= rank:
                    return bound
            return float("inf")

    def snapshot(self, **labels: str) -> HistogramSnapshot:
        """A consistent point-in-time copy of one child's cumulative
        state. An unobserved label set snapshots as all-zero (so
        ``later.delta(earlier)`` works uniformly across children that
        appear mid-window)."""
        with self._lock:
            key = _labels_key(self.labels, labels)
            child = self._children.get(key)
            if child is None:
                return HistogramSnapshot(
                    self.buckets, [0] * len(self.buckets), 0.0, 0)
            return HistogramSnapshot(self.buckets, list(child.counts),
                                     child.total, child.n)

    def label_sets(self) -> List[Dict[str, str]]:
        """The label sets observed so far — e.g. every ``phase`` the
        tracing plane has fed ``deap_service_phase_seconds``."""
        with self._lock:
            return [dict(zip(self.labels, key))
                    for key in sorted(self._children)]

    def samples(self):
        # consistent per-child copy under the lock (see
        # Counter.samples): a mid-copy observe would otherwise tear a
        # child's counts/total/n apart across the exposition
        with self._lock:
            items = [(key, list(child.counts), child.total, child.n)
                     for key, child in sorted(self._children.items())]
        for key, counts, total, n in items:
            for bound, c in zip(self.buckets, counts):
                yield "_bucket", _render_labels(
                    self.labels, key, f'le="{_fmt_value(bound)}"'), c
            yield "_bucket", _render_labels(self.labels, key,
                                            'le="+Inf"'), n
            yield "_sum", _render_labels(self.labels, key), total
            yield "_count", _render_labels(self.labels, key), n


class MetricsRegistry:
    """One process's (or one scheduler's) instrument set.

    Instruments are create-or-get by name: calling :meth:`counter`
    twice with one name returns the same instrument (with a type/label
    mismatch raising), so subsystems can declare their metrics
    independently and still share a registry.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def _get_or_make(self, cls, name, help, labels, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls) or \
                        inst.labels != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} re-declared as {cls.__name__}"
                        f"{tuple(labels)} (was {type(inst).__name__}"
                        f"{inst.labels})")
                want = kw.get("buckets")
                if want is not None and isinstance(inst, Histogram) \
                        and inst.buckets != tuple(
                            sorted(float(b) for b in want)):
                    # a silent bucket mismatch would make a per-metric
                    # override a no-op — the saturation bug would
                    # survive looking fixed
                    raise ValueError(
                        f"histogram {name!r} re-declared with buckets "
                        f"{tuple(want)} (was {inst.buckets})")
                return inst
            inst = cls(name, help, labels, self._lock, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "",
                labels: Sequence[str] = ()) -> Counter:
        return self._get_or_make(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Sequence[str] = ()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_make(Histogram, name, help, labels,
                                 buckets=buckets)

    def metrics_text(self) -> str:
        """The full registry in Prometheus text exposition format
        (version 0.0.4) — what ``GET /metrics`` returns."""
        with self._lock:
            instruments = sorted(self._instruments.values(),
                                 key=lambda i: i.name)
        out: List[str] = []
        for inst in instruments:
            out.extend(inst.expose())
        return "\n".join(out) + ("\n" if out else "")


#: process-default registry — what the scheduler and resilience engine
#: record into unless handed their own
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


class MetricsServer:
    """A daemon-thread HTTP server exposing one registry at
    ``/metrics``. Close it (or let the process exit) to stop."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self, registry: MetricsRegistry,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        server = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                if self.path.split("?")[0] not in ("/metrics", "/"):
                    self.send_error(404)
                    return
                body = server.registry.metrics_text().encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", server.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not log lines
                pass

        self._httpd = http.server.ThreadingHTTPServer((host, port),
                                                      _Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}/metrics"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="deap-tpu-metrics",
            daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)

    def __enter__(self) -> "MetricsServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def resolve_registry(spec) -> Optional[MetricsRegistry]:
    """The ``metrics=`` argument convention shared by the scheduler
    and the resilience engine: ``None``/``False`` → metrics off,
    ``True`` → the process default registry, a registry instance →
    itself."""
    if spec is None or spec is False:
        return None
    if spec is True:
        return get_registry()
    if not isinstance(spec, MetricsRegistry):
        raise TypeError(f"metrics= expects a MetricsRegistry, True or "
                        f"None, got {type(spec).__name__}")
    return spec


def phase_histogram(registry: Optional[MetricsRegistry] = None
                    ) -> Histogram:
    """Declare (or fetch) the per-phase request-latency histogram
    ``deap_service_phase_seconds{phase=...}`` on ``registry`` (default:
    the process registry). The tracing plane's metrics face: every
    emitted span with a phase label observes here, generalizing the
    autoscaler's queue-wait signal to all phases (see
    ``telemetry/tracing.py`` ``PHASES`` for the label vocabulary)."""
    reg = registry if registry is not None else get_registry()
    return reg.histogram(
        "deap_service_phase_seconds",
        "Per-phase request latency from the tracing plane "
        "(queue_wait, wal_fsync, admission, compile, device, "
        "checkpoint, wire_encode, replay, build).",
        labels=("phase",), buckets=SERVING_PHASE_BUCKETS)


def startup_phase_histogram(registry: Optional[MetricsRegistry] = None
                            ) -> Histogram:
    """Declare (or fetch) the startup waterfall histogram
    ``deap_service_startup_phase_seconds{phase=...}`` on ``registry``
    (default: the process registry). One observation per phase per
    service start — wal_replay (reading + rebuilding accepted jobs),
    restore (checkpoint payload verify + materialise), prewarm
    (warm-handoff lattice compile/deserialize), first_result (start →
    first completed tenant). The metrics face of the journal's
    ``startup_phase`` rows (docs/advanced/coldstart.md)."""
    reg = registry if registry is not None else get_registry()
    return reg.histogram(
        "deap_service_startup_phase_seconds",
        "Per-phase service startup wall time (wal_replay, restore, "
        "prewarm, first_result) — the cold-start waterfall.",
        labels=("phase",),
        buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 4.0, 8.0, 15.0,
                 30.0, 60.0, 120.0))


def alarms_total(registry: Optional[MetricsRegistry] = None
                 ) -> Counter:
    """Declare (or fetch) the HealthMonitor alarm counter
    ``deap_alarms_total{kind=...}`` on ``registry`` (default: the
    process registry). Before ISSUE 19 alarms reached only the
    journal; this is their scrapeable face — the label vocabulary is
    ``probes.HealthMonitor.ALARM_KINDS`` (non_finite, clone_spike,
    premature_convergence, zero_improvement, hlo_drift, driver_stall,
    canary)."""
    reg = registry if registry is not None else get_registry()
    return reg.counter(
        "deap_alarms_total",
        "HealthMonitor alarms fired, by kind (the journal's alarm "
        "rows as a scrapeable counter).",
        labels=("kind",))


def alert_state_gauge(registry: Optional[MetricsRegistry] = None
                      ) -> Gauge:
    """Declare (or fetch) the burn-rate alert state gauge
    ``deap_alert_state{name=...}`` on ``registry`` (default: the
    process registry) — 0 inactive/resolved, 1 pending, 2 firing
    (``telemetry.alerts.ALERT_STATE_VALUES``). The service updates it
    on every alert transition, so a scraper sees exactly what
    ``GET /v1/alerts`` reports."""
    reg = registry if registry is not None else get_registry()
    return reg.gauge(
        "deap_alert_state",
        "Burn-rate alert state by rule name (0 inactive/resolved, "
        "1 pending, 2 firing).",
        labels=("name",))


def metrics_text(registry: Optional[MetricsRegistry] = None) -> str:
    """Prometheus text exposition of ``registry`` (default: the
    process registry) — exactly what ``GET /metrics`` would return."""
    return (registry if registry is not None
            else get_registry()).metrics_text()


def serve_metrics(registry: Optional[MetricsRegistry] = None,
                  host: str = "127.0.0.1", port: int = 0
                  ) -> MetricsServer:
    """Start the ``/metrics`` endpoint for ``registry`` (default: the
    process registry) on a daemon thread; returns the
    :class:`MetricsServer` (``.url`` holds the scrape target —
    ``port=0`` picks a free port). Stdlib ``http.server`` only: no new
    dependency, and safe to run next to a single-client TPU runtime."""
    return MetricsServer(registry if registry is not None
                         else get_registry(), host=host, port=port)
