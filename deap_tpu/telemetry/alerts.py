"""Multi-window burn-rate SLO alerting — pending → firing → resolved.

The journal, the windowed SLO curves and the Prometheus instruments
are all *passive*: somebody has to look. This module is the active
half of the observability plane (ISSUE 19): a deterministic,
tick-driven alert state machine in the SRE multi-window burn-rate
style. Each :class:`AlertRule` watches one metric stream (a
:data:`~deap_tpu.telemetry.slo.CURVE_METRICS` name, a per-boundary
sample the service feeds live, or a phase-histogram quantile) over a
**fast/slow window pair**: the fast window makes the alert responsive,
the slow window makes it confident — both must burn for the alert to
fire, which is what keeps one noisy sample from paging anyone.

Definitions, chosen for exactness over journal-row streams (the
"error budget" of a latency SLO is not a counter, so classic
request-ratio burn rates don't apply directly):

- a **sample** is one ``(t, value)`` observation of a rule's metric;
  it *burns* when ``value > threshold``;
- a window's **burn rate** is the burning fraction of the samples
  inside ``(now - window_s, now]`` — ``None`` with no samples
  (absence of evidence never transitions an alert);
- the state machine (per rule, evaluated at :meth:`AlertEngine.tick`):

  ======== ===================================== =========
  from     condition                             to
  ======== ===================================== =========
  inactive fast ≥ burn and slow ≥ burn           firing
  inactive fast ≥ burn (slow not yet)            pending
  pending  fast ≥ burn and slow ≥ burn           firing
  pending  fast < burn (or no fast samples)      inactive
  firing   fast < burn (or no fast samples)      resolved
  resolved (immediately, unjournaled)            inactive
  ======== ===================================== =========

Every transition is journaled as one ``alert`` row and handed to
``on_transition`` (the service updates the ``deap_alert_state`` gauge
there). **Determinism is the design contract**: the engine never
reads a clock — every ``observe``/``tick`` takes an explicit ``t`` —
so the same sample stream and config produce byte-identical journaled
transitions (pinned by ``tests/test_alerts.py``).

Like ``slo.py`` and ``report.py`` this module imports **nothing but
the standard library** and is loadable standalone by file path (no
``deap_tpu`` package, no jax) — the fleet report evaluates journaled
curves through it on boxes that must not initialise a backend.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

__all__ = ["ALERT_STATES", "ALERT_STATE_VALUES", "AlertRule",
           "AlertEngine", "default_rules", "service_rules"]

#: the alert lifecycle (``resolved`` is the one-transition
#: notification state; the engine collapses it to ``inactive`` at the
#: next evaluation without journaling the collapse)
ALERT_STATES = ("inactive", "pending", "firing", "resolved")

#: the ``deap_alert_state{name}`` gauge encoding — resolved is 0 so
#: scrapers see firing alerts, not history
ALERT_STATE_VALUES = {"inactive": 0, "resolved": 0,
                      "pending": 1, "firing": 2}


@dataclass(frozen=True)
class AlertRule:
    """One burn-rate alert: samples of ``metric`` above ``threshold``
    burn; the alert fires when the burning fraction reaches ``burn``
    in BOTH the fast and the slow window."""

    name: str
    metric: str
    threshold: float
    fast_window_s: float = 10.0
    slow_window_s: float = 60.0
    burn: float = 0.5
    description: str = ""

    def __post_init__(self):
        if self.fast_window_s <= 0:
            raise ValueError("fast_window_s must be positive")
        if self.slow_window_s < self.fast_window_s:
            raise ValueError("slow_window_s must be >= fast_window_s "
                             "(the slow window is the confidence "
                             "window)")
        if not 0.0 < self.burn <= 1.0:
            raise ValueError("burn must be in (0, 1]")


def default_rules(fast_window_s: float = 10.0,
                  slow_window_s: float = 60.0) -> tuple:
    """Rules over the windowed-SLO-curve vocabulary (thresholds match
    :data:`deap_tpu.telemetry.slo.DEFAULT_SLOS`) — feed with
    :meth:`AlertEngine.observe_curve`."""
    mk = lambda *a, **kw: AlertRule(  # noqa: E731
        *a, fast_window_s=fast_window_s,
        slow_window_s=slow_window_s, **kw)
    return (
        mk("shed_rate", "shed_rate", 0.05,
           description="over 5% of offered load shed"),
        mk("deadline_miss_rate", "deadline_miss_rate", 0.01,
           description="over 1% of arrivals miss their deadline"),
        mk("queue_wait_p99", "queue_wait_p99", 60.0,
           description="tenants queued over 60 s at p99"),
        mk("segment_p99", "segment_p99", 30.0,
           description="scheduler segments over 30 s at p99"),
    )


def service_rules(fast_window_s: float = 10.0,
                  slow_window_s: float = 60.0) -> tuple:
    """The rules the service driver loop feeds live at every segment
    boundary: the canary's known-answer verdicts plus the boundary's
    shed/deadline-miss deltas. The canary rule's ``burn`` is an
    epsilon: a known-answer failure is an *incident*, not a rate, so
    ANY failing sample in the window fires — even when surrounded by
    passing canaries at a tight cadence — within the same boundary the
    mismatch is detected at (the ≤ 2 boundary detection-latency gate
    of ``bench.py --canary``). It resolves once the fast window is
    clean again."""
    mk = lambda *a, **kw: AlertRule(  # noqa: E731
        *a, fast_window_s=fast_window_s,
        slow_window_s=slow_window_s, **kw)
    return (
        mk("canary_failure", "canary_fail", 0.5, burn=1e-9,
           description="known-answer canary wire-digest mismatch"),
        mk("shed_rate", "shed_rate", 0.05,
           description="over 5% of offered load shed"),
        mk("deadline_miss_rate", "deadline_miss_rate", 0.01,
           description="over 1% of arrivals miss their deadline"),
    )


class AlertEngine:
    """The tick-driven burn-rate state machine over a set of
    :class:`AlertRule`\\ s.

    ``journal`` (a :class:`~deap_tpu.telemetry.journal.RunJournal`,
    duck-typed on ``.event``) receives one ``alert`` row per
    transition; ``on_transition(transition_dict)`` is the metrics
    hook. Feed samples with :meth:`observe` (live) or
    :meth:`observe_curve` (a ``windowed_curve`` result), then
    :meth:`tick` with the evaluation time."""

    def __init__(self, rules: Optional[Iterable[AlertRule]] = None,
                 journal: Any = None,
                 on_transition: Optional[
                     Callable[[Dict[str, Any]], None]] = None):
        self.rules = tuple(default_rules() if rules is None
                           else rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.journal = journal
        self.on_transition = on_transition
        self._by_metric: Dict[str, List[AlertRule]] = {}
        for r in self.rules:
            self._by_metric.setdefault(r.metric, []).append(r)
        self._samples: Dict[str, List[tuple]] = \
            {r.name: [] for r in self.rules}
        self._state: Dict[str, str] = \
            {r.name: "inactive" for r in self.rules}
        self._since: Dict[str, Optional[float]] = \
            {r.name: None for r in self.rules}
        self._last_burn: Dict[str, tuple] = \
            {r.name: (None, None) for r in self.rules}
        #: the full transition history, in order — the deterministic
        #: artifact the tests pin
        self.transitions: List[Dict[str, Any]] = []

    # -- ingestion -----------------------------------------------------

    def observe(self, t: float, metric: str, value: Any) -> None:
        """One sample of ``metric`` at time ``t``; ``None`` values are
        skipped (an empty window must not look healthy *or* sick)."""
        if value is None:
            return
        for rule in self._by_metric.get(metric, ()):
            self._samples[rule.name].append(
                (float(t), float(value) > rule.threshold))

    def observe_curve(self,
                      windows: Iterable[Dict[str, Any]]) -> None:
        """Feed a :func:`~deap_tpu.telemetry.slo.windowed_curve`
        result: each window's metrics are observed at the window's
        closing edge ``t1``."""
        for w in windows:
            t = w.get("t1", w.get("t0", 0.0))
            for metric in self._by_metric:
                if metric in w:
                    self.observe(t, metric, w[metric])

    # -- evaluation ----------------------------------------------------

    def _burn(self, rule: AlertRule, now: float,
              window_s: float) -> Optional[float]:
        lo = now - window_s
        n = bad = 0
        for t, burning in self._samples[rule.name]:
            if lo < t <= now:
                n += 1
                bad += burning
        return (bad / n) if n else None

    def tick(self, now: float) -> List[Dict[str, Any]]:
        """Evaluate every rule at time ``now``; returns (and records,
        and journals) the transitions this tick produced."""
        now = float(now)
        out: List[Dict[str, Any]] = []
        for rule in self.rules:
            fast = self._burn(rule, now, rule.fast_window_s)
            slow = self._burn(rule, now, rule.slow_window_s)
            self._last_burn[rule.name] = (fast, slow)
            fast_hot = fast is not None and fast >= rule.burn
            slow_hot = slow is not None and slow >= rule.burn
            st = self._state[rule.name]
            if st == "resolved":  # one-tick state; collapse silently
                st = "inactive"
            new = st
            if st == "inactive":
                if fast_hot and slow_hot:
                    new = "firing"
                elif fast_hot:
                    new = "pending"
            elif st == "pending":
                if fast_hot and slow_hot:
                    new = "firing"
                elif not fast_hot:
                    new = "inactive"
            elif st == "firing":
                if not fast_hot:
                    new = "resolved"
            if new != st:
                tr = {"name": rule.name, "metric": rule.metric,
                      "from": st, "to": new, "at": round(now, 6),
                      "fast_burn": (round(fast, 4)
                                    if fast is not None else None),
                      "slow_burn": (round(slow, 4)
                                    if slow is not None else None),
                      "threshold": rule.threshold, "burn": rule.burn}
                self.transitions.append(tr)
                out.append(tr)
                self._since[rule.name] = now
                if self.journal is not None:
                    self.journal.event(
                        "alert", name=tr["name"], state=tr["to"],
                        prev=tr["from"], at=tr["at"],
                        metric=tr["metric"],
                        fast_burn=tr["fast_burn"],
                        slow_burn=tr["slow_burn"],
                        threshold=tr["threshold"], burn=tr["burn"])
                if self.on_transition is not None:
                    self.on_transition(tr)
            self._state[rule.name] = new
            # trim: samples older than the slow window can never
            # matter again (ticks are monotone by contract)
            lo = now - rule.slow_window_s
            buf = self._samples[rule.name]
            if buf and buf[0][0] <= lo:
                self._samples[rule.name] = \
                    [s for s in buf if s[0] > lo]
        return out

    # -- inspection ----------------------------------------------------

    def state(self, name: str) -> str:
        return self._state[name]

    def firing(self) -> List[str]:
        """The names of currently-firing alerts, sorted."""
        return sorted(n for n, s in self._state.items()
                      if s == "firing")

    def snapshot(self) -> List[Dict[str, Any]]:
        """The full ``GET /v1/alerts`` payload: one dict per rule
        (state, windows, last burn rates, since-when)."""
        out = []
        for rule in self.rules:
            fast, slow = self._last_burn[rule.name]
            out.append({
                "name": rule.name, "metric": rule.metric,
                "threshold": rule.threshold, "burn": rule.burn,
                "fast_window_s": rule.fast_window_s,
                "slow_window_s": rule.slow_window_s,
                "state": self._state[rule.name],
                "since": self._since[rule.name],
                "fast_burn": fast, "slow_burn": slow,
                "description": rule.description,
            })
        return out
