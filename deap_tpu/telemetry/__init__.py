"""Run-journal telemetry — the observability subsystem.

Three planes (see docs/advanced/telemetry.md):

1. **In-scan metrics** (:mod:`~deap_tpu.telemetry.meter`): a
   :class:`Meter` of counters/gauges/histograms whose state rides the
   jit'd generation scans as auxiliary carry and comes back as stacked
   per-generation arrays — zero host round trips, plus an opt-in
   ``jax.debug.callback`` streaming emitter.
2. **Host events** (:mod:`~deap_tpu.telemetry.journal`): a JSONL
   :class:`RunJournal` with run header (backend/device/toolbox
   fingerprint), compile/**retrace** events via ``jax.monitoring``
   listeners, subsystem events, and a final summary.
3. **Span aggregation**: while a :class:`RunTelemetry` context is
   active, ``support.profiling.span`` blocks aggregate host wall time
   per name (count/total/p50/p99) into the journal — the per-collective
   ``genome_shard/*`` spans yield numbers even with no xplane capture.

The third observability layer (after the journal/meter and the probes)
adds the **program and serving planes**:
:mod:`~deap_tpu.telemetry.costs` — the :class:`ProgramObservatory`
profiling every AOT-compiled program (flops/bytes, memory + donation
aliasing, compile time, HLO fingerprint, ``hlo_drift`` alarms) — and
:mod:`~deap_tpu.telemetry.metrics` — a stdlib-only host metrics
registry (counters/gauges/histograms) exported as Prometheus text via
:func:`metrics_text` / :func:`serve_metrics`, fed by the serving
scheduler and the resilience engine.

On top of the pipes, :mod:`~deap_tpu.telemetry.probes` is the
evolution-specific *content*: jit-safe population probes (diversity,
selection pressure, landscape stats, front quality) threaded through
every loop's ``probes=`` argument, a host-side :class:`HealthMonitor`
turning meter rows into journaled ``alarm`` events, and
:mod:`~deap_tpu.telemetry.report` — a stdlib-only terminal renderer for
any journal (``python bench_report.py --health run.jsonl``).

The reference's only telemetry is the ``nevals`` logbook column; none
of the JAX-native EC frameworks (evosax, Kozax — PAPERS.md) emit
structured machine-readable run telemetry either. This subsystem is
opt-in everywhere and changes no computed result when enabled.
"""

from deap_tpu.telemetry.alerts import (
    AlertEngine,
    AlertRule,
    default_rules,
    service_rules,
)
from deap_tpu.telemetry.costs import (
    ProgramObservatory,
    observatory,
    profile_compiled,
)
from deap_tpu.telemetry.federation import (
    federate,
    fleet_summary,
    fleet_trace,
    register_process,
)
from deap_tpu.telemetry.journal import (
    RunJournal,
    broadcast,
    environment_fingerprint,
    read_journal,
    toolbox_fingerprint,
)
from deap_tpu.telemetry.meter import Meter, MeterState
from deap_tpu.telemetry.metrics import (
    HistogramSnapshot,
    MetricsRegistry,
    get_registry,
    metrics_text,
    serve_metrics,
)
from deap_tpu.telemetry.slo import (
    DEFAULT_SLOS,
    SLO_JOURNAL_KINDS,
    SloSpec,
    attribute_regression,
    evaluate_gates,
    windowed_curve,
)
from deap_tpu.telemetry.probes import (
    PROBE_REGISTRY,
    DiversityProbe,
    FitnessProbe,
    FrontProbe,
    HealthMonitor,
    Probe,
    QuarantineProbe,
    SelectionProbe,
    TreeDiversityProbe,
    compose_probes,
    exact_hypervolume,
    register_probe,
)
from deap_tpu.telemetry.run import RunTelemetry, strategy_probe

__all__ = [
    "AlertEngine",
    "AlertRule",
    "DEFAULT_SLOS",
    "HistogramSnapshot",
    "Meter",
    "MeterState",
    "MetricsRegistry",
    "SLO_JOURNAL_KINDS",
    "SloSpec",
    "PROBE_REGISTRY",
    "Probe",
    "ProgramObservatory",
    "DiversityProbe",
    "TreeDiversityProbe",
    "FitnessProbe",
    "SelectionProbe",
    "FrontProbe",
    "HealthMonitor",
    "QuarantineProbe",
    "RunJournal",
    "RunTelemetry",
    "attribute_regression",
    "broadcast",
    "compose_probes",
    "default_rules",
    "evaluate_gates",
    "federate",
    "fleet_summary",
    "fleet_trace",
    "windowed_curve",
    "environment_fingerprint",
    "exact_hypervolume",
    "get_registry",
    "metrics_text",
    "observatory",
    "profile_compiled",
    "read_journal",
    "register_probe",
    "register_process",
    "serve_metrics",
    "service_rules",
    "strategy_probe",
    "toolbox_fingerprint",
]
