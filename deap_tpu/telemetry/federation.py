"""Fleet journal federation — many processes, one timeline.

Every observability surface before ISSUE 19 reads ONE process's
journal. The router tier and rolling upgrades (ROADMAP items 1 and 4)
put several driver processes behind one front end, so this module
defines the **fleet root** contract and the federator that merges the
member journals back into a single story:

- **Layout.** A fleet root is a directory of per-process journal
  dirs: ``<root>/<process_id>/journal.jsonl`` plus that journal's
  rotated ``.N`` generations (kill-9 restarts) and an optional
  ``meta.json`` written at registration. :func:`register_process`
  creates the dir and returns the journal path for the process to
  open — registration IS the directory, so a kill-9'd member needs no
  deregistration and a scraper needs no lockfile.
- **Merge.** Journal ``t`` values are monotonic offsets from each
  file's own epoch; each generation's header carries ``wall_start``,
  so ``wall_start + t`` rebases every row onto one wall-clock axis —
  exactly the epoch-rebase discipline
  :func:`~deap_tpu.telemetry.tracing.assemble_trace` uses across
  restarts, applied across processes. :func:`federate` returns the
  merged rows (each stamped with its ``process`` and absolute
  ``wall`` seconds) sorted into one fleet timeline, tolerating torn
  tails and headerless generations in any member
  (``read_journal(strict=False)``; a generation whose header was
  lost keeps its rows at the timeline origin rather than poisoning
  the merge).
- **Stitch.** Trace ids derive deterministically from request ids
  (:func:`~deap_tpu.telemetry.tracing.trace_id_for`), so spans for
  one request emitted by *different processes* (client + server, or
  a tenant migrated between drivers) already share a trace id with
  zero coordination — :func:`fleet_trace` assembles the cross-process
  waterfall and :func:`cross_process_traces` lists the trace ids that
  actually span members.
- **Rollup.** :func:`process_health` summarises each member (rows,
  generations, tears, alarms, stalls, canary verdicts, firing
  alerts); :func:`fleet_curve` re-windows the merged timeline through
  :func:`~deap_tpu.telemetry.slo.windowed_curve` for the fleet-wide
  SLO view. ``report.py --fleet`` renders all of it (with ``--watch``
  for a live refresh).

Like its siblings this module imports **nothing but the standard
library** and loads ``journal.py``/``tracing.py``/``slo.py`` by file
path, so a fleet report renders on a box with no jax installed
(``tests/test_federation.py`` pins the no-jax subprocess guarantee).
"""

from __future__ import annotations

import importlib.util
import json
import os
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["register_process", "fleet_processes", "process_groups",
           "process_health", "federate", "fleet_curve",
           "fleet_trace", "cross_process_traces", "fleet_summary"]

#: the journal filename every member opens inside its process dir
JOURNAL_NAME = "journal.jsonl"

#: registration metadata filename (optional; scrapers must not
#: require it — a member that died before writing it still federates)
META_NAME = "meta.json"

_here = os.path.dirname(os.path.abspath(__file__))
_mods: Dict[str, Any] = {}


def _load(fname: str):
    """A sibling telemetry module loaded standalone by path (never
    through the ``deap_tpu`` package, which imports jax). Registered
    in ``sys.modules`` before exec so dataclass processing resolves
    ``cls.__module__`` (the report.py pattern)."""
    if fname not in _mods:
        spec = importlib.util.spec_from_file_location(
            "_deap_tpu_fed_" + fname[:-3], os.path.join(_here, fname))
        mod = importlib.util.module_from_spec(spec)
        sys.modules[spec.name] = mod
        spec.loader.exec_module(mod)
        _mods[fname] = mod
    return _mods[fname]


def _journal():
    return _load("journal.py")


def _tracing():
    return _load("tracing.py")


def _slo():
    return _load("slo.py")


# ------------------------------------------------------ fleet root ----

def register_process(root: str, process_id: Optional[str] = None,
                     **meta: Any) -> str:
    """Create ``<root>/<process_id>/`` and return the journal path
    inside it (pass to :class:`~deap_tpu.telemetry.journal.RunJournal`
    or as a service/scheduler root's journal). ``process_id``
    defaults to ``proc-<pid>``; extra ``meta`` lands in ``meta.json``
    (best-effort — federation never requires it)."""
    pid = str(process_id) if process_id else f"proc-{os.getpid()}"
    if os.sep in pid or pid in (".", ".."):
        raise ValueError(f"process_id {pid!r} must be a plain name")
    d = os.path.join(str(root), pid)
    os.makedirs(d, exist_ok=True)
    try:
        with open(os.path.join(d, META_NAME), "w") as fh:
            json.dump({"process_id": pid, "pid": os.getpid(),
                       **meta}, fh, sort_keys=True)
            fh.write("\n")
    except OSError:
        pass
    return os.path.join(d, JOURNAL_NAME)


def fleet_processes(root: str) -> List[str]:
    """The registered process ids under ``root`` (sorted): every
    subdirectory holding at least one journal generation."""
    jm = _journal()
    out = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    for name in entries:
        d = os.path.join(root, name)
        if not os.path.isdir(d):
            continue
        if jm.journal_generations(os.path.join(d, JOURNAL_NAME)):
            out.append(name)
    return out


def process_meta(root: str, process_id: str) -> Dict[str, Any]:
    """The member's ``meta.json`` (``{}`` when absent/unreadable)."""
    try:
        with open(os.path.join(root, process_id, META_NAME)) as fh:
            meta = json.load(fh)
        return meta if isinstance(meta, dict) else {}
    except (OSError, ValueError):
        return {}


def process_groups(root: str, process_id: str
                   ) -> List[Tuple[Optional[dict], Any]]:
    """One member's journal generations, oldest first, parsed into
    the ``(header_row_or_None, rows)`` pairs
    :func:`~deap_tpu.telemetry.tracing.assemble_trace` stitches
    across (torn tails tolerated — ``strict=False``)."""
    jm = _journal()
    path = os.path.join(root, process_id, JOURNAL_NAME)
    groups: List[Tuple[Optional[dict], Any]] = []
    for p in jm.journal_generations(path):
        try:
            rows = jm.read_journal(p, strict=False)
        except OSError:
            continue
        header = next((e for e in rows
                       if e.get("kind") == "header"), None)
        groups.append((header, rows))
    return groups


# ----------------------------------------------------------- merge ----

def federate(root: str) -> Dict[str, Any]:
    """Merge every member's journal generations into one
    monotonic-rebased fleet timeline.

    Returns ``{"root", "processes": {pid: health}, "rows"}`` where
    ``rows`` is the merged timeline sorted by absolute time: each row
    is a copy of the journal row plus ``process`` (the member id) and
    ``wall`` (``header.wall_start + t`` — the epoch rebase; rows from
    a generation whose header was torn away get ``wall = t`` and the
    member's health notes the missing header). The sort is stable on
    ``(wall, process)`` so equal-time rows order deterministically."""
    processes: Dict[str, Dict[str, Any]] = {}
    merged: List[Dict[str, Any]] = []
    for pid in fleet_processes(root):
        groups = process_groups(root, pid)
        processes[pid] = process_health(groups,
                                        meta=process_meta(root, pid))
        for header, rows in groups:
            wall0 = float((header or {}).get("wall_start", 0.0))
            for row in rows:
                r = dict(row)
                r["process"] = pid
                r["wall"] = wall0 + float(row.get("t", 0.0) or 0.0)
                merged.append(r)
    merged.sort(key=lambda r: (r["wall"], r["process"]))
    return {"root": str(root), "processes": processes,
            "rows": merged}


def process_health(groups: List[Tuple[Optional[dict], Any]],
                   meta: Optional[Dict[str, Any]] = None
                   ) -> Dict[str, Any]:
    """One member's health column: row/generation counts, torn-tail
    and missing-header flags, alarm/stall/shed/deadline tallies, the
    canary verdict counts, currently-firing alerts (the last ``alert``
    row per name wins) and the member's absolute time span."""
    n_rows = 0
    tears = 0
    missing_header = 0
    alarms: Dict[str, int] = {}
    stalls = canary_ok = canary_failed = sheds = deadline = 0
    alert_state: Dict[str, str] = {}
    lo = hi = None
    for header, rows in groups:
        wall0 = float((header or {}).get("wall_start", 0.0))
        if header is None:
            missing_header += 1
        n_rows += len(rows)
        if getattr(rows, "tear_offset", None) is not None:
            tears += 1
        for row in rows:
            kind = row.get("kind")
            w = wall0 + float(row.get("t", 0.0) or 0.0)
            lo = w if lo is None else min(lo, w)
            hi = w if hi is None else max(hi, w)
            if kind == "alarm":
                a = str(row.get("alarm", "?"))
                alarms[a] = alarms.get(a, 0) + 1
            elif kind == "driver_stall" and "stalled_s" in row:
                stalls += 1
            elif kind == "canary_ok":
                canary_ok += 1
            elif kind == "canary_failed":
                canary_failed += 1
            elif kind == "load_shed":
                sheds += 1
            elif kind == "deadline_exceeded":
                deadline += 1
            elif kind == "alert":
                alert_state[str(row.get("name", "?"))] = \
                    str(row.get("state", "?"))
    return {
        "generations": len(groups), "rows": n_rows,
        "torn_tails": tears, "missing_headers": missing_header,
        "alarms": alarms, "driver_stalls": stalls,
        "canary_ok": canary_ok, "canary_failed": canary_failed,
        "load_sheds": sheds, "deadline_misses": deadline,
        "firing_alerts": sorted(n for n, s in alert_state.items()
                                if s == "firing"),
        "wall_lo": lo, "wall_hi": hi,
        "meta": meta or {},
    }


def fleet_curve(rows: List[Dict[str, Any]],
                window_s: float = 1.0) -> List[Dict[str, Any]]:
    """The fleet-wide windowed SLO curve: the merged timeline's rows
    re-anchored to the fleet's earliest wall second and fed through
    :func:`~deap_tpu.telemetry.slo.windowed_curve` (which windows on
    ``t``)."""
    sl = _slo()
    timed = [r for r in rows
             if isinstance(r.get("wall"), (int, float))]
    if not timed:
        return []
    t0 = min(r["wall"] for r in timed)
    rebased = [dict(r, t=r["wall"] - t0) for r in timed]
    return sl.windowed_curve(rebased, window_s=window_s)


# ---------------------------------------------------------- traces ----

def _all_groups(root: str) -> List[Tuple[Optional[dict], Any]]:
    groups: List[Tuple[Optional[dict], Any]] = []
    for pid in fleet_processes(root):
        groups.extend(process_groups(root, pid))
    return groups


def resolve_request_id(root: str, ident: str) -> Optional[str]:
    """``ident`` as a request id, or resolved from a tenant id via
    any member's rows that carry both (the ``report.py --trace``
    convention, fleet-wide)."""
    groups = _all_groups(root)
    for _, rows in groups:
        for e in rows:
            if e.get("request_id") == ident:
                return ident
    for _, rows in groups:
        for e in rows:
            if e.get("tenant_id") == ident and e.get("request_id"):
                return str(e["request_id"])
    return None


def fleet_trace(root: str, ident: str) -> Optional[Dict[str, Any]]:
    """One request's trace assembled across EVERY member's journal
    generations — the deterministic trace id stitches spans emitted
    by different processes with zero coordination. Returns the
    :func:`~deap_tpu.telemetry.tracing.assemble_trace` dict plus
    ``request_id`` and ``processes`` (which members contributed
    spans), or ``None`` when no member knows ``ident``."""
    tr = _tracing()
    rid = resolve_request_id(root, ident)
    if rid is None:
        return None
    trace_id = tr.trace_id_for(rid)
    contributing: List[str] = []
    groups: List[Tuple[Optional[dict], Any]] = []
    for pid in fleet_processes(root):
        pg = process_groups(root, pid)
        groups.extend(pg)
        if any(e.get("kind") == "trace_span"
               and e.get("trace_id") == trace_id
               for _, rows in pg for e in rows):
            contributing.append(pid)
    trace = tr.assemble_trace(groups, trace_id)
    trace["request_id"] = rid
    trace["processes"] = contributing
    return trace


def cross_process_traces(root: str) -> List[Dict[str, Any]]:
    """The trace ids whose spans appear in more than one member —
    the proof a request (or a migrated tenant) crossed a process
    boundary. Returns ``[{"trace_id", "request_id", "processes",
    "spans"}]`` sorted by span count descending."""
    seen: Dict[str, Dict[str, Any]] = {}
    for pid in fleet_processes(root):
        for _, rows in process_groups(root, pid):
            for e in rows:
                if e.get("kind") != "trace_span":
                    continue
                tid = e.get("trace_id")
                if not tid:
                    continue
                rec = seen.setdefault(
                    tid, {"trace_id": tid, "request_id": None,
                          "processes": set(), "spans": 0})
                rec["processes"].add(pid)
                rec["spans"] += 1
                if rec["request_id"] is None and e.get("request_id"):
                    rec["request_id"] = str(e["request_id"])
    out = [dict(r, processes=sorted(r["processes"]))
           for r in seen.values() if len(r["processes"]) > 1]
    out.sort(key=lambda r: (-r["spans"], r["trace_id"]))
    return out


# --------------------------------------------------------- summary ----

def fleet_summary(root: str, window_s: float = 1.0
                  ) -> Dict[str, Any]:
    """Everything ``report.py --fleet`` renders, in one call: the
    federated timeline, per-process health, the fleet SLO curve and
    the cross-process trace index."""
    fed = federate(root)
    return {
        "root": fed["root"],
        "processes": fed["processes"],
        "rows": fed["rows"],
        "curve": fleet_curve(fed["rows"], window_s=window_s),
        "cross_traces": cross_process_traces(root),
    }
