"""In-scan metrics plane — counters/gauges/histograms as scan carry.

The reference's only run telemetry is the per-generation ``nevals``
logbook column (deap/algorithms.py:158,185). Our loops compile whole
runs into one ``lax.scan``, so anything worth observing must ride the
scan as data: a :class:`Meter` declares a fixed set of metrics, its
``init()`` state is a flat dict-of-arrays pytree threaded as auxiliary
carry, and pure functional updates (``inc``/``set``/``observe``) run
on device inside the step. Emitting the state as the scan's stacked
``y`` output yields per-generation metric rows with **zero host round
trips**; an opt-in ``jax.debug.callback`` emitter streams live rows
for long runs (see :meth:`Meter.stream`).

Telemetry must never change computed results: meter updates read
population state but touch no RNG keys and feed nothing back into the
evolutionary computation (pinned by
``tests/test_telemetry.py::test_meter_carry_bit_identical``).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Mapping, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Meter", "MeterState"]

MeterState = Dict[str, jnp.ndarray]

_KINDS = ("counter", "gauge", "histogram")


class Meter:
    """Declarative metric registry with a pytree state.

    Declare every metric *before* ``init()`` — the state is scan carry,
    so its structure is fixed at trace time::

        meter = Meter()
        meter.counter("nevals")
        meter.gauge("best")
        meter.histogram("fitness", lo=0.0, hi=100.0, bins=16)
        state = meter.init()
        # inside the scanned step (pure, on device):
        state = meter.inc(state, "nevals", jnp.sum(~pop.valid))
        state = meter.set(state, "best", jnp.max(pop.wvalues[:, 0]))
        state = meter.observe(state, "fitness", pop.wvalues[:, 0])

    Counters are monotone and cumulative across generations; gauges
    hold the last value set; histograms accumulate bucket counts over
    a fixed ``[lo, hi)`` range (under/overflow clamps into the edge
    buckets, so totals stay conserved).
    """

    def __init__(self):
        self._specs: Dict[str, dict] = {}

    # ------------------------------------------------------- declaration ----

    def _declare(self, name: str, **spec) -> None:
        prev = self._specs.get(name)
        if prev is not None:
            if prev != spec:
                raise ValueError(
                    f"metric {name!r} re-declared with a different spec: "
                    f"{prev} vs {spec}")
            return  # idempotent: algorithms and user probes may both declare
        self._specs[name] = spec

    def counter(self, name: str, shape: Sequence[int] = (),
                dtype=jnp.int32, internal: bool = False) -> None:
        self._declare(name, kind="counter", shape=tuple(shape),
                      dtype=jnp.dtype(dtype).name, internal=bool(internal))

    def gauge(self, name: str, shape: Sequence[int] = (),
              dtype=jnp.float32, internal: bool = False) -> None:
        """``internal=True`` marks carry-only state (a probe's previous
        best, a per-individual lineage array): it lives in the meter
        state like any gauge but is omitted from :meth:`row`/:meth:`
        rows`, so bulky or meaningless-to-humans carries never bloat
        the journal."""
        self._declare(name, kind="gauge", shape=tuple(shape),
                      dtype=jnp.dtype(dtype).name, internal=bool(internal))

    def histogram(self, name: str, lo: float, hi: float,
                  bins: int = 16) -> None:
        if not hi > lo:
            raise ValueError(f"histogram {name!r}: need hi > lo, "
                             f"got [{lo}, {hi})")
        self._declare(name, kind="histogram", lo=float(lo), hi=float(hi),
                      bins=int(bins))

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(self._specs)

    def spec(self, name: str) -> dict:
        return dict(self._specs[name])

    # ------------------------------------------------------------- state ----

    def init(self) -> MeterState:
        state: MeterState = {}
        for name, s in self._specs.items():
            if s["kind"] == "histogram":
                state[name] = jnp.zeros((s["bins"],), jnp.int32)
            else:
                state[name] = jnp.zeros(s["shape"], jnp.dtype(s["dtype"]))
        return state

    def _check(self, name: str, kind: str) -> dict:
        s = self._specs.get(name)
        if s is None:
            raise KeyError(f"metric {name!r} was never declared "
                           f"(known: {sorted(self._specs)})")
        if s["kind"] != kind:
            raise TypeError(f"metric {name!r} is a {s['kind']}, "
                            f"not a {kind}")
        return s

    # --------------------------------------------------- in-scan updates ----

    def inc(self, state: MeterState, name: str, value=1) -> MeterState:
        s = self._check(name, "counter")
        v = jnp.asarray(value, jnp.dtype(s["dtype"]))
        return {**state, name: state[name] + v}

    def set(self, state: MeterState, name: str, value) -> MeterState:
        s = self._check(name, "gauge")
        v = jnp.broadcast_to(
            jnp.asarray(value, jnp.dtype(s["dtype"])), s["shape"])
        return {**state, name: v}

    def observe(self, state: MeterState, name: str, values,
                mask=None) -> MeterState:
        """Bucketize ``values`` into the histogram's counts; ``mask``
        (same shape) drops rows without changing bucket geometry."""
        s = self._check(name, "histogram")
        v = jnp.ravel(jnp.asarray(values, jnp.float32))
        bins, lo, hi = s["bins"], s["lo"], s["hi"]
        idx = jnp.clip(
            jnp.floor((v - lo) / (hi - lo) * bins), 0, bins - 1
        ).astype(jnp.int32)
        ones = jnp.ones_like(idx)
        if mask is not None:
            ones = jnp.where(jnp.ravel(mask), ones, 0)
        return {**state, name: state[name].at[idx].add(ones)}

    # --------------------------------------------------------- streaming ----

    def stream(self, state: MeterState, gen, emit: Callable) -> None:
        """Opt-in live tail: inside jit/scan, ship this generation's
        state to the host ``emit(gen, row_dict)`` via
        ``jax.debug.callback``. Unordered (does not serialise device
        execution); the callback sees concrete numpy values."""
        def _cb(gen, **st):
            emit(int(gen), self.row(st))
        jax.debug.callback(_cb, gen, **state)

    def get(self, state: MeterState, name: str) -> jnp.ndarray:
        """Read a metric's current value out of the state (probes use
        this for carried quantities)."""
        if name not in self._specs:
            raise KeyError(f"metric {name!r} was never declared "
                           f"(known: {sorted(self._specs)})")
        return state[name]

    # ----------------------------------------------------- host decoding ----

    def row(self, state: Mapping[str, Any]) -> Dict[str, Any]:
        """One state snapshot as a JSON-serialisable dict (``internal``
        metrics — carry-only state — are omitted)."""
        out: Dict[str, Any] = {}
        for name, s in self._specs.items():
            if s.get("internal"):
                continue
            a = np.asarray(state[name])
            if a.ndim == 0:
                out[name] = a.item()
            else:
                out[name] = a.tolist()
        return out

    def rows(self, stacked: Mapping[str, Any]) -> list:
        """Decode a scan's stacked ``[ngen, ...]`` meter output into a
        list of per-generation row dicts."""
        arrs = {k: np.asarray(v) for k, v in stacked.items()}
        ngen = next(iter(arrs.values())).shape[0] if arrs else 0
        return [self.row({k: v[i] for k, v in arrs.items()})
                for i in range(ngen)]
