"""Windowed SLO curves, gates and per-phase regression attribution.

The load observatory's analysis plane (ISSUE 17): the serving stack
already journals every signal an SLO needs — per-boundary ``slo`` rows
(queue depth, segment wall seconds, cumulative arrival / shed /
deadline-miss counters), exact ``wait_s`` on every
``tenant_admitted``/``tenant_resumed`` row, and ``trace_span`` rows
with per-phase durations. This module turns those rows into:

- **windowed curves** (:func:`windowed_curve`): the journal sliced
  into fixed-width time windows, each window carrying arrival rate,
  shed rate, deadline-miss rate and exact admission / queue-wait /
  segment percentiles — a latency *curve* over the run instead of one
  end-of-run blob;
- **gates** (:class:`SloSpec`, :func:`evaluate_gates`): declarative
  pass/fail thresholds over a curve's worst window, journaled as
  ``slo_gate`` rows;
- **regression attribution** (:func:`attribute_regression`): the
  end-to-end latency delta between two runs decomposed into per-phase
  percentile deltas from the trace spans, so the report says
  "``segment`` +1.8 s at p99", not "it got slower".

Live (non-journal) consumers use the same math through
:class:`~deap_tpu.telemetry.metrics.HistogramSnapshot`: snapshot a
cumulative histogram at a window's edges, ``delta()`` the pair, and
``quantile()`` the delta — cumulative-only counts cannot give
windowed percentiles, snapshots can.

Like ``report.py`` and ``metrics.py`` this module imports **nothing
but the standard library** — a box rendering SLO curves from a
shipped journal must never initialise an XLA backend
(``tests/test_loadgen.py`` pins the no-jax guarantee).
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, Iterable, List, Optional, Sequence

__all__ = ["CURVE_METRICS", "DEFAULT_SLOS", "SLO_JOURNAL_KINDS",
           "SloSpec", "attribute_regression", "evaluate_gates",
           "exact_quantile", "phase_samples", "windowed_curve"]

#: journal kinds this plane writes (documented in the
#: docs/advanced/telemetry.md kind table; drift-gated by
#: tests/test_loadgen.py alongside SERVICE_JOURNAL_KINDS)
SLO_JOURNAL_KINDS = ("loadgen_run", "slo_gate")


def exact_quantile(samples: Sequence[float], q: float
                   ) -> Optional[float]:
    """The q-th order statistic (nearest-rank, the Prometheus
    convention's exact twin): ``None`` on no samples."""
    if not samples:
        return None
    xs = sorted(samples)
    rank = max(1, math.ceil(q * len(xs)))
    return xs[min(rank, len(xs)) - 1]


# --------------------------------------------------------- curves ----

#: the windowed-curve metric vocabulary — what :class:`SloSpec` may
#: gate on. Rates are per-window fractions; latencies are exact
#: per-window percentiles (seconds).
CURVE_METRICS = ("admission_p99", "queue_wait_p99", "segment_p99",
                 "shed_rate", "deadline_miss_rate", "arrival_rate")


def windowed_curve(rows: Iterable[Dict[str, Any]],
                   window_s: float = 1.0) -> List[Dict[str, Any]]:
    """Slice journal ``rows`` (dicts with ``t``/``kind``) into
    ``window_s``-wide windows and compute each window's SLO sample.

    Per window: ``arrivals`` (``job_submitted`` rows) and
    ``arrival_rate`` (/s), ``sheds``/``shed_rate`` (``load_shed``
    rows; rate over arrivals+sheds — offered load),
    ``deadline_misses``/``deadline_miss_rate``, ``admission_p99``
    (fresh ``tenant_admitted`` ``wait_s``), ``queue_wait_p99``
    (admissions *and* resumes — the full queue-wait distribution) and
    ``segment_p99`` (``slo`` rows' ``segment_s``). Latency fields are
    ``None`` in windows with no samples (distinguish "no data" from
    "0 s"). Windows are anchored at the first row's ``t``."""
    rows = [r for r in rows if isinstance(r.get("t"), (int, float))]
    if not rows:
        return []
    window_s = float(window_s)
    if window_s <= 0:
        raise ValueError("window_s must be positive")
    t0 = min(r["t"] for r in rows)
    t_hi = max(r["t"] for r in rows)
    n_win = max(1, int(math.floor((t_hi - t0) / window_s)) + 1)
    wins: List[Dict[str, Any]] = []
    for i in range(n_win):
        wins.append({"t0": round(t0 + i * window_s, 6),
                     "t1": round(t0 + (i + 1) * window_s, 6),
                     "arrivals": 0, "sheds": 0, "deadline_misses": 0,
                     "_adm": [], "_wait": [], "_seg": []})
    for r in rows:
        w = wins[min(n_win - 1,
                     int((r["t"] - t0) / window_s))]
        kind = r.get("kind")
        if kind == "job_submitted":
            w["arrivals"] += 1
        elif kind == "load_shed":
            w["sheds"] += int(r.get("new", 1) or 1)
        elif kind == "deadline_exceeded":
            w["deadline_misses"] += 1
        elif kind == "tenant_admitted":
            wait = r.get("wait_s")
            if wait is not None:
                w["_adm"].append(float(wait))
                w["_wait"].append(float(wait))
        elif kind == "tenant_resumed":
            wait = r.get("wait_s")
            if wait is not None:
                w["_wait"].append(float(wait))
        elif kind == "slo":
            seg = r.get("segment_s")
            if seg is not None:
                w["_seg"].append(float(seg))
    for w in wins:
        offered = w["arrivals"] + w["sheds"]
        w["arrival_rate"] = round(w["arrivals"] / window_s, 4)
        w["shed_rate"] = (round(w["sheds"] / offered, 4)
                          if offered else 0.0)
        w["deadline_miss_rate"] = (
            round(w["deadline_misses"] / max(1, w["arrivals"]), 4))
        w["admission_p99"] = exact_quantile(w.pop("_adm"), 0.99)
        w["queue_wait_p99"] = exact_quantile(w.pop("_wait"), 0.99)
        w["segment_p99"] = exact_quantile(w.pop("_seg"), 0.99)
    return wins


# ---------------------------------------------------------- gates ----

@dataclass(frozen=True)
class SloSpec:
    """One declarative SLO: gate ``metric`` (a :data:`CURVE_METRICS`
    name) at ``threshold`` over a curve's worst window. Windows with
    no samples don't count against the gate — an empty window is
    absence of evidence, not a 0-second latency."""

    name: str
    metric: str
    threshold: float
    description: str = ""

    def __post_init__(self):
        if self.metric not in CURVE_METRICS:
            raise ValueError(f"unknown SLO metric {self.metric!r}; "
                             f"expected one of {CURVE_METRICS}")

    def worst(self, curve: Sequence[Dict[str, Any]]
              ) -> Optional[float]:
        vals = [w[self.metric] for w in curve
                if w.get(self.metric) is not None]
        return max(vals) if vals else None

    def check(self, curve: Sequence[Dict[str, Any]]
              ) -> Dict[str, Any]:
        worst = self.worst(curve)
        ok = worst is None or worst <= self.threshold
        return {"slo": self.name, "metric": self.metric,
                "threshold": self.threshold,
                "worst": (round(worst, 6) if worst is not None
                          else None),
                "ok": bool(ok), "windows": len(curve)}


#: a serviceable default gate set — bench/tests tighten or loosen per
#: traffic model; thresholds here are deliberately generous so the
#: defaults only catch order-of-magnitude regressions
DEFAULT_SLOS = (
    SloSpec("admission_p99", "admission_p99", 30.0,
            "fresh submissions admitted within 30 s at p99"),
    SloSpec("queue_wait_p99", "queue_wait_p99", 60.0,
            "no tenant (incl. resumes) queued over 60 s at p99"),
    SloSpec("segment_p99", "segment_p99", 30.0,
            "scheduler segments under 30 s at p99"),
    SloSpec("shed_rate", "shed_rate", 0.05,
            "under 5% of offered load shed per window"),
    SloSpec("deadline_miss_rate", "deadline_miss_rate", 0.01,
            "under 1% of admitted arrivals miss their deadline"),
)


def evaluate_gates(curve: Sequence[Dict[str, Any]],
                   specs: Sequence[SloSpec] = DEFAULT_SLOS,
                   journal=None, **journal_ctx: Any
                   ) -> List[Dict[str, Any]]:
    """Check every spec against the curve's worst window; returns the
    gate dicts (``ok`` per spec). With a ``journal``
    (:class:`~deap_tpu.telemetry.journal.RunJournal`), each gate also
    lands as one ``slo_gate`` row (plus ``journal_ctx`` — e.g. the
    traffic-model name) so the verdicts ride the same artifact as the
    evidence."""
    gates = [spec.check(curve) for spec in specs]
    if journal is not None:
        for g in gates:
            journal.event("slo_gate", **g, **journal_ctx)
    return gates


# ---------------------------------------------------- attribution ----

def _span_phase(row: Dict[str, Any]) -> Optional[str]:
    """The attribution key of one ``trace_span`` row: the scheduler's
    per-tenant ``segment`` span keeps its name (its ``phase`` label is
    ``device``, but "the segment got slower" is the operator-facing
    statement); every other span attributes to its tracing-plane
    phase, falling back to its name."""
    name = row.get("name")
    if name == "segment":
        return "segment"
    return row.get("phase") or name


def phase_samples(rows: Iterable[Dict[str, Any]]
                  ) -> Dict[str, List[float]]:
    """Per-phase duration samples from a journal's ``trace_span``
    rows (see :func:`_span_phase` for the key)."""
    out: Dict[str, List[float]] = {}
    for r in rows:
        if r.get("kind") != "trace_span":
            continue
        phase = _span_phase(r)
        dur = r.get("dur_s")
        if phase is None or dur is None:
            continue
        out.setdefault(phase, []).append(float(dur))
    return out


def _end_to_end(rows: Iterable[Dict[str, Any]]) -> List[float]:
    """Per-tenant submit→finish wall seconds from the journal's
    monotonic ``t`` stamps."""
    start: Dict[str, float] = {}
    out: List[float] = []
    for r in rows:
        tid = r.get("tenant_id")
        if tid is None or not isinstance(r.get("t"), (int, float)):
            continue
        if r.get("kind") == "job_submitted":
            start.setdefault(tid, r["t"])
        elif r.get("kind") == "tenant_finished" and tid in start:
            out.append(r["t"] - start.pop(tid))
    return out


def attribute_regression(base_rows: Sequence[Dict[str, Any]],
                         probe_rows: Sequence[Dict[str, Any]],
                         q: float = 0.99) -> Dict[str, Any]:
    """Decompose the end-to-end latency delta between two runs into
    per-phase percentile deltas.

    ``base_rows``/``probe_rows`` are two journals' rows (baseline and
    suspect run of comparable workloads). End-to-end is per-tenant
    submit→finish; phases come from the trace spans (run both with
    ``trace_sample`` on). Returns the phase table sorted by delta
    descending plus ``top_phase`` — the named culprit ("``segment``
    +1.8 s at p99"), or ``None`` when nothing regressed."""
    base_pha = phase_samples(base_rows)
    probe_pha = phase_samples(probe_rows)
    table: List[Dict[str, Any]] = []
    for phase in sorted(set(base_pha) | set(probe_pha)):
        pa = exact_quantile(base_pha.get(phase, ()), q)
        pb = exact_quantile(probe_pha.get(phase, ()), q)
        delta = (pb or 0.0) - (pa or 0.0)
        table.append({"phase": phase,
                      "base_q": (round(pa, 6) if pa is not None
                                 else None),
                      "probe_q": (round(pb, 6) if pb is not None
                                  else None),
                      "delta_s": round(delta, 6),
                      "n_base": len(base_pha.get(phase, ())),
                      "n_probe": len(probe_pha.get(phase, ()))})
    table.sort(key=lambda r: r["delta_s"], reverse=True)
    e2e_a = exact_quantile(_end_to_end(base_rows), q)
    e2e_b = exact_quantile(_end_to_end(probe_rows), q)
    top = table[0] if table and table[0]["delta_s"] > 0 else None
    return {
        "q": q,
        "end_to_end_base": (round(e2e_a, 6) if e2e_a is not None
                            else None),
        "end_to_end_probe": (round(e2e_b, 6) if e2e_b is not None
                             else None),
        "end_to_end_delta": (round(e2e_b - e2e_a, 6)
                             if None not in (e2e_a, e2e_b) else None),
        "phases": table,
        "top_phase": (top["phase"] if top else None),
        "top_delta_s": (top["delta_s"] if top else None),
    }
