"""Program cost/memory observatory — what did XLA actually build?

Every per-generation claim this stack makes ultimately rests on
compiled XLA programs nobody can see: donation aliasing is asserted by
one bench row, retraces surface as wall-time cliffs, and cross-backend
comparisons (the Speed-Benchmarking-of-GP-frameworks critique,
PAPERS.md) are meaningless without per-program cost attribution. This
module intercepts the AOT seam every important program already goes
through — ``ShardingPlan.compile``, the resilience engine's segment
scan, the serving engine's batched advance — and records, per compiled
program:

- ``cost_analysis()`` — flops and bytes accessed (the roofline
  numerator per program, not per wall-clock anecdote);
- ``memory_analysis()`` — argument/output/temp bytes and **aliased
  (donated) bytes**: the PR 8 donation contract proven per program on
  every run, instead of once by ``bench.py --mesh``;
- compile wall seconds and an **HLO fingerprint** (sha1 of the lowered
  StableHLO text — deterministic for an identical program).

Each record is journaled as a ``program_profile`` event. The
fingerprint registry also catches the silent-retrace regression class:
when the same ``(label, input signature)`` compiles again to a
*different* HLO hash or cost, the observatory raises an ``hlo_drift``
alarm through the :class:`~deap_tpu.telemetry.probes.HealthMonitor`
(and journals it) — a shape-stable closure change that re-specialises a
program mid-run becomes an alarm, not an unexplained wall-time cliff.

Mechanically, :func:`instrument` wraps a jit-compiled callable: while
an observatory is **active** (``with ProgramObservatory(...):``), calls
route through an explicit ``.lower()`` → ``.compile()`` cache keyed on
the concrete input signature (tree structure, per-leaf
shape/dtype/sharding — at least as strict as jit's own cache), so the
executed program is the *same* executable jit would have built: results
are bit-identical, pinned by ``tests/test_costs.py``. With no active
observatory the wrapper is a single ``None`` check and a tail call —
the instrumented seams cost nothing when the observatory is off.

Usage::

    from deap_tpu.telemetry import ProgramObservatory

    with ProgramObservatory(journal=tel.journal, health=monitor) as obs:
        res = ResilientRun(ckdir, plan=plan, telemetry=tel)
        pop, logbook, hof = res.ea_simple(key, pop, tb, .5, .2, 100)
    obs.profiles   # one dict per compiled program (also journaled)
"""

from __future__ import annotations

import hashlib
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from deap_tpu.telemetry import tracing

__all__ = ["ProgramObservatory", "instrument", "observatory",
           "profile_compiled"]

#: the active observatory — one slot, module-global (the seams that
#: instrument their programs are constructed far from the run driver)
_ACTIVE: list = [None]

#: lazily-bound deap_tpu.support.artifacts module (imported on first
#: use, not at module import — support imports jax eagerly and this
#: module must stay cheap to import)
_ARTIFACTS: list = [None]


def _artifact_store():
    """The active executable artifact store, or None — the second
    activator (besides an observatory) of the explicit AOT path."""
    mod = _ARTIFACTS[0]
    if mod is None:
        from deap_tpu.support import artifacts as mod
        _ARTIFACTS[0] = mod
    return mod.active_store()


def observatory() -> Optional["ProgramObservatory"]:
    """The currently active observatory, or None."""
    return _ACTIVE[0]


def _leaf_descriptor(leaf: Any) -> Tuple:
    """A hashable signature for one argument leaf, at least as strict
    as jit's own cache key: arrays by shape/dtype/sharding (a committed
    array re-placed differently must re-lower — the compiled executable
    is layout-specific), everything else by repr."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        sharding = getattr(leaf, "sharding", None)
        return (tuple(shape), str(dtype),
                repr(sharding) if sharding is not None else "host")
    return ("py", repr(leaf))


def _hlo_fingerprint(lowered: Any) -> str:
    """sha1 of the lowered StableHLO text — deterministic for an
    identical traced program, different the moment a closure or shape
    change alters what XLA is asked to build."""
    return hashlib.sha1(
        lowered.as_text().encode("utf-8", "replace")).hexdigest()[:16]


def _cost_dict(compiled: Any) -> Dict[str, float]:
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    out = {}
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("optimal_seconds", "optimal_seconds")):
        v = ca.get(key) if hasattr(ca, "get") else None
        if isinstance(v, (int, float)):
            out[name] = float(v)
    return out


def _memory_dict(compiled: Any) -> Dict[str, int]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    out = {}
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "aliased_bytes"),
                       ("generated_code_size_in_bytes", "code_bytes")):
        v = getattr(ma, attr, None)
        if isinstance(v, int):
            out[name] = v
    return out


class ProgramObservatory:
    """Collects per-program compile profiles and drift alarms.

    :param journal: a :class:`~deap_tpu.telemetry.journal.RunJournal`
        to write ``program_profile`` / ``alarm`` events into; default
        broadcasts into every open journal (the ResilientRun pattern —
        subsystem seams must not depend on holding a journal).
    :param health: a :class:`~deap_tpu.telemetry.probes.HealthMonitor`;
        HLO drift fires its ``hlo_drift`` alarm (recorded, counted
        toward ``early_stop``, ``on_alarm`` invoked). Without one the
        drift still lands in the journal as an ``alarm`` event.
    :param on_profile: optional callback receiving each profile dict —
        the bench harness hook.

    Entering the context installs this observatory as the process-wide
    active one (instrumented seams check the active slot at call time);
    exiting restores the previous. :attr:`profiles` accumulates one
    dict per compiled program; :attr:`drifts` the drift alarms.
    """

    def __init__(self, journal=None, health=None,
                 on_profile: Optional[Callable] = None):
        self.journal = journal
        self.health = health
        self.on_profile = on_profile
        self.profiles: List[Dict[str, Any]] = []
        self.drifts: List[Dict[str, Any]] = []
        #: (label, signature) -> (hlo_hash, flops, bytes_accessed)
        self._fingerprints: Dict[Tuple, Tuple] = {}
        self._prev: Optional[ProgramObservatory] = None

    # ---------------------------------------------------------- lifecycle ----

    def __enter__(self) -> "ProgramObservatory":
        self._prev = _ACTIVE[0]
        _ACTIVE[0] = self
        return self

    def __exit__(self, *exc) -> None:
        _ACTIVE[0] = self._prev
        self._prev = None

    # ------------------------------------------------------------ plumbing ----

    def _journal(self, kind: str, **payload) -> None:
        if self.journal is not None:
            self.journal.event(kind, **payload)
        else:
            from deap_tpu.telemetry.journal import broadcast
            broadcast(kind, **payload)

    # ------------------------------------------------------------- record ----

    def record(self, label: str, lowered: Any, compiled: Any,
               compile_s: float, signature: Any = None,
               donating: bool = False) -> Dict[str, Any]:
        """Profile one freshly compiled program: journal its
        ``program_profile`` row and run drift detection against any
        earlier compile of the same ``(label, signature)``."""
        profile: Dict[str, Any] = {
            "label": str(label),
            "hlo_hash": _hlo_fingerprint(lowered),
            "compile_s": round(float(compile_s), 6),
            "donating": bool(donating),
        }
        profile.update(_cost_dict(compiled))
        profile.update(_memory_dict(compiled))
        # compiles that happen while serving a traced request carry
        # the trace/span ids, linking the HLO cost row into the
        # request's waterfall — and the compile itself becomes an
        # always-on span (a recompile on the hot path is exactly what
        # a latency investigation needs to see)
        ids = tracing.current_ids()
        if ids:
            profile.update(ids)
            tracing.emit_current("compile", compile_s, phase="compile",
                                 always=True, label=profile["label"],
                                 hlo_hash=profile["hlo_hash"])
        self.profiles.append(profile)
        self._journal("program_profile", **profile)
        if self.on_profile is not None:
            self.on_profile(profile)

        key = (profile["label"], signature)
        seen = self._fingerprints.get(key)
        fp = (profile["hlo_hash"], profile.get("flops"),
              profile.get("bytes_accessed"))
        if seen is not None and seen != fp:
            self._drift(profile, seen, fp)
        self._fingerprints[key] = fp
        return profile

    def record_error(self, label: str, exc: BaseException) -> None:
        self._journal("program_profile_error", label=str(label),
                      error=repr(exc)[:300])

    def _drift(self, profile: Dict[str, Any], seen: Tuple, fp: Tuple
               ) -> None:
        """The same (label, signature) compiled to a different program:
        the silent-retrace regression class, surfaced as an alarm."""
        detail = {
            "program": profile["label"],
            "prev_hlo_hash": seen[0], "hlo_hash": fp[0],
            "prev_flops": seen[1], "flops": fp[1],
            "prev_bytes_accessed": seen[2], "bytes_accessed": fp[2],
        }
        if self.health is not None:
            alarm = self.health.program_drift(**detail)
        else:
            alarm = {"alarm": "hlo_drift", "gen": None, **detail}
        self.drifts.append(alarm)
        self._journal("alarm", **alarm)
        # a drifted program invalidates its measured dispatch winners:
        # the tuning cache's timings belonged to the old HLO
        # (journaled per eviction as ``tuning_invalidation``)
        try:
            from deap_tpu import tuning
            tuning.note_hlo_drift(profile["label"])
        except Exception:
            pass


# --------------------------------------------------------- instrumenting ----

def profile_compiled(label: str, lowered: Any, compiled: Any,
                     compile_s: float, signature: Any = None,
                     donating: bool = False) -> Optional[Dict[str, Any]]:
    """Record an externally AOT-compiled program (a caller that already
    drives ``.lower()``/``.compile()`` itself — the bench harness) into
    the active observatory, if any."""
    obs = _ACTIVE[0]
    if obs is None:
        return None
    return obs.record(label, lowered, compiled, compile_s,
                      signature=signature, donating=donating)


class _InstrumentedFunction:
    """The wrapper :func:`instrument` returns. No active observatory
    and no active artifact store → two None-checks and a tail call
    into the wrapped jit. Either active → explicit
    ``.lower().compile()`` with a per-signature executable cache
    (bit-identical: the executable is the one jit would build), each
    compile profiled and drift-checked (observatory) and each HLO hash
    consulted against / persisted into the serialized-executable store
    (:mod:`deap_tpu.support.artifacts`) — the restart fast path."""

    def __init__(self, fn: Callable, label: str,
                 static_argnums: Tuple[int, ...] = (),
                 static_argnames: Tuple[str, ...] = (),
                 donating: bool = False):
        self._fn = fn
        self.label = str(label)
        self._static_argnums = tuple(int(i) for i in static_argnums)
        self._static_argnames = tuple(str(n) for n in static_argnames)
        self._donating = bool(donating)
        self._cache: Dict[Tuple, Any] = {}
        self._broken = False

    def __getattr__(self, name):
        # .lower / .clear_cache / __wrapped__ still reach the jit
        return getattr(self._fn, name)

    def _signature(self, args, kwargs) -> Tuple:
        import jax

        leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
        if any(isinstance(leaf, jax.core.Tracer) for leaf in leaves):
            # called under an enclosing trace (inlined into a larger
            # program): there is no standalone executable to profile —
            # bypass the AOT path for this call only
            raise TypeError("traced arguments")
        return (str(treedef),
                tuple(_leaf_descriptor(leaf) for leaf in leaves))

    def _strip_static(self, args, kwargs):
        """The compiled executable is specialised to its static
        arguments and is called WITHOUT them."""
        if self._static_argnums:
            args = tuple(a for i, a in enumerate(args)
                         if i not in self._static_argnums)
        if self._static_argnames:
            kwargs = {k: v for k, v in kwargs.items()
                      if k not in self._static_argnames}
        return args, kwargs

    def __call__(self, *args, **kwargs):
        obs = _ACTIVE[0]
        store = _artifact_store()
        if (obs is None and store is None) or self._broken:
            return self._fn(*args, **kwargs)
        try:
            sig = self._signature(args, kwargs)
        except Exception:
            return self._fn(*args, **kwargs)
        compiled = self._cache.get(sig)
        if compiled is None:
            from_artifact = False
            try:
                t0 = time.perf_counter()
                lowered = self._fn.lower(*args, **kwargs)
                hlo_hash = _hlo_fingerprint(lowered)
                # the artifact fast path: a serialized executable for
                # this exact HLO under this exact (backend, device
                # kind, jax version) loads instead of compiling — any
                # store failure returns None and the compile below
                # builds the bit-identical program
                if store is not None:
                    compiled = store.get(self.label, hlo_hash)
                    from_artifact = compiled is not None
                if compiled is None:
                    compiled = lowered.compile()
                compile_s = time.perf_counter() - t0
            except Exception as exc:
                # an exotic argument the AOT path can't take: profile
                # nothing, run the program — observability must never
                # take down the run it observes
                self._broken = True
                if obs is not None:
                    obs.record_error(self.label, exc)
                return self._fn(*args, **kwargs)
            if obs is not None:
                obs.record(self.label, lowered, compiled, compile_s,
                           signature=sig, donating=self._donating)
            if store is not None and not from_artifact:
                store.put(self.label, hlo_hash, compiled)
            self._cache[sig] = compiled
        call_args, call_kwargs = self._strip_static(args, kwargs)
        return compiled(*call_args, **call_kwargs)


def instrument(fn: Callable, label: str,
               static_argnums: Tuple[int, ...] = (),
               static_argnames=None,
               donating: bool = False) -> Callable:
    """Wrap a jit-compiled callable so the active observatory profiles
    every program it compiles (see :class:`_InstrumentedFunction`).
    ``static_argnums``/``static_argnames`` must mirror the jit's own —
    the compiled executable is called without its statics. ``donating``
    tags the profile rows (the donation-contract audit keys on it)."""
    return _InstrumentedFunction(
        fn, label, static_argnums=static_argnums,
        static_argnames=tuple(static_argnames or ()), donating=donating)
