"""Automatically Defined Functions — multi-branch tensor programs.

Counterpart of the reference's ADF machinery: ``PrimitiveSetTyped.addADF``
(/root/reference/deap/gp.py:414-423) and ``compileADF`` (gp.py:490-513),
where an individual is a *list* of trees — MAIN first, then the ADF
branches — and each branch's primitive set may invoke later branches as
ordinary primitives (examples/gp/adf_symbreg.py builds a 3-ADF ladder
this way).

Here an individual is a tuple of tensor genomes, one per branch. An ADF
call node in branch *i*'s prefix array evaluates branch *j* (``j > i``,
mirroring the reference's progressive-context compile order) on the
operand vectors at the call site — a nested stack-machine scan. Cost is
O(len_i · len_j) per call level, fully jit/vmap-safe, and — unlike the
reference's eval-of-nested-lambdas — depth-bounded by construction.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from deap_tpu.gp.interpreter import run_data_pass
from deap_tpu.gp.pset import PrimitiveSet
from deap_tpu.gp.tree import Genome, make_generator


Branches = Sequence[Tuple[PrimitiveSet, int]]   # [(pset, max_len), ...]


def _build_branch(pset: PrimitiveSet, max_len: int, branch_idx: int,
                  interps: dict, max_actives=None) -> Callable:
    """interp(genomes, X) for one branch; ADF nodes dispatch into
    ``interps`` (already built for every branch index > branch_idx).
    ``max_actives[i]`` optionally bounds branch *i*'s passes to its
    population's largest live prefix (gp/interpreter.py contract)."""
    prims = list(pset.primitives)
    ma = None if max_actives is None else max_actives[branch_idx]

    def interpret(genomes, X):
        # the shared two-pass core (gp/interpreter.py run_data_pass);
        # only the primitive evaluation differs — ADF call nodes
        # dispatch into the callee branch's interpreter
        def prim_rows(ops_in):
            rows = []
            for p in prims:
                if p.adf is None:
                    rows.append(p.fn(*ops_in[: p.arity]))
                else:
                    sub_X = jnp.stack(ops_in[: p.arity], axis=1)
                    rows.append(interps[p.adf](genomes, sub_X))
            return rows

        return run_data_pass(pset, max_len, genomes[branch_idx], X,
                             prim_rows, max_active=ma)

    return interpret


def _validate_branches(branches: Branches) -> None:
    for i, (pset, _) in enumerate(branches):
        for p in pset.primitives:
            if p.adf is None:
                continue
            if p.adf <= i:
                raise ValueError(
                    f"branch {i} calls branch {p.adf}; ADF calls must "
                    "target later branches (no recursion, matching the "
                    "reference's progressive compile order)")
            if p.adf >= len(branches):
                raise ValueError(
                    f"branch {i} calls branch {p.adf}, but only "
                    f"{len(branches)} branches were given")
            callee = branches[p.adf][0]
            if p.arity != callee.n_args:
                raise ValueError(
                    f"ADF call {p.name!r} passes {p.arity} operands but "
                    f"branch {p.adf} ({callee.name!r}) takes "
                    f"{callee.n_args} arguments")


def _link_branches(branches: Branches, max_actives=None) -> Callable:
    interps: dict = {}
    for i in reversed(range(len(branches))):
        pset, max_len = branches[i]
        interps[i] = _build_branch(pset, max_len, i, interps, max_actives)
    return interps[0]


def make_adf_interpreter(branches: Branches) -> Callable:
    """Build ``evaluate(genomes, X) -> f32[points]`` over a multi-branch
    individual. ``branches[0]`` is MAIN (compileADF's ``func``,
    gp.py:508-513); branch *i* may contain ``add_adf(..., branch=j)``
    nodes only for ``j > i``."""
    _validate_branches(branches)
    return _link_branches(branches)


def make_adf_batch_interpreter(branches: Branches) -> Callable:
    """``interpret(genomes, X) -> f32[pop, points]`` over a population
    of multi-branch individuals (a tuple of stacked branch genomes) —
    the ADF analog of ``gp.make_batch_interpreter``: every branch's
    passes are bounded to that branch's population-max live prefix
    ``T_i = max(length_i)``, closed over the vmapped call so the
    bounds stay unbatched (batch-uniform writes)."""
    _validate_branches(branches)

    def interpret_batch(genomes, X):
        Ts = tuple(
            jnp.clip(jnp.max(g["length"]), 1,
                     min(g["nodes"].shape[-1], ml)).astype(jnp.int32)
            for g, (_, ml) in zip(genomes, branches))
        main = _link_branches(branches, Ts)
        return jax.vmap(lambda gt: main(gt, X))(genomes)

    return interpret_batch


def make_adf_generator(branches: Branches, min_depth: int, max_depth: int,
                       mode: str = "half_and_half") -> Callable:
    """``gen(key) -> tuple of genomes`` — every branch generated with
    its own vocabulary (the reference initialises each subtree with its
    own pset's expr, examples/gp/adf_symbreg.py:44-56)."""
    gens = [make_generator(pset, max_len, min_depth, max_depth, mode)
            for pset, max_len in branches]

    def gen(key: jax.Array):
        keys = jax.random.split(key, len(gens))
        return tuple(g(k) for g, k in zip(gens, keys))

    return gen


def branch_wise_cx(cx_ops: List[Callable]) -> Callable:
    """Apply a crossover per branch pair — the ADF mating pattern
    (examples/gp/adf_symbreg.py:77-83: ``for tree1, tree2 in zip(ind1,
    ind2): toolbox.mate(tree1, tree2)``)."""

    def cx(key, g1, g2):
        keys = jax.random.split(key, len(cx_ops))
        outs = [op(k, a, b) for op, k, a, b in zip(cx_ops, keys, g1, g2)]
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    return cx


def branch_wise_mut(mut_ops: List[Callable]) -> Callable:
    """Apply a mutation per branch (adf_symbreg.py:85-89)."""

    def mut(key, g):
        keys = jax.random.split(key, len(mut_ops))
        return tuple(op(k, b) for op, k, b in zip(mut_ops, keys, g))

    return mut
