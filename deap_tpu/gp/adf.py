"""Automatically Defined Functions — multi-branch tensor programs.

Counterpart of the reference's ADF machinery: ``PrimitiveSetTyped.addADF``
(/root/reference/deap/gp.py:414-423) and ``compileADF`` (gp.py:490-513),
where an individual is a *list* of trees — MAIN first, then the ADF
branches — and each branch's primitive set may invoke later branches as
ordinary primitives (examples/gp/adf_symbreg.py builds a 3-ADF ladder
this way).

Here an individual is a tuple of tensor genomes, one per branch. An ADF
call node in branch *i*'s prefix array evaluates branch *j* (``j > i``,
mirroring the reference's progressive-context compile order) on the
operand vectors at the call site — a nested stack-machine scan. Cost is
O(len_i · len_j) per call level, fully jit/vmap-safe, and — unlike the
reference's eval-of-nested-lambdas — depth-bounded by construction.
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from deap_tpu.gp.interpreter import run_data_pass
from deap_tpu.gp.pset import PrimitiveSet
from deap_tpu.gp.tree import Genome, make_generator


Branches = Sequence[Tuple[PrimitiveSet, int]]   # [(pset, max_len), ...]


def _build_branch(pset: PrimitiveSet, max_len: int, branch_idx: int,
                  interps: dict, max_actives=None,
                  masks=None) -> Callable:
    """interp(genomes, X) for one branch; ADF nodes dispatch into
    ``interps`` (already built for every branch index > branch_idx).
    ``max_actives[i]`` optionally bounds branch *i*'s passes to its
    population's largest live prefix (gp/interpreter.py contract);
    ``masks[i]`` optionally restricts branch *i*'s dispatch to its live
    opcode subset (ids into ``pset.primitives`` — ADF call ids
    included, so an unused callee is neither evaluated nor recursed
    into)."""
    ids = (range(pset.n_ops) if masks is None or masks[branch_idx] is None
           else masks[branch_idx])
    prims = [(i, pset.primitives[i]) for i in ids]
    ma = None if max_actives is None else max_actives[branch_idx]

    def interpret(genomes, X):
        # the shared two-pass core (gp/interpreter.py run_data_pass);
        # only the primitive evaluation differs — ADF call nodes
        # dispatch into the callee branch's interpreter
        def prim_rows(ops_in):
            rows = []
            for i, p in prims:
                if p.adf is None:
                    rows.append((i, p.fn(*ops_in[: p.arity])))
                else:
                    sub_X = jnp.stack(ops_in[: p.arity], axis=1)
                    rows.append((i, interps[p.adf](genomes, sub_X)))
            return rows

        return run_data_pass(pset, max_len, genomes[branch_idx], X,
                             prim_rows, max_active=ma)

    return interpret


def _validate_branches(branches: Branches) -> None:
    for i, (pset, _) in enumerate(branches):
        for p in pset.primitives:
            if p.adf is None:
                continue
            if p.adf <= i:
                raise ValueError(
                    f"branch {i} calls branch {p.adf}; ADF calls must "
                    "target later branches (no recursion, matching the "
                    "reference's progressive compile order)")
            if p.adf >= len(branches):
                raise ValueError(
                    f"branch {i} calls branch {p.adf}, but only "
                    f"{len(branches)} branches were given")
            callee = branches[p.adf][0]
            if p.arity != callee.n_args:
                raise ValueError(
                    f"ADF call {p.name!r} passes {p.arity} operands but "
                    f"branch {p.adf} ({callee.name!r}) takes "
                    f"{callee.n_args} arguments")


def _link_branches(branches: Branches, max_actives=None,
                   masks=None) -> Callable:
    interps: dict = {}
    for i in reversed(range(len(branches))):
        pset, max_len = branches[i]
        interps[i] = _build_branch(pset, max_len, i, interps,
                                   max_actives, masks)
    return interps[0]


def make_adf_interpreter(branches: Branches) -> Callable:
    """Build ``evaluate(genomes, X) -> f32[points]`` over a multi-branch
    individual. ``branches[0]`` is MAIN (compileADF's ``func``,
    gp.py:508-513); branch *i* may contain ``add_adf(..., branch=j)``
    nodes only for ``j > i``."""
    _validate_branches(branches)
    return _link_branches(branches)


def make_adf_batch_interpreter(branches: Branches,
                               specialize: str = "auto") -> Callable:
    """``interpret(genomes, X) -> f32[pop, points]`` over a population
    of multi-branch individuals (a tuple of stacked branch genomes) —
    the ADF analog of ``gp.make_batch_interpreter``: every branch's
    passes are bounded to that branch's population-max live prefix
    ``T_i = max(length_i)``, closed over the vmapped call so the
    bounds stay unbatched (batch-uniform writes).

    ``specialize='auto'`` composes the live-vocab masks of
    ``gp.make_batch_interpreter`` with ADF dispatch: when called with
    concrete genomes, each branch's select-chain is compiled for that
    branch's live opcode subset — ADF call ids included, so a call
    primitive no live tree uses skips the whole callee recursion.
    Masks grow monotonically per interpreter (bounded recompiles);
    under tracing the full per-branch vocabularies are used.
    Bit-identical either way."""
    _validate_branches(branches)
    if specialize not in ("auto", "none"):
        raise ValueError(f"unknown specialize policy {specialize!r}")

    def _traced(masks):
        def interpret_batch(genomes, X):
            Ts = tuple(
                jnp.clip(jnp.max(g["length"]), 1,
                         min(g["nodes"].shape[-1], ml)).astype(jnp.int32)
                for g, (_, ml) in zip(genomes, branches))
            main = _link_branches(branches, Ts, masks)
            return jax.vmap(lambda gt: main(gt, X))(genomes)

        return interpret_batch

    base = _traced(None)
    if specialize == "none":
        return base

    from deap_tpu.gp.interpreter import _is_concrete, _used_ops

    state = {"masks": tuple(() for _ in branches), "cache": {}}

    def interpret_batch(genomes, X):
        leaves = [a for g in genomes
                  for a in (g["nodes"], g["consts"], g["length"])] + [X]
        if not _is_concrete(*leaves):
            return base(genomes, X)
        import numpy as np

        masks = []
        for prev, g, (ps, ml) in zip(state["masks"], genomes, branches):
            used = _used_ops(ps.n_ops, np.asarray(g["nodes"])[:, :ml],
                             np.asarray(g["length"]))
            masks.append(tuple(sorted(set(prev) | set(used))))
        state["masks"] = key = tuple(masks)
        fn = state["cache"].get(key)
        if fn is None:
            fn = state["cache"][key] = jax.jit(_traced(key))
            from deap_tpu.telemetry.journal import broadcast
            broadcast("gp_dispatch", mode="adf", mask=[
                [branches[i][0].primitives[j].name for j in m]
                for i, m in enumerate(key)])
        return fn(genomes, X)

    return interpret_batch


def make_adf_generator(branches: Branches, min_depth: int, max_depth: int,
                       mode: str = "half_and_half") -> Callable:
    """``gen(key) -> tuple of genomes`` — every branch generated with
    its own vocabulary (the reference initialises each subtree with its
    own pset's expr, examples/gp/adf_symbreg.py:44-56)."""
    gens = [make_generator(pset, max_len, min_depth, max_depth, mode)
            for pset, max_len in branches]

    def gen(key: jax.Array):
        keys = jax.random.split(key, len(gens))
        return tuple(g(k) for g, k in zip(gens, keys))

    return gen


def branch_wise_cx(cx_ops: List[Callable]) -> Callable:
    """Apply a crossover per branch pair — the ADF mating pattern
    (examples/gp/adf_symbreg.py:77-83: ``for tree1, tree2 in zip(ind1,
    ind2): toolbox.mate(tree1, tree2)``)."""

    def cx(key, g1, g2):
        keys = jax.random.split(key, len(cx_ops))
        outs = [op(k, a, b) for op, k, a, b in zip(cx_ops, keys, g1, g2)]
        return tuple(o[0] for o in outs), tuple(o[1] for o in outs)

    return cx


def branch_wise_mut(mut_ops: List[Callable]) -> Callable:
    """Apply a mutation per branch (adf_symbreg.py:85-89)."""

    def mut(key, g):
        keys = jax.random.split(key, len(mut_ops))
        return tuple(op(k, b) for op, k, b in zip(mut_ops, keys, g))

    return mut
