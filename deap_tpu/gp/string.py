"""Host-side tree display and parsing.

Counterpart of the reference's ``PrimitiveTree.__str__`` (stack-based
prefix→infix printer, /root/reference/deap/gp.py:90-104) and
``PrimitiveTree.from_string`` (gp.py:106-153) — for logging, debugging
and checkpoint round-trips. These run on host numpy arrays; the device
never needs strings.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from deap_tpu.gp.pset import PrimitiveSet


def to_string(genome, pset: PrimitiveSet) -> str:
    """Render a prefix-array genome as a readable expression."""
    nodes = np.asarray(genome["nodes"])
    consts = np.asarray(genome["consts"])
    length = int(genome["length"])

    def render(i: int) -> Tuple[str, int]:
        node = int(nodes[i])
        if node < pset.n_ops:
            prim = pset.primitives[node]
            args, j = [], i + 1
            for _ in range(prim.arity):
                s, j = render(j)
                args.append(s)
            return prim.format(*args), j
        return pset.node_name(node, consts[i]), i + 1

    if length == 0:
        return ""
    s, end = render(0)
    assert end == length, f"malformed prefix tree: used {end} of {length}"
    return s


def to_graph(genome, pset: PrimitiveSet):
    """``(nodes, edges, labels)`` for graph libraries — counterpart of
    the reference's ``gp.graph`` (/root/reference/deap/gp.py:1138-1208):
    node ids are prefix positions, ``edges`` are (parent, child) pairs,
    ``labels`` maps id → primitive/terminal name. Feed directly to
    ``networkx.Graph`` / pygraphviz exactly as the reference documents.
    """
    nodes_arr = np.asarray(genome["nodes"])
    consts = np.asarray(genome["consts"])
    length = int(genome["length"])
    arity = np.asarray(pset.arity_table())

    nodes = list(range(length))
    labels = {i: pset.node_name(int(nodes_arr[i]), consts[i])
              for i in range(length)}
    edges = []
    # prefix walk: a stack of (parent, remaining-children) mirrors the
    # reference's edge construction (gp.py:1199-1206)
    stack: list = []
    for i in range(length):
        if stack:
            parent = stack[-1][0]
            edges.append((parent, i))
            stack[-1][1] -= 1
            if stack[-1][1] == 0:
                stack.pop()
        a = int(arity[int(nodes_arr[i])])
        if a > 0:
            stack.append([i, a])
    return nodes, edges, labels


def from_string(expr: str, pset: PrimitiveSet, max_len: int):
    """Parse ``name(arg, ...)`` prefix syntax into a genome dict
    (gp.py:106-153). Tokens must name primitives, arguments, fixed
    terminals, or be numeric literals (stored as constants)."""
    import re

    tokens = re.split(r"[ \t\n\r\f\v(),]+", expr)
    tokens = [t for t in tokens if t]
    prim_by_name = {p.name: i for i, p in enumerate(pset.primitives)}
    arg_by_name = {n: pset.n_ops + i for i, n in enumerate(pset.arg_names)}
    const_by_name = {n: pset.const_id + i
                     for i, n in enumerate(pset.const_names)}

    nodes = np.full(max_len, pset.const_id, np.int32)
    consts = np.zeros(max_len, np.float32)
    for t, tok in enumerate(tokens):
        if t >= max_len:
            raise ValueError(f"expression longer than max_len={max_len}")
        if tok in prim_by_name:
            nodes[t] = prim_by_name[tok]
        elif tok in arg_by_name:
            nodes[t] = arg_by_name[tok]
        elif tok in const_by_name:
            nodes[t] = const_by_name[tok]
            consts[t] = pset.const_values[const_by_name[tok] - pset.const_id]
        else:
            try:
                value = float(tok)
            except ValueError:
                raise TypeError(
                    f"unknown symbol {tok!r} in expression") from None
            if pset.has_erc:
                nodes[t] = pset.erc_id
            else:
                # no ERC pool: a literal is only representable if it is
                # the value of a fixed terminal (otherwise the id would
                # alias that terminal's name while evaluating differently)
                matches = [i for i, v in enumerate(pset.const_values)
                           if v == value]
                if not matches:
                    raise ValueError(
                        f"literal {tok!r} is not a fixed terminal of "
                        f"{pset.name!r} and the set has no ephemeral "
                        f"constant to hold it")
                nodes[t] = pset.const_id + matches[0]
            consts[t] = value
    import jax.numpy as jnp

    return {"nodes": jnp.asarray(nodes), "consts": jnp.asarray(consts),
            "length": jnp.int32(len(tokens))}
