"""Strongly-typed GP — type constraints as static tables + masked draws.

Counterpart of the reference's ``PrimitiveSetTyped`` and the type-aware
generator/operators (/root/reference/deap/gp.py:260-429 for the set;
``generate`` type threading at gp.py:589-638; type-aware ``cxOnePoint``
at gp.py:645-682; same-signature ``mutNodeReplacement`` at gp.py:760-783;
typed ``mutInsert`` gp.py:814-851 and ``mutShrink`` gp.py:854-887).

Types are interned to dense int ids. The set compiles to three static
tables — ``arity_table`` (inherited), ``ret_type_table`` (int32[vocab])
and ``arg_type_table`` (int32[n_ops, max_arity]) — and every stochastic
draw becomes a masked uniform-score argmax over the eligible ids, which
is exactly a uniform draw over the eligible set and jit/vmap-safe.

Where the reference raises ``IndexError`` at generation time when a
required type has no terminal (gp.py:603-608), the tensor generator
validates the vocabulary once at build time.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet, _Primitive
from deap_tpu.gp.tree import Genome, _splice, subtree_end


class PrimitiveSetTyped(PrimitiveSet):
    """A primitive set whose nodes carry return/argument types.

    :param in_types: type names of the tree's input arguments.
    :param ret_type: type name the whole tree must return.

    All data still flows through one f32 stack row per slot (booleans are
    {0.0, 1.0} floats); types only constrain *structure*, as in the
    reference where the interpreter (Python eval) is also untyped and
    types exist purely in the generation/variation machinery.
    """

    def __init__(self, name: str, in_types: Sequence[str], ret_type: str,
                 prefix: str = "ARG"):
        super().__init__(name, len(in_types), prefix)
        self._types: dict = {}
        self.ret = self.type_id(ret_type)
        self.in_type_ids = [self.type_id(t) for t in in_types]
        self.prim_rets: list = []
        self.prim_args: list = []
        self.const_types: list = []
        self.erc_entries: list = []     # (name, sampler, type_id)

    # ------------------------------------------------------------- builder ----

    def type_id(self, name: str) -> int:
        if name not in self._types:
            self._types[name] = len(self._types)
        return self._types[name]

    @property
    def n_types(self) -> int:
        return len(self._types)

    def add_primitive(self, fn: Callable, in_types: Sequence[str],
                      ret_type: str, name: Optional[str] = None,
                      fmt: Optional[str] = None) -> None:
        """Register a typed operator (gp.py:325-346)."""
        assert len(in_types) >= 1, "arity should be >= 1"
        self.primitives.append(
            _Primitive(name or fn.__name__, fn, len(in_types), fmt))
        self.prim_rets.append(self.type_id(ret_type))
        self.prim_args.append([self.type_id(t) for t in in_types])

    def add_terminal(self, value: float, ret_type: str,
                     name: Optional[str] = None) -> None:
        """Register a typed constant terminal (gp.py:348-380)."""
        super().add_terminal(value, name)
        self.const_types.append(self.type_id(ret_type))

    def add_ephemeral_constant(self, name: str, sampler: Callable,
                               ret_type: str) -> None:
        """Register a typed ERC (gp.py:382-412); unlike the untyped set,
        a typed set may hold one ERC pool *per type*."""
        self.erc_entries.append((name, sampler, self.type_id(ret_type)))

    def add_adf(self, name: str, in_types: Sequence[str], ret_type: str,
                branch: int = None) -> None:
        """Typed ADF call (``PrimitiveSetTyped.addADF``, gp.py:414-423):
        the call node carries the callee's argument/return types so the
        typed tables stay aligned."""
        if branch is None:
            raise TypeError(
                "PrimitiveSetTyped.add_adf(name, in_types, ret_type, "
                "branch) — the branch index is required")
        super().add_adf(name, len(in_types), branch)
        self.prim_rets.append(self.type_id(ret_type))
        self.prim_args.append([self.type_id(t) for t in in_types])

    # -------------------------------------------------------------- layout ----

    @property
    def has_erc(self) -> bool:
        return bool(self.erc_entries)

    @property
    def n_ercs(self) -> int:
        return len(self.erc_entries)

    @property
    def vocab(self) -> int:
        return self.n_ops + self.n_args + self.n_consts + self.n_ercs

    @property
    def n_terminal_choices(self) -> int:
        return self.n_args + self.n_consts + self.n_ercs

    def node_name(self, node_id: int, const: float = 0.0) -> str:
        if node_id >= self.erc_id:
            return repr(round(float(const), 6))
        return super().node_name(node_id, const)

    # -------------------------------------------------------- static tables ----

    def ret_type_table(self) -> jnp.ndarray:
        """int32[vocab] — return type of every node id."""
        rets = (list(self.prim_rets) + list(self.in_type_ids)
                + list(self.const_types)
                + [t for (_, _, t) in self.erc_entries])
        return jnp.asarray(rets, jnp.int32)

    def arg_type_table(self) -> jnp.ndarray:
        """int32[n_ops, max_arity] — argument types per operator
        (padded with 0 past each arity)."""
        m = max(self.max_arity, 1)
        rows = [args + [0] * (m - len(args)) for args in self.prim_args]
        if not rows:
            rows = [[0] * m]
        return jnp.asarray(rows, jnp.int32)

    def _term_masks(self) -> np.ndarray:
        """bool[n_types, n_terminal_choices]."""
        n_t = max(self.n_terminal_choices, 1)
        mask = np.zeros((max(self.n_types, 1), n_t), bool)
        types = (list(self.in_type_ids) + list(self.const_types)
                 + [t for (_, _, t) in self.erc_entries])
        for j, t in enumerate(types):
            mask[t, j] = True
        return mask

    def _op_masks(self) -> np.ndarray:
        """bool[n_types, n_ops] — operators returning each type."""
        mask = np.zeros((max(self.n_types, 1), max(self.n_ops, 1)), bool)
        for j, t in enumerate(self.prim_rets):
            mask[t, j] = True
        return mask

    def validate(self) -> None:
        """Every type demanded anywhere (root, operator argument) must
        have at least one terminal — the build-time analog of the
        generator's IndexError (gp.py:603-608)."""
        term = self._term_masks().any(axis=1)
        demanded = {self.ret}
        for args in self.prim_args:
            demanded.update(args)
        names = {v: k for k, v in self._types.items()}
        for t in demanded:
            if not term[t]:
                raise ValueError(
                    f"type {names.get(t, t)!r} has no terminal; generation "
                    "would be unable to close a branch of this type")

    # --------------------------------------------------------- typed draws ----

    def sample_terminal_typed(self, key: jax.Array, type_: jnp.ndarray,
                              ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Uniform draw among terminals returning ``type_`` →
        (node_id, const_value)."""
        k_c, k_v = jax.random.split(key)
        n_t = max(self.n_terminal_choices, 1)
        mask = jnp.asarray(self._term_masks())[type_]
        scores = jax.random.uniform(k_c, (n_t,))
        choice = jnp.argmax(jnp.where(mask, scores, -1.0))
        vals = jnp.zeros((n_t,), jnp.float32)
        if self.n_consts:
            vals = vals.at[self.n_args:self.n_args + self.n_consts].set(
                jnp.asarray(self.const_values, jnp.float32))
        for j, (_, sampler, _t) in enumerate(self.erc_entries):
            vals = vals.at[self.n_args + self.n_consts + j].set(
                sampler(jax.random.fold_in(k_v, j)))
        node = (self.n_ops + choice).astype(jnp.int32)
        return node, vals[choice]

    def sample_op_typed(self, key: jax.Array, type_: jnp.ndarray,
                        room: Optional[jnp.ndarray] = None,
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Uniform draw among operators returning ``type_`` (and fitting
        ``room`` slots) → (op_id, found)."""
        n_o = max(self.n_ops, 1)
        mask = jnp.asarray(self._op_masks())[type_]
        if room is not None:
            mask = mask & (self.arity_table()[:n_o] <= room)
        scores = jax.random.uniform(key, (n_o,))
        op = jnp.argmax(jnp.where(mask, scores, -1.0)).astype(jnp.int32)
        return op, mask.any()


# ---------------------------------------------------------------- generator ----

def make_generator_typed(pset: PrimitiveSetTyped, max_len: int,
                         min_depth: int, max_depth: int,
                         mode: str = "half_and_half") -> Callable:
    """Typed tree generator: ``gen(key, ret_type=None) -> genome``.

    Tensor counterpart of the type-threading ``generate``
    (gp.py:589-638): the pending stack carries (depth, required type);
    children are pushed rightmost-first so the LIFO pop order walks the
    prefix left-to-right with each slot's required argument type.
    """
    if mode not in ("full", "grow", "half_and_half"):
        raise ValueError(mode)
    pset.validate()
    t_ratio = pset.terminal_ratio
    arity = pset.arity_table()
    arg_types = pset.arg_type_table()
    # same depth-capped scan bound as the untyped make_generator: a
    # depth-<=max_depth tree at arity <=a never needs more slots
    a = max(int(pset.max_arity), 1)
    depth_cap = (max_depth + 1 if a == 1
                 else (a ** (max_depth + 1) - 1) // (a - 1))
    scan_len = min(max_len, depth_cap)
    max_ar = max(pset.max_arity, 1)

    def gen(key: jax.Array, ret_type=None) -> Genome:
        root_t = jnp.int32(pset.ret if ret_type is None else ret_type)
        k_h, k_mode, k_scan = jax.random.split(key, 3)
        height = jax.random.randint(k_h, (), min_depth, max_depth + 1)
        if mode == "full":
            grow = jnp.bool_(False)
        elif mode == "grow":
            grow = jnp.bool_(True)
        else:
            grow = jax.random.bernoulli(k_mode, 0.5)

        nodes0 = jnp.full((max_len,), pset.const_id, jnp.int32)
        consts0 = jnp.zeros((max_len,), jnp.float32)
        dstack0 = jnp.zeros((max_len + 1,), jnp.int32)
        tstack0 = jnp.zeros((max_len + 1,), jnp.int32).at[0].set(root_t)

        def step(carry, inp):
            nodes, consts, dstack, tstack, sp, length = carry
            t, k = inp
            pending = sp > 0
            top = jnp.maximum(sp - 1, 0)
            d = dstack[top]
            ty = tstack[top]
            sp_pop = sp - 1

            k_t, k_term, k_op = jax.random.split(k, 3)
            room = max_len - t - sp_pop - 1
            force_term = (d >= height) | (room < 1)
            grow_term = grow & (d >= min_depth) & (
                jax.random.uniform(k_t) < t_ratio)
            op_node, has_op = pset.sample_op_typed(k_op, ty, room)
            is_term = force_term | grow_term | ~has_op

            term_node, term_val = pset.sample_terminal_typed(k_term, ty)
            node = jnp.where(is_term, term_node, op_node)
            val = jnp.where(is_term, term_val, 0.0)

            nodes = jnp.where(pending, nodes.at[t].set(node), nodes)
            consts = jnp.where(pending, consts.at[t].set(val), consts)
            ar = jnp.where(is_term, 0, arity[op_node])
            idx = jnp.arange(max_len + 1)
            push = (idx >= sp_pop) & (idx < sp_pop + ar)
            # slot sp_pop+j receives arg ar-1-j: leftmost arg on top
            child_arg = jnp.clip(ar - 1 - (idx - sp_pop), 0, max_ar - 1)
            child_t = arg_types[op_node][child_arg]
            dstack = jnp.where(pending & push, d + 1, dstack)
            tstack = jnp.where(pending & push, child_t, tstack)
            sp = jnp.where(pending, sp_pop + ar, sp)
            length = length + pending.astype(jnp.int32)
            return (nodes, consts, dstack, tstack, sp, length), None

        keys = jax.random.split(k_scan, scan_len)
        init = (nodes0, consts0, dstack0, tstack0, jnp.int32(1),
                jnp.int32(0))
        (nodes, consts, _, _, _, length), _ = lax.scan(
            step, init, (jnp.arange(scan_len), keys))
        return {"nodes": nodes, "consts": consts, "length": length}

    return gen


# ---------------------------------------------------------------- crossover ----

def make_cx_one_point_typed(pset: PrimitiveSetTyped) -> Callable:
    """Type-aware one-point crossover (gp.py:645-682): the swap points
    must have equal return types; when the parents share no common type
    below the root, both pass through unchanged."""
    arity = pset.arity_table()
    rett = pset.ret_type_table()

    def cx(key: jax.Array, g1: Genome, g2: Genome) -> Tuple[Genome, Genome]:
        k1, k2 = jax.random.split(key)
        L = g1["nodes"].shape[0]
        idx = jnp.arange(L)
        in1 = (idx >= 1) & (idx < g1["length"])
        in2 = (idx >= 1) & (idx < g2["length"])
        t1 = rett[g1["nodes"]]
        t2 = rett[g2["nodes"]]
        # eligible in g1: some node of the same type exists in g2
        match = (t1[:, None] == t2[None, :]) & in2[None, :]
        elig1 = in1 & match.any(axis=1)
        ok = elig1.any()
        s1 = jax.random.uniform(k1, (L,))
        i1 = jnp.argmax(jnp.where(elig1, s1, -1.0))
        elig2 = in2 & (t2 == t1[i1])
        s2 = jax.random.uniform(k2, (L,))
        i2 = jnp.argmax(jnp.where(elig2, s2, -1.0))
        e1 = subtree_end(g1["nodes"], arity, i1)
        e2 = subtree_end(g2["nodes"], arity, i2)
        c1 = _splice(g1, i1, e1, g2["nodes"], g2["consts"], i2, e2 - i2)
        c2 = _splice(g2, i2, e2, g1["nodes"], g1["consts"], i1, e1 - i1)

        def pick(child, parent):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), child, parent)

        return pick(c1, g1), pick(c2, g2)

    return cx


# ---------------------------------------------------------------- mutations ----

def make_mut_uniform_typed(pset: PrimitiveSetTyped, expr: Callable) -> Callable:
    """Typed subtree replacement (mutUniform, gp.py:743-757): the fresh
    expression is generated with the replaced subtree's return type.
    ``expr`` must accept ``(key, ret_type)`` — see
    :func:`make_generator_typed`."""
    arity = pset.arity_table()
    rett = pset.ret_type_table()

    def mut(key: jax.Array, g: Genome) -> Genome:
        k_i, k_e = jax.random.split(key)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        e = subtree_end(g["nodes"], arity, i)
        new = expr(k_e, rett[g["nodes"][i]])
        return _splice(g, i, e, new["nodes"], new["consts"], 0,
                       new["length"])

    return mut


def make_mut_node_replacement_typed(pset: PrimitiveSetTyped) -> Callable:
    """Same-signature node replacement (mutNodeReplacement,
    gp.py:760-783): terminals are redrawn among terminals of the same
    type; operators among operators with identical (ret, args)
    signature."""
    arity = pset.arity_table()
    rett = pset.ret_type_table()
    n_o = max(pset.n_ops, 1)
    sig_groups: dict = {}
    sig_mask = np.zeros((n_o, n_o), bool)
    for j, (r, args) in enumerate(zip(pset.prim_rets, pset.prim_args)):
        sig_groups.setdefault((r, tuple(args)), []).append(j)
    for members in sig_groups.values():
        for a in members:
            for b in members:
                sig_mask[a, b] = True
    sig_mask_j = jnp.asarray(sig_mask)

    def mut(key: jax.Array, g: Genome) -> Genome:
        k_i, k_t, k_o = jax.random.split(key, 3)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        node = g["nodes"][i]
        is_term = arity[node] == 0
        term_node, term_val = pset.sample_terminal_typed(k_t, rett[node])
        scores = jax.random.uniform(k_o, (n_o,))
        row = sig_mask_j[jnp.clip(node, 0, n_o - 1)]
        op_node = jnp.argmax(jnp.where(row, scores, -1.0)).astype(jnp.int32)
        new_node = jnp.where(is_term, term_node, op_node)
        new_val = jnp.where(is_term, term_val, g["consts"][i])
        return {
            "nodes": g["nodes"].at[i].set(new_node),
            "consts": g["consts"].at[i].set(new_val),
            "length": g["length"],
        }

    return mut


def make_mut_ephemeral_typed(pset: PrimitiveSetTyped,
                             mode: str = "one") -> Callable:
    """Typed ERC resampling (mutEphemeral, gp.py:786-811) over every ERC
    pool; each node redraws from its own pool's sampler."""
    if not pset.has_erc:
        raise ValueError("primitive set has no ephemeral constant")
    if mode not in ("one", "all"):
        raise ValueError(mode)
    first_erc = pset.erc_id

    def mut(key: jax.Array, g: Genome) -> Genome:
        L = g["nodes"].shape[0]
        k_pick, k_val = jax.random.split(key)
        is_erc = (g["nodes"] >= first_erc) & (jnp.arange(L) < g["length"])
        new_vals = g["consts"]
        for j, (_, sampler, _t) in enumerate(pset.erc_entries):
            draws = jax.vmap(sampler)(
                jax.random.split(jax.random.fold_in(k_val, j), L))
            new_vals = jnp.where(g["nodes"] == first_erc + j, draws,
                                 new_vals)
        if mode == "one":
            scores = jax.random.uniform(k_pick, (L,))
            chosen = jnp.argmax(jnp.where(is_erc, scores, -1.0))
            target = is_erc & (jnp.arange(L) == chosen)
        else:
            target = is_erc
        return {
            "nodes": g["nodes"],
            "consts": jnp.where(target, new_vals, g["consts"]),
            "length": g["length"],
        }

    return mut


def make_mut_insert_typed(pset: PrimitiveSetTyped) -> Callable:
    """Typed insertion (mutInsert, gp.py:814-851): the new operator must
    return the chosen subtree's type and accept it among its arguments;
    remaining arguments are fresh terminals of the operator's declared
    argument types. No eligible operator → unchanged."""
    arity = pset.arity_table()
    rett = pset.ret_type_table()
    arg_types = pset.arg_type_table()
    max_ar = max(pset.max_arity, 1)
    n_o = max(pset.n_ops, 1)
    # accepts[j, t] — operator j has some argument of type t
    n_ty = max(pset.n_types, 1)
    accepts = np.zeros((n_o, n_ty), bool)
    for j, args in enumerate(pset.prim_args):
        for t in args:
            accepts[j, t] = True
    accepts_j = jnp.asarray(accepts)
    op_ret = jnp.asarray(
        (pset.prim_rets or [0]), jnp.int32)

    def mut(key: jax.Array, g: Genome) -> Genome:
        L = g["nodes"].shape[0]
        k_i, k_op, k_slot, k_terms = jax.random.split(key, 4)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        t = rett[g["nodes"][i]]
        e = subtree_end(g["nodes"], arity, i)
        seg = e - i
        mask = (op_ret == t) & accepts_j[:, t]
        found = mask.any()
        scores = jax.random.uniform(k_op, (n_o,))
        op = jnp.argmax(jnp.where(mask, scores, -1.0)).astype(jnp.int32)
        ar = arity[op]
        # choose the argument slot (of type t) receiving the old subtree
        slot_ok = (arg_types[op] == t) & (jnp.arange(max_ar) < ar)
        s = jax.random.uniform(k_slot, (max_ar,))
        pos = jnp.argmax(jnp.where(slot_ok, s, -1.0))
        t_draws = [pset.sample_terminal_typed(
            jax.random.fold_in(k_terms, j), arg_types[op][j])
            for j in range(max_ar)]
        t_nodes = jnp.stack([n for n, _ in t_draws])
        t_vals = jnp.stack([v for _, v in t_draws])

        DL = 1 + max_ar + L
        k = jnp.arange(DL)
        donor_nodes = jnp.zeros((DL,), jnp.int32).at[0].set(op)
        donor_consts = jnp.zeros((DL,), jnp.float32)
        in_pre = (k >= 1) & (k < 1 + pos)
        in_sub = (k >= 1 + pos) & (k < 1 + pos + seg)
        in_post = (k >= 1 + pos + seg) & (k < 1 + seg + ar - 1)
        src_term_pre = jnp.clip(k - 1, 0, max_ar - 1)
        src_sub = jnp.clip(i + k - 1 - pos, 0, L - 1)
        # arg index at post position k: pos pre-terminals + the subtree
        # + offset past it = k - seg
        src_term_post = jnp.clip(k - seg, 0, max_ar - 1)
        donor_nodes = jnp.where(
            in_pre, t_nodes[src_term_pre], jnp.where(
                in_sub, g["nodes"][src_sub], jnp.where(
                    in_post, t_nodes[src_term_post], donor_nodes)))
        donor_consts = jnp.where(
            in_pre, t_vals[src_term_pre], jnp.where(
                in_sub, g["consts"][src_sub], jnp.where(
                    in_post, t_vals[src_term_post], donor_consts)))
        out = _splice(g, i, e, donor_nodes, donor_consts, 0,
                      1 + (ar - 1) + seg)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(found, a, b), out, g)

    return mut


def make_mut_shrink_typed(pset: PrimitiveSetTyped) -> Callable:
    """Typed shrink (mutShrink, gp.py:854-887): collapse an operator
    onto one of its argument subtrees *of the same return type*."""
    arity = pset.arity_table()
    rett = pset.ret_type_table()
    arg_types = pset.arg_type_table()
    max_ar = max(pset.max_arity, 1)
    n_o = max(pset.n_ops, 1)
    # shrinkable[j]: operator j returns a type it also accepts
    shrinkable = np.zeros((n_o,), bool)
    for j, (r, args) in enumerate(zip(pset.prim_rets, pset.prim_args)):
        shrinkable[j] = r in args
    shrinkable_j = jnp.asarray(shrinkable)

    def mut(key: jax.Array, g: Genome) -> Genome:
        L = g["nodes"].shape[0]
        k_i, k_c = jax.random.split(key)
        idx = jnp.arange(L)
        in_tree = (idx >= 1) & (idx < g["length"])
        node_ok = (arity[g["nodes"]] > 0) & in_tree & shrinkable_j[
            jnp.clip(g["nodes"], 0, n_o - 1)]
        has = node_ok.any() & (g["length"] >= 3)
        scores = jax.random.uniform(k_i, (L,))
        i = jnp.argmax(jnp.where(node_ok, scores, -1.0))
        op = g["nodes"][i]
        ar = arity[op]
        t = rett[op]
        ok_child = (arg_types[op] == t) & (jnp.arange(max_ar) < ar)
        s = jax.random.uniform(k_c, (max_ar,))
        child = jnp.argmax(jnp.where(ok_child, s, -1.0))

        def walk(j, start):
            return jnp.where(j < child,
                             subtree_end(g["nodes"], arity, start), start)

        c_begin = lax.fori_loop(0, max_ar, walk, i + 1)
        c_end = subtree_end(g["nodes"], arity, c_begin)
        e = subtree_end(g["nodes"], arity, i)
        out = _splice(g, i, e, g["nodes"], g["consts"], c_begin,
                      c_end - c_begin)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(has, a, b), out, g)

    return mut


# ------------------------------------------------------------ stock vocab ----

def spam_set(n_features: int = 2) -> PrimitiveSetTyped:
    """A bool/float typed vocabulary in the mold of the reference's
    spambase example (examples/gp/spambase.py:26-49): float comparisons
    feed boolean logic feeding an if-then-else over floats."""
    ps = PrimitiveSetTyped("SPAM", ["float"] * n_features, "bool")
    ps.add_primitive(lambda a, b: (a * b), ["bool", "bool"], "bool", "and_",
                     "({0} & {1})")
    ps.add_primitive(lambda a, b: jnp.minimum(a + b, 1.0),
                     ["bool", "bool"], "bool", "or_", "({0} | {1})")
    ps.add_primitive(lambda a: 1.0 - a, ["bool"], "bool", "not_", "(~{0})")
    ps.add_primitive(lambda a, b: (a < b).astype(jnp.float32),
                     ["float", "float"], "bool", "lt", "({0} < {1})")
    ps.add_primitive(lambda a, b: (a == b).astype(jnp.float32),
                     ["float", "float"], "bool", "eq", "({0} == {1})")
    ps.add_primitive(jnp.add, ["float", "float"], "float", "add",
                     "({0} + {1})")
    ps.add_primitive(jnp.subtract, ["float", "float"], "float", "sub",
                     "({0} - {1})")
    ps.add_primitive(jnp.multiply, ["float", "float"], "float", "mul",
                     "({0} * {1})")
    ps.add_primitive(lambda c, a, b: jnp.where(c > 0.5, a, b),
                     ["bool", "float", "float"], "float", "if_then_else")
    ps.add_terminal(0.0, "bool", "False")
    ps.add_terminal(1.0, "bool", "True")
    ps.add_ephemeral_constant(
        "rand100",
        lambda k: jax.random.uniform(k, (), minval=0.0, maxval=100.0),
        "float")
    return ps
