"""Genetic programming over tensor prefix trees.

Counterpart of /root/reference/deap/gp.py, re-designed for TPUs: trees
are fixed-width prefix arrays, evaluation is a batched stack interpreter
(one XLA program for the whole population × all datapoints — replacing
the reference's per-individual string-codegen ``eval``, gp.py:462-487),
and the generators / crossovers / mutations are pure index arithmetic
usable inside jit (SURVEY.md §7.2 item 8).
"""

from deap_tpu.gp.interpreter import (make_batch_interpreter,
                                     make_interpreter,
                                     make_population_evaluator)
from deap_tpu.gp.pset import (
    PrimitiveSet,
    bool_set,
    math_set,
    protected_div,
)
from deap_tpu.gp.tree import (
    Genome,
    gen_full,
    gen_grow,
    gen_half_and_half,
    make_cx_one_point,
    make_cx_one_point_leaf_biased,
    make_generator,
    make_mut_ephemeral,
    make_mut_insert,
    make_mut_node_replacement,
    make_mut_shrink,
    make_mut_uniform,
    static_limit,
    subtree_end,
    tree_height,
)
from deap_tpu.gp.string import from_string, to_graph, to_string
from deap_tpu.gp.typed import (
    PrimitiveSetTyped,
    make_cx_one_point_typed,
    make_generator_typed,
    make_mut_ephemeral_typed,
    make_mut_insert_typed,
    make_mut_node_replacement_typed,
    make_mut_shrink_typed,
    make_mut_uniform_typed,
    spam_set,
)
from deap_tpu.gp.adf import (
    branch_wise_cx,
    branch_wise_mut,
    make_adf_generator,
    make_adf_batch_interpreter,
    make_adf_interpreter,
)
from deap_tpu.gp.semantic import (
    add_semantic_primitives,
    logistic,
    make_cx_semantic,
    make_mut_semantic,
)
from deap_tpu.gp.harm import harm
from deap_tpu.gp.loop import make_gp_loop, make_symbreg_loop
from deap_tpu.gp import ant, loop

__all__ = [
    "PrimitiveSetTyped",
    "make_generator_typed",
    "make_cx_one_point_typed",
    "make_mut_uniform_typed",
    "make_mut_node_replacement_typed",
    "make_mut_ephemeral_typed",
    "make_mut_insert_typed",
    "make_mut_shrink_typed",
    "spam_set",
    "make_adf_batch_interpreter",
    "make_adf_interpreter",
    "make_adf_generator",
    "branch_wise_cx",
    "branch_wise_mut",
    "add_semantic_primitives",
    "logistic",
    "make_mut_semantic",
    "make_cx_semantic",
    "harm",
    "Genome",
    "PrimitiveSet",
    "bool_set",
    "math_set",
    "protected_div",
    "make_batch_interpreter",
    "make_interpreter",
    "make_population_evaluator",
    "make_gp_loop",
    "make_symbreg_loop",
    "make_generator",
    "gen_full",
    "gen_grow",
    "gen_half_and_half",
    "make_cx_one_point",
    "make_cx_one_point_leaf_biased",
    "make_mut_uniform",
    "make_mut_node_replacement",
    "make_mut_ephemeral",
    "make_mut_insert",
    "make_mut_shrink",
    "static_limit",
    "subtree_end",
    "tree_height",
    "to_string",
    "to_graph",
    "from_string",
]

# DEAP-style aliases
genFull = gen_full
genGrow = gen_grow
genHalfAndHalf = gen_half_and_half
staticLimit = static_limit
