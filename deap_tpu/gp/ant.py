"""The Koza artificial ant — batched toroidal-grid rollouts.

Counterpart of the reference's ant example (/root/reference/examples/gp/
ant.py:75-150 pure-Python ``AntSimulator``, and the C++ fast path
``AntSimulatorFast.cpp`` whose native equivalent lives in
``deap_tpu/native/src/ant.cpp``): a GP *action* tree over
``if_food_ahead``/``prog2``/``prog3`` with ``move_forward``/
``turn_left``/``turn_right`` terminals is executed repeatedly on a
toroidal grid until ``max_moves`` (543) moves are spent; fitness is the
food eaten (89 pieces on the Santa Fe trail, ant.py:26-46).

Unlike the data-flow stack interpreter (interpreter.py), an action tree
is executed for its *side effects*: the rollout walks the prefix array
with an explicit program-counter stack inside ``lax.while_loop`` —
``prog`` nodes push all children, ``if_food_ahead`` pushes only the
branch selected by the food sensor, terminals mutate the ant state.
``vmap`` over the population turns the whole evaluation into one XLA
program (the idiomatic TPU path; the C++ simulator serves the
host/native pattern the reference demonstrates with
AntSimulatorFast.cpp).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet
from deap_tpu.gp.tree import subtree_end

# The Santa Fe trail (Koza 1992): 32×32 torus, 89 food cells, start at
# the S corner facing east. Data layout matches the reference fixture
# (examples/gp/ant/santafe_trail.txt; its row-25 stray space is read as
# an empty cell, where the reference's parser silently drops the column).
SANTA_FE_TRAIL = """\
S###............................
...#............................
...#.....................###....
...#....................#....#..
...#....................#....#..
...####.#####........##.........
............#................#..
............#.......#...........
............#.......#........#..
............#.......#...........
....................#...........
............#................#..
............#...................
............#.......#.....###...
............#.......#..#........
.................#..............
................................
............#...........#.......
............#...#..........#....
............#...#...............
............#...#...............
............#...#.........#.....
............#..........#........
............#...................
...##..#####....#...............
.#..............#...............
.#..............#...............
.#......#######.................
.#.....#........................
.......#........................
..####..........................
................................"""

# op ids by registration order in ant_pset()
IF_FOOD_AHEAD, PROG2, PROG3 = 0, 1, 2
MOVE_FORWARD, TURN_LEFT, TURN_RIGHT = 0, 1, 2   # terminal action codes

# direction vectors indexed north/east/south/west (ant.py:76-78)
_DIR_ROW = jnp.asarray([1, 0, -1, 0], jnp.int32)
_DIR_COL = jnp.asarray([0, 1, 0, -1], jnp.int32)


def ant_pset() -> PrimitiveSet:
    """The ant vocabulary (ant.py:150-160): if_food_ahead(2), prog2(2),
    prog3(3); terminals move_forward / turn_left / turn_right. The
    primitive fns are placeholders — ant trees are executed by
    :func:`make_ant_evaluator`, never by the data-flow interpreter."""
    ps = PrimitiveSet("ANT", 0)
    dummy2 = lambda a, b: a
    dummy3 = lambda a, b, c: a
    ps.add_primitive(dummy2, 2, "if_food_ahead")
    ps.add_primitive(dummy2, 2, "prog2")
    ps.add_primitive(dummy3, 3, "prog3")
    ps.add_terminal(float(MOVE_FORWARD), "move_forward")
    ps.add_terminal(float(TURN_LEFT), "turn_left")
    ps.add_terminal(float(TURN_RIGHT), "turn_right")
    return ps


def parse_trail(text: str = SANTA_FE_TRAIL,
                ) -> Tuple[np.ndarray, Tuple[int, int]]:
    """Trail text → (bool food grid [R, C], start (row, col)). ``#`` is
    food, ``S`` the start cell (empty), anything else empty
    (ant.py:128-146)."""
    lines = text.splitlines()
    rows, cols = len(lines), max(len(l) for l in lines)
    grid = np.zeros((rows, cols), bool)
    start = (0, 0)
    for i, line in enumerate(lines):
        for j, ch in enumerate(line):
            if ch == "#":
                grid[i, j] = True
            elif ch == "S":
                start = (i, j)
    return grid, start


def make_ant_evaluator(pset: PrimitiveSet, max_len: int,
                       trail: np.ndarray, start: Tuple[int, int],
                       max_moves: int = 600,
                       start_dir: int = 1) -> Callable:
    """Build ``evaluate(genome) -> eaten`` (vmap over genomes for the
    population). Semantics follow AntSimulator: actions only spend a
    move while ``moves < max_moves`` (ant.py:97-113); eaten cells are
    cleared; the routine restarts from the root whenever it completes
    (run(), ant.py:123-126)."""
    arity = np.asarray(pset.arity_table())
    n_ops = pset.n_ops
    const_id = pset.const_id
    arity_j = jnp.asarray(arity)
    trail_j = jnp.asarray(trail)
    R, C = trail.shape
    r0, c0 = start
    # safety bound on executed nodes: each routine pass executes >= 1
    # action and costs <= max_len pops
    max_steps = max_moves * max_len + max_len

    def evaluate(genome) -> jnp.ndarray:
        nodes = genome["nodes"]
        L = nodes.shape[0]
        # precompute every subtree end once — the while_loop body would
        # otherwise redo the O(L) arity walk on loop-invariant data at
        # every executed node
        ends = jax.vmap(lambda i: subtree_end(nodes, arity_j, i))(
            jnp.arange(L))

        def ahead(row, col, d):
            return ((row + _DIR_ROW[d]) % R, (col + _DIR_COL[d]) % C)

        def body(state):
            stack, sp, row, col, d, moves, eaten, grid, steps = state
            # empty stack → restart the routine from the root
            restart = sp == 0
            stack = jnp.where(restart, stack.at[0].set(0), stack)
            sp = jnp.where(restart, 1, sp)

            node_idx = stack[sp - 1]
            node = nodes[node_idx]
            sp = sp - 1
            is_op = node < n_ops
            action = jnp.where(is_op, -1, node - const_id)

            # --- operators: push children (reverse order → leftmost on
            # top). child k+1 starts where child k's subtree closes
            # (precomputed searchSubtree arity walk).
            c1 = node_idx + 1
            c2 = ends[jnp.minimum(c1, L - 1)]
            c3 = ends[jnp.minimum(c2, L - 1)]

            # if_food_ahead: sense and choose branch (ant.py:115-121)
            ar_, ac = ahead(row, col, d)
            food_ahead = grid[ar_, ac]
            chosen = jnp.where(food_ahead, c1, c2)

            push_if = is_op & (node == IF_FOOD_AHEAD)
            push2 = is_op & (node == PROG2)
            push3 = is_op & (node == PROG3)

            # prog3: push c3, c2, c1; prog2: push c2, c1; if: push chosen
            stack = jnp.where(push3, stack.at[sp].set(c3), stack)
            sp3 = sp + push3.astype(jnp.int32)
            stack = jnp.where(push2 | push3, stack.at[sp3].set(c2), stack)
            sp2 = sp3 + (push2 | push3).astype(jnp.int32)
            stack = jnp.where(push2 | push3, stack.at[sp2].set(c1),
                              jnp.where(push_if, stack.at[sp2].set(chosen),
                                        stack))
            sp = sp2 + (push2 | push3 | push_if).astype(jnp.int32)

            # --- terminal actions (ant.py:97-113): spend a move only
            # while budget remains
            can = (~is_op) & (moves < max_moves)
            moves = jnp.where(can, moves + 1, moves)
            d = jnp.where(can & (action == TURN_LEFT), (d - 1) % 4,
                          jnp.where(can & (action == TURN_RIGHT),
                                    (d + 1) % 4, d))
            fwd = can & (action == MOVE_FORWARD)
            nr = (row + _DIR_ROW[d]) % R
            nc = (col + _DIR_COL[d]) % C
            row = jnp.where(fwd, nr, row)
            col = jnp.where(fwd, nc, col)
            ate = fwd & grid[row, col]
            eaten = eaten + ate.astype(jnp.int32)
            grid = jnp.where(ate, grid.at[row, col].set(False), grid)

            return (stack, sp, row, col, d, moves, eaten, grid, steps + 1)

        def cond(state):
            _, _, _, _, _, moves, _, _, steps = state
            return (moves < max_moves) & (steps < max_steps)

        init = (jnp.zeros((L + 3,), jnp.int32), jnp.int32(0),
                jnp.int32(r0), jnp.int32(c0), jnp.int32(start_dir),
                jnp.int32(0), jnp.int32(0), trail_j, jnp.int32(0))
        out = lax.while_loop(cond, body, init)
        return out[6].astype(jnp.float32)

    return evaluate
