"""HARM-GP bloat control (Gardner, Gagné & Parizeau 2015).

Counterpart of the reference's ``gp.harm`` (/root/reference/deap/gp.py:
938-1135): each generation (1) models the *natural* size distribution by
generating a large trial offspring population, (2) smooths it with a
small discrete kernel (weights 0.4/0.2/0.2/0.1/0.1 at offsets
0/±1/±2, gp.py:1080-1089), (3) picks a cutoff size from the sizes of the
fittest (1−rho) tail (gp.py:1091-1097), (4) shapes a target
distribution that decays exponentially past the cutoff with half-life
``alpha·size + beta`` (gp.py:1099-1107), and (5) produces the real
offspring by accepting trial individuals with probability
target/natural of their size (gp.py:1109-1117).

The accept-reject stream of the reference (host Python, one aspirant at
a time, gp.py:993-1043) is replaced by a batched formulation: the trial
population *is* the acceptance pool, and the offspring are drawn by
Gumbel top-k over acceptance-weighted scores — accepted sizes follow
the same target distribution, with no per-individual host dispatch. The
per-generation cutoff/histogram scalars are data-dependent, so the
generation loop runs on host around jit-compiled kernels (SURVEY.md
§7.3).
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp

from deap_tpu.algorithms import evaluate_invalid
from deap_tpu.core.population import Population, gather
from deap_tpu.support.hof import hof_update
from deap_tpu.support.logbook import Logbook


def _sizes(pop: Population) -> jnp.ndarray:
    return pop.genomes["length"]


def _trial_offspring(key: jax.Array, pop: Population, toolbox, n: int,
                     cxpb: float, mutpb: float) -> Population:
    """Generate ``n`` trial children the way the reference's ``_genpop``
    does (gp.py:993-1043): parents via ``toolbox.select``; each child is
    a crossover child (prob cxpb), a mutant (prob mutpb) or a reproduced
    copy that keeps its valid fitness."""
    k_u, k_sel, k_cx, k_mut = jax.random.split(key, 4)
    u = jax.random.uniform(k_u, (n,))
    idx = toolbox.select(k_sel, pop.wvalues, 2 * n)
    p1 = gather(pop, idx[:n])
    p2 = gather(pop, idx[n:])
    c1, _ = jax.vmap(toolbox.mate)(jax.random.split(k_cx, n),
                                   p1.genomes, p2.genomes)
    m1 = jax.vmap(toolbox.mutate)(jax.random.split(k_mut, n), p1.genomes)
    is_cx = u < cxpb
    is_mut = (u >= cxpb) & (u < cxpb + mutpb)

    def mix(cx_leaf, mut_leaf, rep_leaf):
        m = is_cx.reshape((-1,) + (1,) * (cx_leaf.ndim - 1))
        mm = is_mut.reshape((-1,) + (1,) * (cx_leaf.ndim - 1))
        return jnp.where(m, cx_leaf, jnp.where(mm, mut_leaf, rep_leaf))

    genomes = jax.tree_util.tree_map(mix, c1, m1, p1.genomes)
    touched = is_cx | is_mut
    return p1.replace(genomes=genomes).invalidate(touched)


@partial(jax.jit, static_argnames=("max_size",))
def _kde_hist(sizes: jnp.ndarray, max_size: int) -> jnp.ndarray:
    """Kernel-smoothed size histogram (gp.py:1080-1089): each size adds
    0.4 at itself, 0.2 at ±1, 0.1 at ±2 (negative bins dropped)."""
    hist = jnp.zeros((max_size + 3,), jnp.float32)
    for off, w in ((0, 0.4), (-1, 0.2), (1, 0.2), (-2, 0.1), (2, 0.1)):
        b = sizes + off
        ok = b >= 0
        hist = hist.at[jnp.where(ok, b, 0)].add(jnp.where(ok, w, 0.0))
    return hist


def harm(key: jax.Array, pop: Population, toolbox, cxpb: float,
         mutpb: float, ngen: int, alpha: float = 0.05, beta: float = 10.0,
         gamma: float = 0.25, rho: float = 0.9, nbrindsmodel: int = -1,
         mincutoff: int = 20, stats=None, halloffame=None,
         verbose: bool = False) -> Tuple[Population, Logbook, Optional[object]]:
    """Run a HARM-GP evolution (gp.py:938-1135 semantics; recommended
    parameters alpha=0.05, beta=10, gamma=0.25, rho=0.9 per the paper's
    note at gp.py:978-984). Genomes must be tensor prefix trees (their
    ``length`` field is the size measure the reference takes as
    ``len(individual)``)."""
    n = pop.size
    if nbrindsmodel == -1:
        nbrindsmodel = max(2000, n)
    max_size = int(pop.genomes["nodes"].shape[-1])
    # jit per harm() call, closing over the toolbox: a cross-call cache
    # keyed on toolbox identity would replay stale operators after a
    # re-register()
    trial = jax.jit(lambda k, p: _trial_offspring(
        k, p, toolbox, nbrindsmodel, cxpb, mutpb))

    nevals0 = int(jnp.sum(~pop.valid))
    pop = evaluate_invalid(pop, toolbox.evaluate)
    hof = halloffame
    if hof is not None:
        hof = hof_update(hof, pop)
    logbook = Logbook()
    rec = stats.compile(pop) if stats else {}
    logbook.record(gen=0, nevals=nevals0, **rec)
    if verbose:
        print(logbook.stream)

    for gen in range(1, ngen + 1):
        key, k_nat, k_acc, k_pick = jax.random.split(key, 4)

        # 1) natural size distribution from a big trial batch
        natural = trial(k_nat, pop)
        sizes = _sizes(natural)
        naturalhist = _kde_hist(sizes, max_size) * (n / nbrindsmodel)

        # 2) cutoff from the fittest tail (gp.py:1091-1097): sort the
        # trial pop ascending by fitness (invalid rows first, like the
        # reference's empty-wvalues tuples) and take the sizes past
        # index n*rho - 1.
        fit_key = jnp.where(natural.valid, natural.wvalues.sum(-1), -jnp.inf)
        order = jnp.argsort(fit_key)
        tail = jnp.asarray(sizes)[order][int(n * rho - 1):]
        cutoffsize = max(mincutoff, int(tail.min()))

        # 3) target distribution with exponential decay past the cutoff
        bins = jnp.arange(max_size + 3, dtype=jnp.float32)
        halflife = bins * alpha + beta
        targetfunc = (gamma * n * math.log(2) / halflife) * jnp.exp(
            -math.log(2) * (bins - cutoffsize) / halflife)
        targethist = jnp.where(bins <= cutoffsize, naturalhist, targetfunc)

        # 4) acceptance probability per size
        probhist = jnp.where(naturalhist > 0, targethist / naturalhist,
                             targethist)
        probs = jnp.clip(probhist[jnp.clip(sizes, 0, max_size + 2)], 0.0, 1.0)

        # 5) offspring: accepted trial individuals first (Gumbel top-k
        # over acceptance draws — the batched analog of the reference's
        # accept-reject stream, gp.py:1109-1117)
        accept = jax.random.bernoulli(k_acc, probs)
        score = jax.random.uniform(k_pick, (nbrindsmodel,)) + accept * 2.0
        take = jax.lax.top_k(score, n)[1]
        offspring = gather(natural, take)
        nevals = int(jnp.sum(~offspring.valid))
        offspring = evaluate_invalid(offspring, toolbox.evaluate)
        if hof is not None:
            hof = hof_update(hof, offspring)
        pop = offspring

        rec = stats.compile(pop) if stats else {}
        logbook.record(gen=gen, nevals=nevals, **rec)
        if verbose:
            print(logbook.stream)

    return pop, logbook, hof
