"""Tensor prefix trees — generation and variation as index arithmetic.

Counterpart of the reference's ``PrimitiveTree`` machinery
(/root/reference/deap/gp.py): the generators genFull/genGrow/
genHalfAndHalf (gp.py:519-638), subtree search (searchSubtree,
gp.py:174-184), crossover (cxOnePoint gp.py:645-682,
cxOnePointLeafBiased gp.py:685-737) and mutations (mutUniform :743,
mutNodeReplacement :760, mutEphemeral :786, mutInsert :814, mutShrink
:854), plus the staticLimit bloat-control decorator (gp.py:890-931).

A tree is a fixed-width prefix array (SURVEY.md §7.2 item 8):
``{"nodes": int32[max_len], "consts": f32[max_len], "length": int32}``.
Slots past ``length`` are padding. All operators are pure jax functions
usable inside jit/vmap/scan; "would exceed max_len" replaces the
reference's unbounded list growth and returns the parent unchanged —
the array-width analog of staticLimit's reject-and-keep-parent.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet

Genome = Dict[str, jnp.ndarray]


# ------------------------------------------------------------- generation ----

def make_generator(pset: PrimitiveSet, max_len: int, min_depth: int,
                   max_depth: int, mode: str = "half_and_half",
                   ) -> Callable[[jax.Array], Genome]:
    """Build ``gen(key) -> genome``, the tensor counterpart of
    genFull/genGrow/genHalfAndHalf (gp.py:519-638).

    The tree grows by scanning slots with a LIFO stack of pending
    (depth-of-slot) entries. A node is a terminal when its depth reaches
    the height budget, when the array is nearly full, or — in grow mode —
    with probability ``terminalRatio`` once past ``min_depth``
    (gp.py:555-582 semantics, vectorised).
    """
    if mode not in ("full", "grow", "half_and_half"):
        raise ValueError(mode)
    t_ratio = pset.terminal_ratio
    arity = pset.arity_table()
    # a depth-bounded tree can never need more slots than the full
    # a-ary tree of that depth, so the scan stops there — mutUniform's
    # genFull(0, 2) donor (7 slots at arity 2) was paying a 32-step
    # scan per individual before this bound
    a = max(int(pset.max_arity), 1)
    depth_cap = (max_depth + 1 if a == 1
                 else (a ** (max_depth + 1) - 1) // (a - 1))
    scan_len = min(max_len, depth_cap)

    def gen(key: jax.Array) -> Genome:
        k_h, k_mode, k_scan = jax.random.split(key, 3)
        height = jax.random.randint(k_h, (), min_depth, max_depth + 1)
        if mode == "full":
            grow = jnp.bool_(False)
        elif mode == "grow":
            grow = jnp.bool_(True)
        else:
            grow = jax.random.bernoulli(k_mode, 0.5)

        nodes0 = jnp.full((max_len,), pset.const_id, jnp.int32)
        consts0 = jnp.zeros((max_len,), jnp.float32)
        depth_stack0 = jnp.zeros((max_len + 1,), jnp.int32)

        def step(carry, inp):
            nodes, consts, stack, sp, length = carry
            t, k = inp
            pending = sp > 0
            d = stack[jnp.maximum(sp - 1, 0)]
            sp_pop = sp - 1

            k_t, k_term, k_op = jax.random.split(k, 3)
            # space guard: after this node the pending subtrees must each
            # still fit one slot
            room = max_len - t - sp_pop - 1
            force_term = (d >= height) | (room < 1)
            grow_term = grow & (d >= min_depth) & (
                jax.random.uniform(k_t) < t_ratio)
            is_term = force_term | grow_term

            term_node, term_val = pset.sample_terminal(k_term)
            op_node = pset.sample_op(k_op)
            # operator whose arity overflows the space guard → terminal
            is_term = is_term | (arity[op_node] > room)
            node = jnp.where(is_term, term_node, op_node)
            val = jnp.where(is_term, term_val, 0.0)

            nodes = jnp.where(pending, nodes.at[t].set(node), nodes)
            consts = jnp.where(pending, consts.at[t].set(val), consts)
            # push children (depth d+1); LIFO order makes the walk prefix
            ar = jnp.where(is_term, 0, arity[op_node])
            idx = jnp.arange(max_len + 1)
            push = (idx >= sp_pop) & (idx < sp_pop + ar)
            stack = jnp.where(push, d + 1, stack)
            sp = jnp.where(pending, sp_pop + ar, sp)
            length = length + pending.astype(jnp.int32)
            return (nodes, consts, stack, sp, length), None

        keys = jax.random.split(k_scan, scan_len)
        init = (nodes0, consts0, depth_stack0.at[0].set(0), jnp.int32(1),
                jnp.int32(0))
        (nodes, consts, _, _, length), _ = lax.scan(
            step, init, (jnp.arange(scan_len), keys))
        return {"nodes": nodes, "consts": consts, "length": length}

    return gen


def gen_full(pset, max_len, min_, max_):
    return make_generator(pset, max_len, min_, max_, "full")


def gen_grow(pset, max_len, min_, max_):
    return make_generator(pset, max_len, min_, max_, "grow")


def gen_half_and_half(pset, max_len, min_, max_):
    return make_generator(pset, max_len, min_, max_, "half_and_half")


# -------------------------------------------------------- tree arithmetic ----

def subtree_end(nodes: jnp.ndarray, arity: jnp.ndarray,
                begin: jnp.ndarray) -> jnp.ndarray:
    """Exclusive end of the subtree rooted at ``begin`` — the arity walk
    of searchSubtree (gp.py:174-184) as a cumulative sum: the subtree
    closes at the first j ≥ begin where 1 + Σ(arity−1) over [begin, j]
    hits zero."""
    L = nodes.shape[0]
    deficit = arity[nodes] - 1                      # -1 terminals, +k ops
    cs = jnp.cumsum(deficit)
    prev = jnp.where(begin > 0, cs[jnp.maximum(begin - 1, 0)], 0)
    total = 1 + cs - prev                           # pending count after j
    closed = (total == 0) & (jnp.arange(L) >= begin)
    return jnp.argmax(closed) + 1


def subtree_ends_all(nodes: jnp.ndarray, length, arity: jnp.ndarray
                     ) -> jnp.ndarray:
    """Exclusive subtree end for EVERY slot at once — ``end_i`` is the
    first ``j ≥ i`` where the pending count ``1 + cs[j] − cs[i−1]``
    hits zero, i.e. the first ``j`` with ``cs[j] ≤ cs[i−1] − 1``. That
    is a next-smaller-element query, answered for all ``i`` together
    by a vectorised binary search over a sparse range-min table of the
    arity cumsum: O(L log L) vector work (the r3 formulation built an
    [L, L] mask per tree, which dominated the GP variation pipeline —
    60+ of 130 ms/gen at pop=4096 went to staticLimit's height
    measure). Slots at/past ``length`` return garbage; mask downstream."""
    L = nodes.shape[0]
    deficit = jnp.where(jnp.arange(L) < length, arity[nodes] - 1, 0)
    cs = jnp.cumsum(deficit)
    prev = jnp.concatenate([jnp.zeros(1, cs.dtype), cs[:-1]])  # cs[i-1]
    NEG = jnp.asarray(-(2 ** 30), cs.dtype)

    # levels[k][p] = min cs over [p, p+2^k), windows truncated at L
    # behaving as NEG (so the search can never skip past the end)
    levels = [cs]
    k = 1
    while k < L:
        m = levels[-1]
        shifted = jnp.concatenate([m[k:], jnp.full((k,), NEG, cs.dtype)])
        levels.append(jnp.minimum(m, shifted))
        k *= 2

    # first j >= i with cs[j] <= target; skip a 2^k block only when its
    # range-min stays above target
    target = prev - 1
    pos = jnp.arange(L)
    for lev in reversed(range(len(levels))):
        step = 1 << lev
        block_min = jnp.where(
            pos < L, levels[lev][jnp.minimum(pos, L - 1)], NEG)
        pos = jnp.where(block_min > target, pos + step, pos)
    return jnp.minimum(pos, L - 1) + 1


def prefix_depths(nodes: jnp.ndarray, length, arity: jnp.ndarray
                  ) -> jnp.ndarray:
    """Depth of every slot (root 0; garbage past ``length``) in closed
    form — no serial walk.

    In prefix order the ancestors of slot ``j`` are exactly the slots
    ``i ≤ j`` whose subtree interval ``[i, end_i)`` contains ``j``, so
    ``depth[j] = #{i ≤ j : end_i > j} − 1 = j − #{i : end_i ≤ j}``
    (``end_i > i`` makes the ``i ≤ j`` constraint automatic). With
    every end from :func:`subtree_ends_all`, that count is one
    histogram cumsum — O(L log L) total, replacing the r3 [L, L]
    ancestor mask (the VPU-shaped formulation of the reference's depth
    stack, gp.py:155-166)."""
    L = nodes.shape[0]
    ends = subtree_ends_all(nodes, length, arity)
    live = jnp.arange(L) < length
    hist = jnp.zeros(L + 1, jnp.int32).at[
        jnp.clip(jnp.where(live, ends, L), 0, L)].add(
        live.astype(jnp.int32), mode="drop")
    closed_by = jnp.cumsum(hist)[:-1]       # #(live ends <= j)
    return (jnp.arange(L) - closed_by).astype(jnp.int32)


def tree_height(genome: Genome, pset: PrimitiveSet) -> jnp.ndarray:
    """Tree height (root at 0), the measure of staticLimit/height
    (gp.py:155-166) — max over :func:`prefix_depths` of the live
    prefix (O(L log L) via the all-ends binary search; the depth-stack
    walk it replaces cost an L-step serial scan per tree)."""
    arity = pset.arity_table()
    nodes, length = genome["nodes"], genome["length"]
    depths = prefix_depths(nodes, length, arity)
    live = jnp.arange(nodes.shape[0]) < length
    return jnp.max(jnp.where(live, depths, 0)).astype(jnp.int32)


def _splice(g: Genome, begin, end, donor_nodes, donor_consts, donor_begin,
            donor_len) -> Genome:
    """Replace ``g[begin:end]`` with ``donor[donor_begin:+donor_len]``.

    Pure gather over output slots; if the result would exceed max_len the
    parent is returned unchanged (the fixed-width staticLimit analog)."""
    L = g["nodes"].shape[0]
    seg = end - begin
    new_len = g["length"] - seg + donor_len
    k = jnp.arange(L)
    in_head = k < begin
    in_donor = (k >= begin) & (k < begin + donor_len)
    src_tail = jnp.clip(k - donor_len + seg, 0, L - 1)
    src_donor = jnp.clip(donor_begin + k - begin, 0, L - 1)

    def mix(own, donor):
        return jnp.where(in_head, own,
                         jnp.where(in_donor, donor[src_donor], own[src_tail]))

    ok = new_len <= L
    nodes = jnp.where(ok, mix(g["nodes"], donor_nodes), g["nodes"])
    consts = jnp.where(ok, mix(g["consts"], donor_consts), g["consts"])
    length = jnp.where(ok, new_len, g["length"])
    return {"nodes": nodes, "consts": consts, "length": length}


# -------------------------------------------------------------- crossover ----

def make_cx_one_point(pset: PrimitiveSet) -> Callable:
    """One-point subtree crossover (gp.py:645-682): swap a random subtree
    of each parent, roots excluded; trees shorter than 2 nodes pass
    through unchanged, as in the reference."""
    arity = pset.arity_table()

    def cx(key: jax.Array, g1: Genome, g2: Genome) -> Tuple[Genome, Genome]:
        k1, k2 = jax.random.split(key)
        len1, len2 = g1["length"], g2["length"]
        ok = (len1 >= 2) & (len2 >= 2)
        i1 = jnp.where(len1 >= 2,
                       jax.random.randint(k1, (), 1, jnp.maximum(len1, 2)), 0)
        i2 = jnp.where(len2 >= 2,
                       jax.random.randint(k2, (), 1, jnp.maximum(len2, 2)), 0)
        e1 = subtree_end(g1["nodes"], arity, i1)
        e2 = subtree_end(g2["nodes"], arity, i2)
        c1 = _splice(g1, i1, e1, g2["nodes"], g2["consts"], i2, e2 - i2)
        c2 = _splice(g2, i2, e2, g1["nodes"], g1["consts"], i1, e1 - i1)

        def pick(child, parent):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), child, parent)

        return pick(c1, g1), pick(c2, g2)

    return cx


def make_cx_one_point_leaf_biased(pset: PrimitiveSet,
                                  termpb: float = 0.1) -> Callable:
    """Leaf-biased crossover (gp.py:685-737): each tree independently
    picks a terminal point with probability ``termpb``, else an internal
    operator (the Koza 90/10 rule; draws are per-tree like the
    reference's two separate ``random.random() < termpb`` tests,
    gp.py:710-711)."""
    arity = pset.arity_table()

    def pick_point(key, g, want_leaf):
        nodes, length = g["nodes"], g["length"]
        L = nodes.shape[0]
        in_tree = (jnp.arange(L) >= 1) & (jnp.arange(L) < length)
        is_leaf = arity[nodes] == 0
        mask = in_tree & jnp.where(want_leaf, is_leaf, ~is_leaf)
        # fall back to any non-root node when the class is empty
        mask = jnp.where(mask.any(), mask, in_tree)
        scores = jax.random.uniform(key, (L,))
        return jnp.argmax(jnp.where(mask, scores, -1.0))

    def cx(key: jax.Array, g1: Genome, g2: Genome) -> Tuple[Genome, Genome]:
        k_b1, k_b2, k1, k2 = jax.random.split(key, 4)
        ok = (g1["length"] >= 2) & (g2["length"] >= 2)
        i1 = pick_point(k1, g1, jax.random.bernoulli(k_b1, termpb))
        i2 = pick_point(k2, g2, jax.random.bernoulli(k_b2, termpb))
        e1 = subtree_end(g1["nodes"], arity, i1)
        e2 = subtree_end(g2["nodes"], arity, i2)
        c1 = _splice(g1, i1, e1, g2["nodes"], g2["consts"], i2, e2 - i2)
        c2 = _splice(g2, i2, e2, g1["nodes"], g1["consts"], i1, e1 - i1)

        def pick(child, parent):
            return jax.tree_util.tree_map(
                lambda a, b: jnp.where(ok, a, b), child, parent)

        return pick(c1, g1), pick(c2, g2)

    return cx


# -------------------------------------------------------------- mutations ----

def make_mut_uniform(pset: PrimitiveSet, expr: Callable) -> Callable:
    """Replace a random subtree with a fresh expression from ``expr``
    (mutUniform, gp.py:743-757; symbreg uses genFull(0, 2) for expr)."""
    arity = pset.arity_table()

    def mut(key: jax.Array, g: Genome) -> Genome:
        k_i, k_e = jax.random.split(key)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        e = subtree_end(g["nodes"], arity, i)
        new = expr(k_e)
        return _splice(g, i, e, new["nodes"], new["consts"], 0,
                       new["length"])

    return mut


def make_mut_node_replacement(pset: PrimitiveSet) -> Callable:
    """Swap one node for another of the same arity (mutNodeReplacement,
    gp.py:760-783): terminals get a fresh terminal draw, operators an
    operator of equal arity."""
    arity = pset.arity_table()
    import numpy as np
    # same-arity pools as a static [max_arity+1, n_ops] mask
    pools = np.zeros((pset.max_arity + 1, max(pset.n_ops, 1)), bool)
    for j, p in enumerate(pset.primitives):
        pools[p.arity, j] = True
    pools_j = jnp.asarray(pools)

    def mut(key: jax.Array, g: Genome) -> Genome:
        k_i, k_t, k_o = jax.random.split(key, 3)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        node = g["nodes"][i]
        ar = arity[node]
        term_node, term_val = pset.sample_terminal(k_t)
        scores = jax.random.uniform(k_o, (max(pset.n_ops, 1),))
        op_node = jnp.argmax(
            jnp.where(pools_j[ar], scores, -1.0)).astype(jnp.int32)
        is_term = ar == 0
        new_node = jnp.where(is_term, term_node, op_node)
        new_val = jnp.where(is_term, term_val, g["consts"][i])
        return {
            "nodes": g["nodes"].at[i].set(new_node),
            "consts": g["consts"].at[i].set(new_val),
            "length": g["length"],
        }

    return mut


def make_mut_ephemeral(pset: PrimitiveSet, mode: str = "one") -> Callable:
    """Resample ephemeral constants (mutEphemeral, gp.py:786-811):
    ``mode='one'`` redraws a single random ERC node, ``'all'`` every one."""
    if not pset.has_erc:
        raise ValueError("primitive set has no ephemeral constant")
    if mode not in ("one", "all"):
        raise ValueError(mode)

    def mut(key: jax.Array, g: Genome) -> Genome:
        L = g["nodes"].shape[0]
        k_pick, k_val = jax.random.split(key)
        is_erc = (g["nodes"] == pset.erc_id) & (jnp.arange(L) < g["length"])
        new_vals = jax.vmap(pset.erc_sampler)(jax.random.split(k_val, L))
        if mode == "one":
            scores = jax.random.uniform(k_pick, (L,))
            chosen = jnp.argmax(jnp.where(is_erc, scores, -1.0))
            target = is_erc & (jnp.arange(L) == chosen)
        else:
            target = is_erc
        return {
            "nodes": g["nodes"],
            "consts": jnp.where(target, new_vals, g["consts"]),
            "length": g["length"],
        }

    return mut


def make_mut_insert(pset: PrimitiveSet) -> Callable:
    """Insert a new operator above a random subtree (mutInsert,
    gp.py:814-851): the old subtree becomes one randomly-chosen argument
    of the new node; the remaining arguments are fresh terminals."""
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)

    def mut(key: jax.Array, g: Genome) -> Genome:
        L = g["nodes"].shape[0]
        k_i, k_op, k_slot, k_terms = jax.random.split(key, 4)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        e = subtree_end(g["nodes"], arity, i)
        seg = e - i
        op = pset.sample_op(k_op)
        ar = arity[op]
        pos = jax.random.randint(k_slot, (), 0, jnp.maximum(ar, 1))
        t_nodes, t_vals = jax.vmap(pset.sample_terminal)(
            jax.random.split(k_terms, max_ar))

        # donor = [op] + pos terminals + subtree + (ar-1-pos) terminals
        DL = 1 + max_ar + L
        k = jnp.arange(DL)
        donor_nodes = jnp.zeros((DL,), jnp.int32)
        donor_consts = jnp.zeros((DL,), jnp.float32)
        donor_nodes = donor_nodes.at[0].set(op)
        in_pre = (k >= 1) & (k < 1 + pos)
        in_sub = (k >= 1 + pos) & (k < 1 + pos + seg)
        in_post = (k >= 1 + pos + seg) & (k < 1 + seg + ar - 1)
        src_term_pre = jnp.clip(k - 1, 0, max_ar - 1)
        src_sub = jnp.clip(i + k - 1 - pos, 0, L - 1)
        src_term_post = jnp.clip(k - 1 - seg, 0, max_ar - 1)
        donor_nodes = jnp.where(
            in_pre, t_nodes[src_term_pre], jnp.where(
                in_sub, g["nodes"][src_sub], jnp.where(
                    in_post, t_nodes[src_term_post], donor_nodes)))
        donor_consts = jnp.where(
            in_pre, t_vals[src_term_pre], jnp.where(
                in_sub, g["consts"][src_sub], jnp.where(
                    in_post, t_vals[src_term_post], donor_consts)))
        donor_len = 1 + (ar - 1) + seg
        return _splice(g, i, e, donor_nodes, donor_consts, 0, donor_len)

    return mut


def make_mut_shrink(pset: PrimitiveSet) -> Callable:
    """Collapse a random operator node to one of its argument subtrees
    (mutShrink, gp.py:854-887); trees with no operator below the root
    pass through unchanged."""
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)

    def mut(key: jax.Array, g: Genome) -> Genome:
        L = g["nodes"].shape[0]
        k_i, k_c = jax.random.split(key)
        # the reference exempts the root and tiny trees (len < 3 or
        # height <= 1, gp.py:858-860): shrink only operators below root
        in_tree = (jnp.arange(L) >= 1) & (jnp.arange(L) < g["length"])
        is_op = (arity[g["nodes"]] > 0) & in_tree
        has_op = is_op.any() & (g["length"] >= 3)
        scores = jax.random.uniform(k_i, (L,))
        i = jnp.argmax(jnp.where(is_op, scores, -1.0))
        ar = arity[g["nodes"]][i]
        child = jax.random.randint(k_c, (), 0, jnp.maximum(ar, 1))

        # walk to the chosen child's start: c0 = i+1, c_{k+1} = end(c_k)
        def walk(j, start):
            return jnp.where(j < child,
                             subtree_end(g["nodes"], arity, start), start)

        c_begin = lax.fori_loop(0, max_ar, walk, i + 1)
        c_end = subtree_end(g["nodes"], arity, c_begin)
        e = subtree_end(g["nodes"], arity, i)
        out = _splice(g, i, e, g["nodes"], g["consts"], c_begin,
                      c_end - c_begin)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(has_op, a, b), out, g)

    return mut


# ------------------------------------------------------------ bloat control ----

def static_limit(measure: Callable, max_value: int) -> Callable:
    """Decorator keeping the parent when an offspring exceeds the limit
    (staticLimit, gp.py:890-931; Koza's height-17 rule). ``measure``
    maps a genome to a scalar (e.g. ``tree_height`` partial or
    ``lambda g: g['length']``)."""

    def decorator(op):
        def wrapped(key, *genomes):
            out = op(key, *genomes)
            outs = out if isinstance(out, tuple) else (out,)
            kept = []
            for child, parent in zip(outs, genomes):
                bad = measure(child) > max_value
                kept.append(jax.tree_util.tree_map(
                    lambda a, b: jnp.where(bad, b, a), child, parent))
            return tuple(kept) if isinstance(out, tuple) else kept[0]

        return wrapped

    return decorator
