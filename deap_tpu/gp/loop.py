"""Host-dispatch GP generation engine — the loop shape that lets the
interpreter's live-population specialization actually engage.

The jit'd ``lax.scan`` loops in :mod:`deap_tpu.algorithms` trace the
evaluator once, so everything inside is shape-static and full-vocab:
the interpreter cannot specialize on what the *current* population
contains. This engine instead drives one generation at a time from the
host — selection and variation stay jit-compiled on device, while
evaluation dispatches through the concrete-genome path of
``gp.make_batch_interpreter`` (live-vocab masks, unique-genome dedup,
opcode-major grouped mode). Two further reference behaviours that the
scan loops pay for but the reference never did become free here:

- **Invalid-only evaluation, for real.** ``evaluate_invalid`` computes
  every row and masks the write (the only formulation a traced scan
  allows); with cxpb=0.5/mutpb=0.1 that is ~2× the reference's work.
  Here the touched mask is concrete, so only touched rows are gathered
  and evaluated — exactly ``nevals`` of the reference loop
  (algorithms.py:149-152). Since PR 6 the touched/crossover/mutation
  index compaction runs **on device** (``compaction='device'``, the
  default): one jit draws the flags and prefix-sum-packs them into
  cycle-padded index arrays (:func:`gp.interpreter.compact_indices`,
  ``np.resize`` pad semantics), and the host reads back only the three
  counts — the full-flag-array fetch + host ``np.nonzero``/``np.resize``
  + index re-upload that used to serialise every generation's dispatch
  is gone. ``compaction='host'`` keeps the PR-3 formulation as the
  bit-parity oracle (tests/test_gp_compaction.py).
- **Algebraic height limits.** ``static_limit`` re-derives every
  offspring's height from scratch (an O(L log L) all-ends query per
  variation operator — measured 2×28 ms/gen at pop=4096 on one CPU
  core). A splice cannot change the depth of any node outside the
  spliced subtree, so this engine threads per-tree *depth arrays*
  through every splice: the donor segment's depths shift by
  ``depth[target] − depth[donor root]`` and everything else is copied —
  the child's height is a masked max, no tree walk. The carried depths
  are pinned equal to ``prefix_depths`` recomputation by
  tests/test_gp_dispatch.py.

Semantics match ``algorithms.ea_simple`` + ``var_and`` with
``static_limit``-wrapped one-point crossover and uniform mutation
(keep-parent on limit breach or overflow; adjacent-pair mating;
touched-row invalidation); RNG streams differ, as everywhere in this
framework. ``bench.py --gp-race`` races this engine against the
scan-loop formulation live (BENCH_GP.json).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import ops
from deap_tpu.gp.interpreter import (DEFAULT_CHUNK, _round_size,
                                     compact_indices,
                                     make_batch_interpreter)
from deap_tpu.gp.pset import PrimitiveSet
from deap_tpu.gp.tree import (make_generator, prefix_depths, subtree_end,
                              _splice)
from deap_tpu.support.profiling import span


def _splice_depths(dep, i, e, donor_dep, di, donor_len, shift, ok):
    """Depth array of ``_splice(g, i, e, donor, di, donor_len)``: head
    and tail keep their depths (a splice cannot re-depth anything
    outside the replaced subtree), the donor segment shifts by
    ``shift = dep[i] − donor_dep[di]``. ``ok`` mirrors _splice's
    overflow keep-parent."""
    L = dep.shape[0]
    k = jnp.arange(L)
    seg = e - i
    in_head = k < i
    in_donor = (k >= i) & (k < i + donor_len)
    src_tail = jnp.clip(k - donor_len + seg, 0, L - 1)
    src_donor = jnp.clip(di + k - i, 0, L - 1)
    mixed = jnp.where(in_head, dep,
                      jnp.where(in_donor, donor_dep[src_donor] + shift,
                                dep[src_tail]))
    return jnp.where(ok, mixed, dep)


def _height(dep, length):
    live = jnp.arange(dep.shape[0]) < length
    return jnp.max(jnp.where(live, dep, 0))


def make_flag_compactor(cxpb: float, mutpb: float) -> Callable:
    """The device half of the GP variation plane: one jit that draws
    the generation's cx/mut Bernoullis AND compacts them into
    cycle-padded index arrays (``np.resize`` semantics, see
    :func:`deap_tpu.gp.interpreter.compact_indices`) — so the only
    thing the host ever reads back is the three counts (12 bytes),
    not the flag arrays themselves.

    Returns ``flags_compact(key, n) -> (cx_idx [n//2], mut_idx [n],
    touched_idx [n], counts int32[3])`` with the exact key-split tree
    of the host path's ``draw_flags`` (bit-parity pinned by
    tests/test_gp_compaction.py)."""

    @partial(jax.jit, static_argnums=1)
    def flags_compact(key, n: int):
        k_pair, k_ind = jax.random.split(key)
        do_cx = jax.random.bernoulli(k_pair, cxpb, (n // 2,))
        do_mut = jax.random.bernoulli(k_ind, mutpb, (n,))
        cx_idx, n_cx = compact_indices(do_cx, max(n // 2, 1))
        mut_idx, n_mut = compact_indices(do_mut, n)
        touched = do_mut
        if n // 2:
            touched = touched | jnp.zeros(n, bool).at[: 2 * (n // 2)].set(
                jnp.repeat(do_cx, 2))
        t_idx, n_t = compact_indices(touched, n)
        return cx_idx, mut_idx, t_idx, jnp.stack([n_cx, n_mut, n_t])

    return flags_compact


def make_compaction_pipelines(cxpb: float, mutpb: float):
    """The two variation-compaction pipelines isolated from the rest of
    the loop — the paired measurement behind ``bench.py --fusion`` and
    the parity suite. Each maps ``(key, n)`` to device-resident,
    lattice-padded ``(cx_idx, mut_idx, touched_idx)`` plus the three
    counts, ready for the cx/mut/eval dispatch; values are
    bit-identical between the two (same draws, same ``np.resize``
    cycle-pad rule).

    - ``host_fn``: the PR-3 round trip — fetch both flag arrays,
      ``np.nonzero``/``np.resize`` on the host, re-upload.
    - ``device_fn``: one jit (draw + prefix-sum compaction), a 12-byte
      count fetch, device-side lattice slices.
    """

    @partial(jax.jit, static_argnums=1)
    def draw_flags(key, n: int):
        k_pair, k_ind = jax.random.split(key)
        return (jax.random.bernoulli(k_pair, cxpb, (n // 2,)),
                jax.random.bernoulli(k_ind, mutpb, (n,)))

    flags_compact = make_flag_compactor(cxpb, mutpb)

    def host_fn(key, n: int):
        do_cx, do_mut = draw_flags(key, n)
        do_cx, do_mut = np.asarray(do_cx), np.asarray(do_mut)
        pidx = np.nonzero(do_cx)[0]
        midx = np.nonzero(do_mut)[0]
        touched = np.zeros(n, bool)
        touched[pidx * 2] = True
        touched[pidx * 2 + 1] = True
        touched[midx] = True
        tidx = np.nonzero(touched)[0]
        out = []
        for idx, cap in ((pidx, max(n // 2, 1)), (midx, n), (tidx, n)):
            P = min(_round_size(max(len(idx), 1)), cap)
            padded = (np.resize(idx, P) if len(idx)
                      else np.zeros(P, np.int32))
            out.append(jnp.asarray(padded, jnp.int32))
        jax.block_until_ready(out)
        return tuple(out), (len(pidx), len(midx), len(tidx))

    def device_fn(key, n: int):
        cx_idx, mut_idx, t_idx, counts = flags_compact(key, n)
        n_cx, n_mut, n_t = (int(c) for c in np.asarray(counts))
        out = []
        for idx, c, cap in ((cx_idx, n_cx, max(n // 2, 1)),
                            (mut_idx, n_mut, n), (t_idx, n_t, n)):
            P = min(_round_size(max(c, 1)), cap)
            out.append(idx[:P])
        jax.block_until_ready(out)
        return tuple(out), (n_cx, n_mut, n_t)

    return host_fn, device_fn


def _compaction_probe_fns(n: int):
    """Race the two compaction pipelines on a representative mask:
    host = flag fetch + ``np.nonzero``, device = jitted prefix-sum
    pack + count fetch. Both produce the same ascending index list
    (the loops' bit-identity pin, tests/test_gp_compaction.py), so the
    probe compares ``idx[:count]`` bitwise."""
    import jax as _jax

    flags = _jax.random.bernoulli(_jax.random.key(0), 0.5, (n,))
    flags_np = np.asarray(flags)
    compact = _jax.jit(compact_indices, static_argnums=1)

    def host():
        return np.nonzero(flags_np)[0].astype(np.int32)

    def device():
        idx, count = compact(flags, n)
        return np.asarray(idx)[: int(count)]

    return {"host": host, "device": device}


def resolve_compaction(mode: str, n: Optional[int] = None) -> str:
    """``'auto'`` → the measured winner per backend: ``'device'`` on
    accelerators (the host round trip is a real transfer+sync there,
    and the prefix-sum compaction stays on device), ``'host'`` on the
    CPU backend — where "device" IS the host, the flag fetch is a
    memcpy, and numpy's serial nonzero scan is bandwidth-optimal:
    measured host/device at pop=1k..100k on this box's CPU, the host
    pipeline wins at every size (1.1-4x), so auto never picks a slower
    path. Both modes are bit-identical (tests/test_gp_compaction.py).

    The static split is now the *default* rung of the dispatch
    tuner's ladder (:func:`deap_tpu.tuning.resolve`): with a tuner
    active, 'auto' short-probes both pipelines at ``n`` (or a
    representative 4096 when the loop builds before the population
    size is known) and persists the winner per backend;
    ``DEAP_TPU_TUNE_COMPACTION=host|device`` overrides either way.
    """
    if mode == "auto":
        import jax as _jax

        from deap_tpu import tuning

        static = "host" if _jax.default_backend() == "cpu" else "device"
        candidates = {"host": None, "device": None}
        if tuning.active_tuner() is not None:
            candidates = _compaction_probe_fns(int(n) if n else 4096)
        return tuning.resolve("compaction", bucket=(), default=static,
                              candidates=candidates, check="bitwise",
                              program="gp_loop")
    if mode not in ("device", "host"):
        raise ValueError(f"unknown compaction mode {mode!r}")
    return mode


class GpStepParts:
    """The per-individual variation/selection machinery of the
    host-dispatch GP loop, factored out of :func:`make_gp_loop` so the
    batched serving engine (:class:`deap_tpu.serving.GpMultiRunEngine`)
    vmaps the *same* traced functions over a leading run axis — the
    construction that makes batched-vs-solo bit-identity structural
    rather than coincidental. All members are pure and trace-safe:

    - ``pair_cx(key, g1, d1, g2, d2)`` — one adjacent-pair one-point
      crossover with carried depth arrays and the Koza keep-parent
      height limit;
    - ``one_mut(key, g, d)`` — one uniform subtree mutation with a
      fresh genFull donor, same depth carry and limit;
    - ``select_idx(key, fit)`` — the tournament index draw;
    - ``depths(g)`` — one genome's ``prefix_depths`` recomputation.
    """

    def __init__(self, pair_cx, one_mut, select_idx, depths, arity,
                 expr, height_limit, tournsize):
        self.pair_cx = pair_cx
        self.one_mut = one_mut
        self.select_idx = select_idx
        self.depths = depths
        self.arity = arity
        self.expr = expr
        self.height_limit = height_limit
        self.tournsize = tournsize


def make_gp_step_parts(pset: PrimitiveSet, max_len: int, *,
                       tournsize: int = 3, height_limit: int = 17,
                       mut_min: int = 0, mut_max: int = 2,
                       mut_width: Optional[int] = None) -> GpStepParts:
    """Build the :class:`GpStepParts` for one (pset, max_len, knobs)
    configuration — the shared kernel of the solo host-dispatch loop
    and the batched multi-run engine."""
    arity = pset.arity_table()
    mut_width = mut_width or min(max_len, 32)
    expr = make_generator(pset, mut_width, mut_min, mut_max, "full")
    ML = max_len

    def pair_cx(key, g1, d1, g2, d2):
        k1, k2 = jax.random.split(key)
        len1, len2 = g1["length"], g2["length"]
        ok = (len1 >= 2) & (len2 >= 2)
        i1 = jnp.where(len1 >= 2,
                       jax.random.randint(k1, (), 1, jnp.maximum(len1, 2)), 0)
        i2 = jnp.where(len2 >= 2,
                       jax.random.randint(k2, (), 1, jnp.maximum(len2, 2)), 0)
        e1 = subtree_end(g1["nodes"], arity, i1)
        e2 = subtree_end(g2["nodes"], arity, i2)
        c1 = _splice(g1, i1, e1, g2["nodes"], g2["consts"], i2, e2 - i2)
        c2 = _splice(g2, i2, e2, g1["nodes"], g1["consts"], i1, e1 - i1)
        # _splice keeps the parent on overflow; mirror its predicate so
        # the depth arrays revert in lockstep
        ok1 = ok & (g1["length"] - (e1 - i1) + (e2 - i2) <= ML)
        ok2 = ok & (g2["length"] - (e2 - i2) + (e1 - i1) <= ML)
        dd1 = _splice_depths(d1, i1, e1, d2, i2, e2 - i2,
                             d1[i1] - d2[i2], ok1)
        dd2 = _splice_depths(d2, i2, e2, d1, i1, e1 - i1,
                             d2[i2] - d1[i1], ok2)
        bad1 = ~ok | (_height(dd1, c1["length"]) > height_limit)
        bad2 = ~ok | (_height(dd2, c2["length"]) > height_limit)
        keep = lambda bad, c, g: jax.tree_util.tree_map(
            lambda a, b: jnp.where(bad, b, a), c, g)
        c1 = keep(bad1, c1, g1)
        c2 = keep(bad2, c2, g2)
        dd1 = jnp.where(bad1, d1, dd1)
        dd2 = jnp.where(bad2, d2, dd2)
        return c1, dd1, c2, dd2

    def one_mut(key, g, d):
        k_i, k_e = jax.random.split(key)
        i = jax.random.randint(k_i, (), 0, jnp.maximum(g["length"], 1))
        e = subtree_end(g["nodes"], arity, i)
        new = expr(k_e)
        new_dep = prefix_depths(new["nodes"], new["length"], arity)
        c = _splice(g, i, e, new["nodes"], new["consts"], 0,
                    new["length"])
        ok = g["length"] - (e - i) + new["length"] <= ML
        dd = _splice_depths(d, i, e, new_dep, 0, new["length"],
                            d[i], ok)
        bad = _height(dd, c["length"]) > height_limit
        c = jax.tree_util.tree_map(
            lambda a, b: jnp.where(bad, b, a), c, g)
        dd = jnp.where(bad, d, dd)
        return c, dd

    def select_idx(key, fit):
        n = fit.shape[0]
        return ops.sel_tournament(key, fit[:, None], n,
                                  tournsize=tournsize)

    def depths(g):
        return prefix_depths(g["nodes"], g["length"], arity)

    return GpStepParts(pair_cx, one_mut, select_idx, depths, arity,
                       expr, height_limit, tournsize)


def make_gp_loop(pset: PrimitiveSet, max_len: int, evaluate: Callable, *,
                 cxpb: float, mutpb: float, tournsize: int = 3,
                 height_limit: int = 17,
                 mut_min: int = 0, mut_max: int = 2,
                 mut_width: Optional[int] = None,
                 compaction: str = "auto",
                 telemetry=None, probes=(), plan=None) -> Callable:
    """Build ``run(key, genomes, ngen) -> result`` — the host-dispatch
    eaSimple-shaped GP loop (tournament selection, adjacent-pair
    one-point crossover at ``cxpb``, uniform subtree mutation at
    ``mutpb`` with a fresh genFull(mut_min, mut_max) donor, Koza
    ``height_limit`` keep-parent, invalid-only evaluation).

    ``evaluate(genomes) -> f32[n]`` maximization fitness, called
    EAGERLY with concrete sub-populations — pair it with a
    ``make_batch_interpreter``/``make_population_evaluator`` evaluator
    so the live-vocab/dedup/grouped dispatch engages. ``compaction``
    picks how the per-generation touched/cx/mut index sets are built:
    ``'device'`` (default — jit'd prefix-sum compaction, only the three
    counts cross to the host) or ``'host'`` (the PR-3
    ``np.nonzero``/``np.resize`` round trip; bit-identical results,
    kept as the parity oracle). The result dict
    carries the final population + depth arrays, the best individual,
    and the reference-comparable ``nevals`` per generation.

    ``plan`` (a :class:`deap_tpu.parallel.ShardingPlan`) shards the
    population arrays (genomes/depths/fitness rows) over the plan's
    mesh: the jitted select/variation programs partition across
    devices and the grouped-dispatch evaluator receives row-sharded
    sub-populations. Results are bit-identical to the unsharded loop
    (sharding is layout, not semantics — pinned in
    ``tests/test_sharding_plan.py``); the per-generation placement pin
    re-uses buffers already laid out correctly.

    ``telemetry``/``probes``: the host-dispatch counterpart of the
    scanned loops' instrumentation — one decoded ``meter`` row per
    generation lands in the journal as it happens (this loop has a
    host in it anyway), probes get the selection indices and, since
    the population is concrete here, the GP interpreter's *exact*
    dedup count via ``host_clone_rate`` (TreeDiversityProbe prefers it
    over its in-scan hash). Because the driver is host-side, a
    :class:`~deap_tpu.telemetry.probes.HealthMonitor` configured with
    ``early_stop`` genuinely stops the run (``result["stopped_at"]``
    records the generation). Telemetry changes no computed result."""
    parts = make_gp_step_parts(
        pset, max_len, tournsize=tournsize, height_limit=height_limit,
        mut_min=mut_min, mut_max=mut_max, mut_width=mut_width)
    pair_cx, one_mut = parts.pair_cx, parts.one_mut

    depths_of = jax.jit(jax.vmap(parts.depths))

    @jax.jit
    def select(key, genomes, depths, fit):
        idx = parts.select_idx(key, fit)
        return (jax.tree_util.tree_map(lambda a: a[idx], genomes),
                depths[idx], fit[idx], idx)

    @partial(jax.jit, static_argnums=1)
    def draw_flags(key, n):
        k_pair, k_ind = jax.random.split(key)
        return (jax.random.bernoulli(k_pair, cxpb, (n // 2,)),
                jax.random.bernoulli(k_ind, mutpb, (n,)))

    @jax.jit
    def cx_apply(key, genomes, depths, pp):
        """Gather the drawn pairs, cross them, scatter the offspring —
        one fused jit. Keys derive from the PAIR id, not the array
        position: lattice padding cycles indices, and duplicate
        scatters are only deterministic when duplicates compute the
        same offspring (np.resize pads by cycling, so row k of the
        computed sub-batch always belongs to pp[k])."""
        rows_e, rows_o = pp * 2, pp * 2 + 1
        g_e = jax.tree_util.tree_map(lambda a: a[rows_e], genomes)
        g_o = jax.tree_util.tree_map(lambda a: a[rows_o], genomes)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(pp)
        c1, dd1, c2, dd2 = jax.vmap(pair_cx)(
            keys, g_e, depths[rows_e], g_o, depths[rows_o])
        genomes = jax.tree_util.tree_map(
            lambda a, s1, s2: a.at[rows_e].set(s1).at[rows_o].set(s2),
            genomes, c1, c2)
        return genomes, depths.at[rows_e].set(dd1).at[rows_o].set(dd2)

    @jax.jit
    def mut_apply(key, genomes, depths, mp):
        g_m = jax.tree_util.tree_map(lambda a: a[mp], genomes)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(mp)
        m_g, m_d = jax.vmap(one_mut)(keys, g_m, depths[mp])
        genomes = jax.tree_util.tree_map(
            lambda a, s: a.at[mp].set(s), genomes, m_g)
        return genomes, depths.at[mp].set(m_d)

    flags_compact = make_flag_compactor(cxpb, mutpb)
    compaction = resolve_compaction(compaction)
    _device_compaction = compaction == "device"

    def vary_host(key, genomes, depths, n):
        """Host-compacted var_and (the PR-3 formulation, kept as the
        parity oracle): the flag arrays round-trip to the host, which
        runs ``np.nonzero``/``np.resize`` and re-uploads the padded
        index arrays — a full device sync in the middle of every
        generation's dispatch. Crossover/mutation are computed only for
        the rows the cxpb/mutpb draws actually touch, padded on the
        size lattice so compacted shapes stay cache-warm. Semantics
        match var_and: adjacent pairs mate with prob cxpb, every row
        then mutates with prob mutpb, touched rows are invalidated."""
        k_draw, k_cx, k_mut = jax.random.split(key, 3)
        do_cx, do_mut = draw_flags(k_draw, n)
        with span("gp_loop/host_compaction_fetch"):
            do_cx, do_mut = np.asarray(do_cx), np.asarray(do_mut)

        pidx = np.nonzero(do_cx)[0]
        if len(pidx):
            pp = np.resize(pidx,
                           min(_round_size(len(pidx)), max(n // 2, 1)))
            genomes, depths = cx_apply(k_cx, genomes, depths,
                                       jnp.asarray(pp))

        midx = np.nonzero(do_mut)[0]
        if len(midx):
            mp = np.resize(midx, min(_round_size(len(midx)), n))
            genomes, depths = mut_apply(k_mut, genomes, depths,
                                        jnp.asarray(mp))

        touched = np.zeros(n, bool)
        touched[pidx * 2] = True
        touched[pidx * 2 + 1] = True
        touched[midx] = True
        tidx = np.nonzero(touched)[0]
        return genomes, depths, tidx, len(tidx)

    def vary_device(key, genomes, depths, n):
        """On-device-compacted var_and: ONE jit draws the flags and
        prefix-sum-compacts them into cycle-padded index arrays
        (:func:`~deap_tpu.gp.interpreter.compact_indices`, bit-equal to
        the host path's ``np.nonzero``+``np.resize``); the host reads
        back only the three counts (12 bytes — needed anyway to pick
        the lattice slice and for reference-exact ``nevals``), slices
        the device arrays at lattice sizes, and dispatches. The flag
        arrays, the nonzero scan, and the pad construction never leave
        the device — the variation plane's full-array host sync is
        gone (journaled as ``variation_dispatch``; the host path's
        fetch is span-visible as ``gp_loop/host_compaction_fetch``,
        absent here)."""
        k_draw, k_cx, k_mut = jax.random.split(key, 3)
        cx_idx, mut_idx, t_idx, counts = flags_compact(k_draw, n)
        with span("gp_loop/compaction_count_fetch"):
            n_cx, n_mut, n_t = (int(c) for c in np.asarray(counts))
        if n_cx:
            P = min(_round_size(n_cx), max(n // 2, 1))
            genomes, depths = cx_apply(k_cx, genomes, depths,
                                       cx_idx[:P])
        if n_mut:
            P = min(_round_size(n_mut), n)
            genomes, depths = mut_apply(k_mut, genomes, depths,
                                        mut_idx[:P])
        return genomes, depths, t_idx, n_t

    vary = vary_device if _device_compaction else vary_host

    tel = telemetry
    if probes and tel is None:
        raise ValueError("probes= requires telemetry= (a RunTelemetry):"
                         " probe state rides the telemetry Meter carry")
    if tel is not None:
        from deap_tpu.telemetry.probes import TreeDiversityProbe
        # the exact interpreter-style dedup costs an O(nL) host pass —
        # only pay it for a probe that will publish it
        _host_dedup = any(isinstance(p, TreeDiversityProbe)
                          for p in tuple(probes) + (tel.probe,)
                          if p is not None)

    def _measure(mstate, ne, genomes, fit, gen, sel_idx=None):
        """One generation's instrumentation — mirrors algorithms.py's
        ``_tel_measure`` but runs eagerly (concrete genomes), so the
        GP interpreter's exact dedup substitutes for the in-scan hash."""
        from deap_tpu.core.fitness import FitnessSpec
        from deap_tpu.core.population import Population
        from deap_tpu.gp.interpreter import _dedup_rows

        n = fit.shape[0]
        m = tel.meter
        mstate = m.inc(mstate, "nevals", ne)
        mstate = m.set(mstate, "best", jnp.max(fit))
        mstate = m.set(mstate, "mean", jnp.mean(fit))
        mstate = m.set(mstate, "evaluated_frac", ne / n)
        clone = None
        if _host_dedup:
            first, _ = _dedup_rows(np.asarray(genomes["nodes"]),
                                   np.asarray(genomes["consts"]),
                                   np.asarray(genomes["length"]))
            clone = 1.0 - len(first) / n
        pv = Population(genomes=genomes, fitness=jnp.asarray(fit)[:, None],
                        valid=jnp.ones(n, bool), spec=FitnessSpec((1.0,)))
        mstate = tel.apply_probe(
            mstate, pop=pv, gen=gen, sel_idx=sel_idx, sel_pool=n,
            parent_idx=sel_idx, host_clone_rate=clone)
        tel.record_row(mstate, gen)
        return mstate

    # The engine is factored into init_state / advance / finalize so a
    # driver with a host between generations (deap_tpu/resilience) can
    # checkpoint the full loop state at any generation boundary and
    # resume bit-exactly: per-generation keys derive from
    # fold_in(key, gen) — stateless in the generation index — so the
    # only state is what these functions carry in the state dict.

    def begin_telemetry(ngen: int, n: int) -> None:
        """Declare this loop's telemetry (meter built-ins + probes) and
        journal the run start. ``init_state`` calls it; a resumed run
        (whose gen-0 happened in an earlier process) calls it directly
        so the fresh Meter knows the metric set the checkpointed
        mstate was built against."""
        from deap_tpu.algorithms import _tel_declare
        tel.begin_run("gp_loop", None, declare=_tel_declare,
                      probes=probes, ngen=ngen, n=n, cxpb=cxpb,
                      mutpb=mutpb)

    def init_state(key, genomes, ngen: int) -> dict:
        n = int(np.asarray(genomes["length"]).shape[0])
        from deap_tpu.telemetry.journal import broadcast
        broadcast("variation_dispatch", op="gp_loop", path=compaction,
                  n=n,
                  # what the variation plane reads back per generation:
                  # three count scalars (device) vs both flag arrays
                  # (host, 1 byte/bool) — the journal evidence that the
                  # device path's compaction runs without a host sync
                  host_fetch_bytes_per_gen=(
                      12 if _device_compaction else n // 2 + n))
        if plan is not None:
            genomes = plan.place(genomes, fresh=False)
        depths = depths_of(genomes)
        fit = evaluate(genomes)
        if plan is not None:
            depths = plan.place(depths, fresh=False)
            fit = plan.place(fit, fresh=False)
        state = {"gen": 0, "genomes": genomes, "depths": depths,
                 "fit": fit, "nevals": [n], "stopped_at": None,
                 "mstate": None}
        best_i = int(jnp.argmax(fit))
        state["best_genome"] = jax.tree_util.tree_map(
            lambda a: a[best_i], genomes)
        state["best_fitness"] = float(fit[best_i])
        if tel is not None:
            begin_telemetry(ngen, n)
            state["mstate"] = _measure(tel.meter.init(), n, genomes,
                                       fit, 0)
        return state

    def advance(key, state: dict) -> dict:
        """One generation, in place: gen ``state['gen'] + 1`` of the
        run keyed by ``key``. Sets ``stopped_at`` when a HealthMonitor
        requested an early stop (the caller's loop honours it)."""
        genomes, depths, fit = (state["genomes"], state["depths"],
                                state["fit"])
        n = int(np.asarray(genomes["length"]).shape[0])
        gen = state["gen"] + 1
        k = jax.random.fold_in(key, gen)
        k_sel, k_var = jax.random.split(k)
        genomes, depths, fit, sel_idx = select(k_sel, genomes,
                                               depths, fit)
        genomes, depths, t_idx, ne = vary(k_var, genomes, depths, n)
        state["nevals"].append(ne)
        if ne:
            P = min(_round_size(ne), n)
            # identical padded index values either way: the device
            # array is already cycle-padded (np.resize semantics), the
            # host array cycles here
            padded = (t_idx[:P] if _device_compaction
                      else jnp.asarray(np.resize(t_idx, P)))
            sub = jax.tree_util.tree_map(lambda a: a[padded], genomes)
            w = evaluate(sub)
            # full-padded scatter (cycled duplicates agree) — see
            # vary_host for the shape-class rationale
            fit = fit.at[padded].set(w)
        best_i = int(jnp.argmax(fit))
        if float(fit[best_i]) > state["best_fitness"]:
            state["best_genome"] = jax.tree_util.tree_map(
                lambda a: a[best_i], genomes)
            state["best_fitness"] = float(fit[best_i])
        if plan is not None:
            # re-pin the carried arrays to the plan between host
            # dispatches (scatters can hand back replicated layouts);
            # an already-correct leaf passes through without a copy
            genomes = plan.place(genomes, fresh=False)
            depths = plan.place(depths, fresh=False)
            fit = plan.place(fit, fresh=False)
        state.update(gen=gen, genomes=genomes, depths=depths, fit=fit)
        if tel is not None:
            state["mstate"] = _measure(state["mstate"], ne, genomes,
                                       fit, gen, sel_idx)
            # the host is in the loop, so tripwires can actually
            # stop the run — the scanned loops can only journal
            if tel.health is not None and tel.health.stop_requested:
                state["stopped_at"] = gen
        return state

    def finalize(state: dict, ngen: int) -> dict:
        if tel is not None:
            tel.end_run("gp_loop", ngen=ngen,
                        stopped_at=state["stopped_at"])
        return {"genomes": state["genomes"], "depths": state["depths"],
                "fitness": state["fit"],
                "best_genome": state["best_genome"],
                "best_fitness": state["best_fitness"],
                "nevals": state["nevals"],
                "stopped_at": state["stopped_at"]}

    def run(key, genomes, ngen: int):
        state = init_state(key, genomes, ngen)
        while state["gen"] < ngen and state["stopped_at"] is None:
            advance(key, state)
        return finalize(state, ngen)

    run.select = select              # exposed for tests
    run.vary = vary
    run.vary_host = vary_host        # parity oracle (tests/bench)
    run.vary_device = vary_device
    run.flags_compact = flags_compact
    run.compaction = compaction
    run.depths_of = depths_of
    run.plan = plan
    run.init_state = init_state     # segmented driving (resilience)
    run.advance = advance
    run.finalize = finalize
    run.begin_telemetry = begin_telemetry if tel is not None else None
    run.telemetry = tel
    return run


def _gp_mode_probe_fns(pset: PrimitiveSet, max_len: int, X,
                       probe_pop: int):
    """Race the three batch-interpreter modes on a small generated
    population over the actual training points. All modes are
    bit-identical (tests/test_gp_dispatch.py), so the probe compares
    the prediction matrices bitwise before trusting a timing."""
    gen = make_generator(pset, max_len, 1, 2, "half_and_half")
    keys = jax.random.split(jax.random.key(0), probe_pop)
    genomes = jax.block_until_ready(jax.vmap(gen)(keys))

    def make(m):
        def fn():
            interp = make_batch_interpreter(pset, max_len, mode=m)
            return np.asarray(interp(genomes, X))
        return fn

    return {m: make(m) for m in ("scan", "sweep", "grouped")}


def resolve_gp_mode(pset: PrimitiveSet, max_len: int, X, *,
                    default: str = "grouped",
                    probe_pop: int = 64) -> str:
    """``mode='auto'`` for the GP batch interpreter, resolved through
    the dispatch tuner's env / cache / probe / static ladder
    (:func:`deap_tpu.tuning.resolve`). This is the call site with a
    training set in hand, so it is where the probe actually runs;
    :func:`make_batch_interpreter` resolves the same knob cache-only.
    """
    from deap_tpu import tuning

    names = ("scan", "sweep", "grouped")
    candidates = dict.fromkeys(names)
    if tuning.active_tuner() is not None and tuning.is_concrete(X):
        candidates = _gp_mode_probe_fns(pset, max_len, X, probe_pop)
    return tuning.resolve(
        "gp_mode", bucket=(tuning.shape_bucket(max_len),),
        default=default, candidates=candidates, check="bitwise",
        program="gp_interpreter")


def make_symbreg_loop(pset: PrimitiveSet, max_len: int, X, y, *,
                      cxpb: float = 0.5, mutpb: float = 0.1,
                      mode: str = "grouped", chunk: int = DEFAULT_CHUNK,
                      dedup: Optional[bool] = None,
                      points_tile: Optional[int] = None,
                      **loop_kwargs) -> Callable:
    """The canonical symbolic-regression configuration of
    :func:`make_gp_loop`: negative-MSE fitness through the specialized
    batch interpreter (``mode='grouped'`` + dedup by default;
    ``mode='auto'`` probes scan/sweep/grouped through the dispatch
    tuner, falling back to 'grouped' — the measured CPU winner — when
    tuning is off)."""
    if mode == "auto":
        mode = resolve_gp_mode(pset, max_len, X, default="grouped")
    interp = make_batch_interpreter(pset, max_len, mode=mode,
                                    chunk=chunk, dedup=dedup,
                                    points_tile=points_tile)
    y = jnp.asarray(y, jnp.float32)
    mse = jax.jit(lambda preds: -jnp.mean((preds - y[None, :]) ** 2,
                                          axis=1))

    def evaluate(genomes):
        # fitness reduces on the unique rows; only the scalars expand
        preds, inv = interp.unique(genomes, X)
        vals = mse(preds)
        return vals if inv is None else vals[inv]

    return make_gp_loop(pset, max_len, evaluate, cxpb=cxpb, mutpb=mutpb,
                        **loop_kwargs)
