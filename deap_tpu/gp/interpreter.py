"""Batched GP evaluation — a stack machine over prefix arrays.

This replaces the reference's per-individual string codegen + Python
``eval`` (/root/reference/deap/gp.py:462-487, the most TPU-hostile stack
in the reference per SURVEY.md §3.3) with a vectorised prefix-tree
interpreter: one ``lax.scan`` over node slots, operating on a stack of
*data vectors*, ``vmap``-batched over the population. Evaluating a
population of trees on all datapoints is a single XLA program with no
per-individual dispatch, and — unlike the reference, which hits a
MemoryError past depth ~90 via nested lambda eval (gp.py:481-487) — cost
is strictly O(max_len · vocab · points).

Execution model — two passes over the prefix, both ``lax.scan``:

1. **Child-table pre-pass (ints only).** Walk the prefix right-to-left
   with a stack of *slot indices*: for each operator slot record which
   slots hold its operands. This touches only ``int32[max_len]``
   arrays, so its per-tree dynamic pushes cost nothing.
2. **Data pass.** Walk slots right-to-left filling an output buffer
   ``out[max_len, points]``: every primitive is evaluated on the
   slots' operand rows (vocab is small — the VPU eats the redundancy),
   the node id selects the row, and the result lands at ``out[slot]``.

The pre-pass exists so the data pass writes at a **batch-uniform**
index (the scan's own slot counter): under ``vmap`` a per-tree write
position turns ``dynamic_update_slice`` into a scatter, which forces
XLA to copy the whole data buffer every step — measured ~250× slower
than the arithmetic itself. With uniform write positions the buffer
updates alias in place and only the (read-only) operand *gathers* are
per-tree. In prefix order children always sit at higher slots than
their parent, so right-to-left slot order evaluates children first for
every tree regardless of its length.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet


def child_table(nodes: jnp.ndarray, length, arity: jnp.ndarray,
                max_ar: int) -> jnp.ndarray:
    """Child-slot table for a prefix genome — the int-only pre-pass
    shared by this module's interpreter and the ADF branch interpreter
    (gp/adf.py).

    Walks the prefix right-to-left with a stack of slot indices; entry
    ``[slot, i]`` of the returned ``int32[ML, max_ar]`` is the slot
    holding operand *i* of the node at ``slot`` (garbage, never
    referenced, for terminals and padding).
    """
    ML = nodes.shape[0]
    ar_all = jnp.where(jnp.arange(ML) < length, arity[nodes], 0)

    def pre(carry, t):
        stack, sp = carry
        rt = ML - 1 - t
        valid = rt < length
        children = jnp.stack([
            lax.dynamic_index_in_dim(stack, sp - 1 - i, keepdims=False)
            for i in range(max_ar)])
        new_sp = jnp.where(valid, sp - ar_all[rt] + 1, sp)
        pushed = lax.dynamic_update_index_in_dim(
            stack, rt, new_sp - 1, axis=0)
        stack = jnp.where(valid, pushed, stack)
        return (stack, new_sp), children

    _, ch = lax.scan(
        pre, (jnp.zeros(ML + max_ar, jnp.int32), jnp.int32(0)),
        jnp.arange(ML))
    return ch[::-1]


def run_data_pass(pset: PrimitiveSet, max_len: int, genome, X,
                  prim_rows: Callable) -> jnp.ndarray:
    """Shared two-pass evaluation core (this module's interpreter and
    the ADF branch interpreter in gp/adf.py).

    ``prim_rows(ops_in) -> [rows]`` evaluates every primitive on the
    operand vectors (the ADF interpreter dispatches call nodes into
    other branches here); everything else — child table, output buffer,
    row selection, padding semantics — is identical across both.
    Returns the root's value vector ``f32[points]``.
    """
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)
    const_row = pset.n_ops + pset.n_args

    nodes, consts, length = (genome["nodes"], genome["consts"],
                             genome["length"])
    # genome arrays may be wider than this interpreter's max_len
    # (semantic operators build wide offspring but cap ``length``,
    # gp/semantic.py _keep_if_fits) or narrower; only the first
    # min(width, max_len) slots can hold real nodes
    ML = min(nodes.shape[0], max_len)
    nodes = nodes[:ML]
    consts = consts[:ML]
    P = X.shape[0]
    argsT = X.T.astype(jnp.float32)                # [n_args, P]
    C = child_table(nodes, length, arity, max_ar)  # [ML, max_ar]

    # pass 2: fill the output buffer, children before parents
    def step(out, t):
        rt = ML - 1 - t                       # batch-uniform index
        # padded slots act as inert constants (never referenced by
        # any real parent's child table)
        node = jnp.where(rt < length, nodes[rt], jnp.int32(const_row))
        cr = C[rt]
        ops_in = [
            lax.dynamic_index_in_dim(out, cr[i], keepdims=False)
            for i in range(max_ar)
        ]
        rows = prim_rows(ops_in)
        rows.extend(argsT)                          # argument terminals
        rows.append(jnp.broadcast_to(consts[rt], (P,)))  # constant
        allv = jnp.stack(rows)                  # [n_ops + n_args + 1, P]
        # every constant-family id (fixed terminal or ERC) shares the
        # one constant row
        row = jnp.minimum(node, jnp.int32(const_row))
        res = lax.dynamic_index_in_dim(allv, row, keepdims=False)
        return lax.dynamic_update_index_in_dim(out, res, rt, axis=0), None

    out, _ = lax.scan(step, jnp.zeros((ML, P), jnp.float32),
                      jnp.arange(ML))
    return out[0]


def make_interpreter(pset: PrimitiveSet, max_len: int) -> Callable:
    """Build ``evaluate(genome, X) -> f32[points]`` for one tree.

    ``genome`` is the dict ``{"nodes": int32[max_len], "consts":
    f32[max_len], "length": int32}``; ``X`` is ``f32[points, n_args]``.
    vmap over genomes for populations, over X for multiple datasets.
    """
    if pset.has_adf:
        raise ValueError(
            "primitive set contains ADF calls; use "
            "deap_tpu.gp.adf.make_adf_interpreter")
    prims = list(pset.primitives)

    def interpret(genome, X):
        def prim_rows(ops_in):
            return [p.fn(*ops_in[: p.arity]) for p in prims]

        return run_data_pass(pset, max_len, genome, X, prim_rows)

    return interpret


def make_population_evaluator(pset: PrimitiveSet, max_len: int,
                              loss: Callable) -> Callable:
    """``evaluate(genomes, X, y) -> f32[pop]``-style batched evaluator:
    interpret every tree on every datapoint and reduce with ``loss(pred,
    X, ...)``. The usual symbolic-regression fitness (mean squared error
    over the sample points, examples/gp/symbreg.py:55-61) is
    ``loss=lambda pred, y: jnp.mean((pred - y) ** 2)``.
    """
    interp = make_interpreter(pset, max_len)

    def evaluate(genomes, X, y):
        preds = jax.vmap(lambda g: interp(g, X))(genomes)   # [pop, points]
        return jax.vmap(lambda p: loss(p, y))(preds)

    return evaluate
