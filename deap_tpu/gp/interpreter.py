"""Batched GP evaluation — a stack machine over prefix arrays.

This replaces the reference's per-individual string codegen + Python
``eval`` (/root/reference/deap/gp.py:462-487, the most TPU-hostile stack
in the reference per SURVEY.md §3.3) with a vectorised prefix-tree
interpreter: one ``lax.scan`` over node slots, operating on a stack of
*data vectors*, ``vmap``-batched over the population. Evaluating a
population of trees on all datapoints is a single XLA program with no
per-individual dispatch, and — unlike the reference, which hits a
MemoryError past depth ~90 via nested lambda eval (gp.py:481-487) — cost
is strictly O(max_len · vocab · points).

Execution model: scan the prefix right-to-left; terminals push their
value vector; an operator of arity k pops k operand vectors and pushes
the result. Per slot, every primitive is evaluated on the stack top
(vocab is small — the VPU eats the redundancy) and the node id selects
the row; this is branch-free and fuses completely.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet


def make_interpreter(pset: PrimitiveSet, max_len: int) -> Callable:
    """Build ``evaluate(genome, X) -> f32[points]`` for one tree.

    ``genome`` is the dict ``{"nodes": int32[max_len], "consts":
    f32[max_len], "length": int32}``; ``X`` is ``f32[points, n_args]``.
    vmap over genomes for populations, over X for multiple datasets.
    """
    if pset.has_adf:
        raise ValueError(
            "primitive set contains ADF calls; use "
            "deap_tpu.gp.adf.make_adf_interpreter")
    arity = pset.arity_table()
    n_ops = pset.n_ops
    max_ar = max(pset.max_arity, 1)
    prims = list(pset.primitives)

    def interpret(genome, X):
        nodes, consts, length = (genome["nodes"], genome["consts"],
                                 genome["length"])
        P = X.shape[0]
        argsT = X.T.astype(jnp.float32)            # [n_args, P]
        stack0 = jnp.zeros((max_len + max_ar, P), jnp.float32)

        def step(carry, t):
            stack, sp = carry
            rt = length - 1 - t                    # walk the prefix backwards
            valid = rt >= 0
            slot = jnp.maximum(rt, 0)
            node = nodes[slot]
            # operand vectors from the stack top
            ops_in = [
                lax.dynamic_index_in_dim(stack, sp - 1 - i, keepdims=False)
                for i in range(max_ar)
            ]
            rows = []
            for p in prims:
                rows.append(p.fn(*ops_in[: p.arity]))
            rows.extend(argsT)                      # argument terminals
            rows.append(jnp.broadcast_to(consts[slot], (P,)))  # constant
            allv = jnp.stack(rows)                  # [n_ops + n_args + 1, P]
            # every constant-family id (fixed terminal or ERC) shares the
            # one constant row
            row = jnp.minimum(node, jnp.int32(n_ops + pset.n_args))
            res = lax.dynamic_index_in_dim(allv, row, keepdims=False)
            ar = arity[node]
            new_sp = sp - ar + 1
            new_stack = lax.dynamic_update_index_in_dim(
                stack, res, new_sp - 1, axis=0)
            stack = jnp.where(valid, new_stack, stack)
            sp = jnp.where(valid, new_sp, sp)
            return (stack, sp), None

        (stack, sp), _ = lax.scan(
            step, (stack0, jnp.int32(0)), jnp.arange(max_len))
        return stack[0]

    return interpret


def make_population_evaluator(pset: PrimitiveSet, max_len: int,
                              loss: Callable) -> Callable:
    """``evaluate(genomes, X, y) -> f32[pop]``-style batched evaluator:
    interpret every tree on every datapoint and reduce with ``loss(pred,
    X, ...)``. The usual symbolic-regression fitness (mean squared error
    over the sample points, examples/gp/symbreg.py:55-61) is
    ``loss=lambda pred, y: jnp.mean((pred - y) ** 2)``.
    """
    interp = make_interpreter(pset, max_len)

    def evaluate(genomes, X, y):
        preds = jax.vmap(lambda g: interp(g, X))(genomes)   # [pop, points]
        return jax.vmap(lambda p: loss(p, y))(preds)

    return evaluate
