"""Batched GP evaluation — a stack machine over prefix arrays.

This replaces the reference's per-individual string codegen + Python
``eval`` (/root/reference/deap/gp.py:462-487, the most TPU-hostile stack
in the reference per SURVEY.md §3.3) with a vectorised prefix-tree
interpreter: one pass over node slots, operating on a stack of *data
vectors*, ``vmap``-batched over the population. Evaluating a population
of trees on all datapoints is a single XLA program with no
per-individual dispatch, and — unlike the reference, which hits a
MemoryError past depth ~90 via nested lambda eval (gp.py:481-487) —
cost is bounded by the population's largest live prefix.

Execution model — two passes over the prefix:

1. **Child-table pre-pass (ints only).** Entry ``[slot, i]`` of the
   child table is the slot holding operand *i* of the node at ``slot``.
   Computed in closed form from the all-slots subtree-end query
   (``gp.tree.subtree_ends_all``): first child = slot+1, each next
   sibling starts where the previous subtree ends — pure gathers,
   O(L log L), no serial walk (the old L-step index-stack scan cost
   ~35 ms/gen at pop=4096 on one CPU core; this form ~5 ms).
2. **Data pass.** Walk slots right-to-left filling an output buffer
   ``out[max_len, points]``: the live primitives are evaluated on the
   slots' operand rows, the node id selects the row, and the result
   lands at ``out[slot]``.

The data pass writes at a **batch-uniform** index (the scan's own slot
counter): under ``vmap`` a per-tree write position turns
``dynamic_update_slice`` into a scatter, which forces XLA to copy the
whole data buffer every step — measured ~250× slower than the
arithmetic itself. With uniform write positions the buffer updates
alias in place and only the (read-only) operand *gathers* are per-tree.
In prefix order children always sit at higher slots than their parent,
so right-to-left slot order evaluates children first for every tree
regardless of its length.

Live-population specialization (this module's dispatch layer)
-------------------------------------------------------------

The naive data pass pays an O(vocab) ``jnp.where`` select-chain at
every slot of every tree — every primitive, transcendentals included,
evaluated whether or not any live tree uses it. Three mechanisms make
dispatch scale with what the population *actually uses* instead:

- **Live-vocab masks.** When the batch interpreter is called with
  concrete (non-traced) genomes, the population's opcode histogram is
  read on the host and the select-chain is compiled for the *live*
  subset only. Observed masks are rounded UP to the monotone union of
  every opcode seen so far by that interpreter, so the number of
  compiled variants is bounded by ``n_ops`` per interpreter — the
  mask lattice. Under ``jax.jit`` tracing the full-vocab chain is used
  (bit-identical; masking is purely an optimisation).
- **Unique-genome dispatch.** Selection duplicates winners: measured
  symbreg populations converge to ~15% unique genomes. The concrete
  path evaluates each distinct genome once and gathers results back —
  bit-identical by construction. Unique counts are rounded up on a
  coarse size lattice to bound shape-driven retraces.
- **Opcode-major evaluation** (``mode='grouped'``). Live operator
  slots are flattened across the population, sorted by
  ``(depth desc, opcode)``, and padded so every ``chunk``-slot block is
  single-opcode; evaluation is then one sequential loop over chunks
  where ``lax.switch`` applies exactly ONE primitive to each block —
  each primitive runs once per site instead of once per vocab entry
  per slot. Dependencies are honoured because children (strictly
  deeper) sort into earlier chunks. On TPU the chunk loop can run as
  one Pallas fused gather-dispatch-scatter kernel
  (``ops.kernels.gp_grouped_dispatch``). Grouped requires concrete
  genomes; under tracing it falls back to the scan chain.

All specialized paths are bit-identical to the full-vocab scan
interpreter (pinned by tests/test_gp_dispatch.py); picking one is
purely a performance decision. Measured component deltas live in
BENCH_GP.json (``bench.py --gp-race``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Sequence, Tuple
from weakref import WeakKeyDictionary

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet

#: per-pset caches: primitive dispatch closures and built interpreters,
#: keyed weakly so a dropped pset releases everything. Repeated
#: ``make_interpreter``/``make_batch_interpreter`` calls on the same
#: set hand back the SAME callable — identity-stable closures keep
#: ``jax.jit`` caches warm across toolbox rebuilds (each fresh closure
#: used to force a full retrace of every downstream jit).
_PRIM_ROWS_CACHE: "WeakKeyDictionary" = WeakKeyDictionary()
_INTERPRETER_CACHE: "WeakKeyDictionary" = WeakKeyDictionary()

#: default instruction-block size for ``mode='grouped'`` — every chunk
#: is single-opcode; smaller chunks waste less padding, larger chunks
#: amortise more per-step dispatch overhead (128 measured best on CPU
#: at pop=4096, pts=256; the TPU kernel wants sublane multiples)
DEFAULT_CHUNK = 128


def _is_concrete(*arrays) -> bool:
    return not any(isinstance(a, jax.core.Tracer) for a in arrays)


def child_table(nodes: jnp.ndarray, length, arity: jnp.ndarray,
                max_ar: int, max_active=None) -> jnp.ndarray:
    """Child-slot table for a prefix genome — closed form.

    Entry ``[slot, i]`` of the returned ``int32[ML, max_ar]`` is the
    slot holding operand *i* of the node at ``slot`` (garbage, never
    referenced, for terminals and padding). In prefix order the first
    child of an operator at ``slot`` is ``slot+1`` and each next
    sibling starts where the previous child's subtree ends, so the
    whole table is gathers over :func:`gp.tree.subtree_ends_all` —
    no serial walk. ``max_active`` is accepted for API compatibility
    (the closed form always costs O(L log L) ints, which is cheaper
    than even the bounded walk it replaced)."""
    del max_active
    from deap_tpu.gp.tree import subtree_ends_all

    ML = nodes.shape[0]
    ends = subtree_ends_all(nodes, length, arity)     # [ML], exclusive
    cols = []
    child = jnp.minimum(jnp.arange(ML, dtype=jnp.int32) + 1, ML - 1)
    for _ in range(max_ar):
        cols.append(child)
        child = jnp.minimum(ends[child].astype(jnp.int32), ML - 1)
    return jnp.stack(cols, axis=1)


def run_data_pass(pset: PrimitiveSet, max_len: int, genome, X,
                  prim_rows: Callable, max_active=None) -> jnp.ndarray:
    """Shared two-pass evaluation core (this module's interpreter and
    the ADF branch interpreter in gp/adf.py).

    ``prim_rows(ops_in) -> [(node_id, row), ...]`` evaluates the live
    primitives on the operand vectors and tags each result row with the
    node id that selects it (the ADF interpreter dispatches call nodes
    into other branches here); everything else — child table, output
    buffer, row selection, padding semantics — is identical across
    callers. Returns the root's value vector ``f32[points]``.

    ``max_active`` bounds the data pass to the live prefix: a traced
    int32 ≥ every tree's ``length``. With it the cost drops from
    O(max_len·vocab·points) to O(max_active·vocab·points) — early GP
    generations hold trees of 3-15 nodes in 64-slot genomes, so this
    is the difference between paying for the genome *width* and paying
    for the population's actual *size* (the reference's direct ``eval``
    of small trees, gp.py:462-487, only ever pays the latter).
    Batching contract: ``max_active`` must be UNBATCHED under ``vmap``
    (a population-level ``max``, closed over or passed with
    ``in_axes=None``) so every write index stays batch-uniform; a
    per-tree value would turn the output-buffer update into a scatter
    (see module docstring).
    """
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)
    const_row = pset.n_ops + pset.n_args

    nodes, consts, length = (genome["nodes"], genome["consts"],
                             genome["length"])
    # genome arrays may be wider than this interpreter's max_len
    # (semantic operators build wide offspring but cap ``length``,
    # gp/semantic.py _keep_if_fits) or narrower; only the first
    # min(width, max_len) slots can hold real nodes
    ML = min(nodes.shape[0], max_len)
    nodes = nodes[:ML]
    consts = consts[:ML]
    P = X.shape[0]
    argsT = X.T.astype(jnp.float32)                # [n_args, P]
    C = child_table(nodes, length, arity, max_ar)  # [ML, max_ar]

    # pass 2: fill the output buffer, children before parents
    def step(out, rt):
        # padded slots act as inert constants (never referenced by
        # any real parent's child table)
        node = jnp.where(rt < length, nodes[rt], jnp.int32(const_row))
        cr = C[rt]
        ops_in = [
            lax.dynamic_index_in_dim(out, cr[i], keepdims=False)
            for i in range(max_ar)
        ]
        rows = list(prim_rows(ops_in))
        rows.extend((pset.n_ops + j, a) for j, a in enumerate(argsT))
        # every constant-family id (fixed terminal or ERC) shares the
        # one constant row
        row = jnp.minimum(node, jnp.int32(const_row))
        # select-chain instead of stack+gather: XLA fuses the whole
        # chain into one elementwise pass over P, where stacking would
        # materialise a [vocab, P] buffer per tree per step (measured
        # ~2× slower on CPU at pop=4096, pts=256)
        res = jnp.broadcast_to(consts[rt], (P,))    # constant default
        for nid, r in rows:
            res = jnp.where(row == nid, r, res)
        return lax.dynamic_update_index_in_dim(out, res, rt, axis=0)

    out0 = jnp.zeros((ML, P), jnp.float32)
    if max_active is None:
        out, _ = lax.scan(lambda o, rt: (step(o, rt), None), out0,
                          jnp.arange(ML - 1, -1, -1))
    else:
        T = max_active
        out = lax.fori_loop(0, T, lambda t, o: step(o, T - 1 - t), out0)
    return out[0]


def _prim_rows_builder(pset: PrimitiveSet,
                       mask: Optional[Tuple[int, ...]] = None) -> Callable:
    """The plain-primitive dispatch shared by both interpreter
    factories (the ADF interpreter substitutes its own, gp/adf.py).

    ``mask`` — live opcode ids — restricts the returned rows to the
    primitives that actually occur in the population (the live-vocab
    specialization); ``None`` means the full set. Cached per
    ``(pset, mask)`` keyed on the operator roster, so a set extended
    afterwards rebuilds — see the module caches above."""
    if pset.has_adf:
        raise ValueError(
            "primitive set contains ADF calls; use "
            "deap_tpu.gp.adf.make_adf_interpreter")
    mask = None if mask is None else tuple(sorted(mask))
    entry = _PRIM_ROWS_CACHE.setdefault(pset, {})
    key = (pset.n_ops, mask)
    cached = entry.get(key)
    if cached is not None:
        return cached
    stale = [k for k in entry if k[0] != pset.n_ops]
    for k in stale:
        del entry[k]
    ids = range(pset.n_ops) if mask is None else mask
    prims = [(i, pset.primitives[i]) for i in ids]

    def prim_rows(ops_in):
        return [(i, p.fn(*ops_in[: p.arity])) for i, p in prims]

    entry[key] = prim_rows
    return prim_rows


def _cached_factory(pset: PrimitiveSet, key, build: Callable,
                    extra: Optional[dict] = None) -> Callable:
    """Return the interpreter cached under ``key`` for ``pset``, or
    build and remember it. The cache entry also pins the operator
    count: growing the set invalidates every interpreter built on it.
    ``extra`` — additional fields for the build's journal event (the
    batched serving engine records ``n_lanes`` and the union-mask
    popcount here, so rebuild budgets stay auditable under a run
    axis)."""
    entry = _INTERPRETER_CACHE.setdefault(pset, {})
    full_key = (pset.n_ops,) + key
    fn = entry.get(full_key)
    if fn is None:
        stale = [k for k in entry if k[0] != pset.n_ops]
        for k in stale:
            del entry[k]
        fn = build()
        entry[full_key] = fn
        # an interpreter rebuild invalidates downstream jax.jit caches —
        # exactly the silent-recompile trigger the telemetry journal
        # exists to surface; no-op unless a journal is open
        from deap_tpu.telemetry.journal import broadcast
        broadcast("gp_interpreter_build", key=repr(full_key),
                  n_stale_evicted=len(stale), **(extra or {}))
    return fn


def make_interpreter(pset: PrimitiveSet, max_len: int) -> Callable:
    """Build ``evaluate(genome, X) -> f32[points]`` for one tree.

    ``genome`` is the dict ``{"nodes": int32[max_len], "consts":
    f32[max_len], "length": int32}``; ``X`` is ``f32[points, n_args]``.
    vmap over genomes for populations, over X for multiple datasets.

    Repeated calls with the same ``(pset, max_len)`` return the SAME
    function object: the primitive dispatch, arity table and evaluator
    closure are built once per set, so rebuilding a toolbox does not
    re-derive the rows or invalidate downstream ``jax.jit`` caches.
    """
    def build():
        prim_rows = _prim_rows_builder(pset)
        pset.arity_table()  # warm the per-pset table cache at build

        def interpret(genome, X):
            return run_data_pass(pset, max_len, genome, X, prim_rows)

        return interpret

    return _cached_factory(pset, ("interp", max_len), build)


def run_sweep_pass(pset: PrimitiveSet, max_len: int, genome, X,
                   prim_rows: Callable, n_sweeps,
                   max_active=None) -> jnp.ndarray:
    """Level-synchronous evaluation: instead of walking slots serially
    (``run_data_pass``), every live slot is (re)evaluated **in
    parallel** each sweep; after ``s`` sweeps every node of height
    < ``s`` holds its final value, so ``n_sweeps = max tree height + 1``
    sweeps suffice.  Trades sweeps× redundant flops for eliminating the
    per-slot serial loop entirely — each sweep is one fused gather +
    elementwise pass over ``[slots, points]``, the shape the VPU (and a
    CPU's vector units) actually like.  ``n_sweeps`` must be unbatched
    under ``vmap`` (a population-level reduction), like
    ``run_data_pass``'s ``max_active``. ``prim_rows`` uses the same
    ``[(node_id, row), ...]`` contract as :func:`run_data_pass`.
    """
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)
    const_row = pset.n_ops + pset.n_args

    nodes, consts, length = (genome["nodes"], genome["consts"],
                             genome["length"])
    ML = min(nodes.shape[0], max_len)
    nodes = nodes[:ML]
    consts = consts[:ML]
    P = X.shape[0]
    argsT = X.T.astype(jnp.float32)                 # [n_args, P]
    C = child_table(nodes, length, arity, max_ar)   # [ML, max_ar]

    node = jnp.where(jnp.arange(ML) < length, nodes, jnp.int32(const_row))
    row = jnp.minimum(node, jnp.int32(const_row))   # [ML]
    const_plane = jnp.broadcast_to(consts[:, None], (ML, P))

    def sweep(out):
        ops_in = [jnp.take(out, C[:, i], axis=0) for i in range(max_ar)]
        rows = list(prim_rows(ops_in))              # each [ML, P]
        rows.extend((pset.n_ops + j,
                     jnp.broadcast_to(a[None, :], (ML, P)))
                    for j, a in enumerate(argsT))
        res = const_plane
        for nid, r in rows:
            res = jnp.where((row == nid)[:, None], r, res)
        return res

    out = lax.fori_loop(0, n_sweeps, lambda s, o: sweep(o),
                        jnp.zeros((ML, P), jnp.float32))
    return out[0]


# ---------------------------------------------------------- size lattices ----

def _round_size(n: int, floor: int = 8) -> int:
    """Round ``n`` up on a coarse geometric lattice ({pow2, 0.75·pow2})
    so data-dependent batch/schedule sizes hit a bounded set of compiled
    shapes (~2 per size decade)."""
    n = max(int(n), 1)
    if n <= floor:
        return floor
    p = 1 << (n - 1).bit_length()
    if (3 * p) // 4 >= n:
        return (3 * p) // 4
    return p


def compact_indices(mask, cap: int):
    """jit-safe prefix-sum compaction with ``np.resize`` pad semantics.

    The device replacement for the host-dispatch loops'
    ``np.nonzero(mask)`` + ``np.resize(idx, P)`` round trip: one cumsum
    positions every True row, a drop-mode scatter packs their indices
    into the front of a static ``cap``-length buffer, and the tail is
    filled by **cycling** the packed prefix — ``out[k] = idx[k % count]``,
    exactly ``np.resize``'s pad rule (pinned by
    tests/test_gp_compaction.py), so any ``out[:P]`` slice with
    ``P <= cap`` is bit-identical to the host path's padded array.
    Shapes are static; nothing leaves the device.

    :param mask: ``bool[n]`` selection mask.
    :param cap: static output capacity (``>= max possible count``).
    :returns: ``(idx int32[cap], count int32)`` — ``idx`` is all zeros
        when ``count == 0`` (callers skip the dispatch entirely, like
        the host path's ``if len(pidx):`` guard).
    """
    mask = mask.astype(bool)
    # gather-only formulation: packed[k] = index of the (k+1)-th True
    # = searchsorted into the inclusive prefix (XLA CPU's generic
    # scatter runs ~10x slower than the binary search here, and on TPU
    # both stay on device)
    inc = jnp.cumsum(mask.astype(jnp.int32))
    count = inc[-1] if mask.shape[0] else jnp.int32(0)
    k = jnp.arange(cap, dtype=jnp.int32)
    packed = jnp.searchsorted(inc, k + 1, side="left").astype(jnp.int32)
    cyc = k % jnp.maximum(count, 1)
    out = jnp.where(k < count, packed, packed[cyc])
    return jnp.where(count > 0, out, 0), count


def _used_ops(n_ops: int, nodes: np.ndarray, length: np.ndarray
              ) -> Tuple[int, ...]:
    """The population's live opcode set, read from concrete arrays."""
    live = np.arange(nodes.shape[1])[None, :] < length[:, None]
    ids = nodes[live]
    return tuple(np.unique(ids[ids < n_ops]).tolist())


def _dedup_rows(nodes: np.ndarray, consts: np.ndarray, length: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
    """(first_indices, inverse) over byte-identical live prefixes —
    padding slots are normalised out so two genomes equal on their live
    prefix dedup together even when their padding differs."""
    live = np.arange(nodes.shape[1])[None, :] < length[:, None]
    nn = np.where(live, nodes, -1).astype(np.int32)
    cc = np.where(live, consts, 0.0).astype(np.float32)
    blob = np.ascontiguousarray(np.concatenate([nn, cc.view(np.int32)], 1))
    seen: dict = {}
    inv = np.empty(len(blob), np.int64)
    first = []
    for i, row in enumerate(blob):
        b = row.tobytes()
        j = seen.get(b)
        if j is None:
            seen[b] = j = len(first)
            first.append(i)
        inv[i] = j
    return np.asarray(first, np.int64), inv


# ------------------------------------------------- grouped (opcode-major) ----

def _round_chunks(n: int) -> int:
    """Chunk-count lattice: pure powers of two (floor 8). The chunk
    count is the ONLY data-dependent static in the grouped evaluator's
    jit signature, so its lattice directly bounds recompiles — a
    typical run's growth path hits 8→16→32→64 and stops."""
    n = max(int(n), 1)
    return max(8, 1 << (n - 1).bit_length())


def _ends_np(nodes: np.ndarray, length: np.ndarray,
             arity: np.ndarray) -> np.ndarray:
    """Numpy port of ``gp.tree.subtree_ends_all`` for a population —
    the grouped schedule builder runs on the host every generation, and
    a jitted ends/depths helper would re-specialize (compile) on every
    new population-size class; this costs ~3 ms at [4096, 64] and never
    compiles anything."""
    pop, L = nodes.shape
    live = np.arange(L)[None, :] < length[:, None]
    deficit = np.where(live, arity[nodes] - 1, 0).astype(np.int64)
    cs = np.cumsum(deficit, axis=1)
    prev = np.concatenate(
        [np.zeros((pop, 1), cs.dtype), cs[:, :-1]], axis=1)
    NEG = -(2 ** 30)
    levels = [cs]
    k = 1
    while k < L:
        m = levels[-1]
        shifted = np.concatenate(
            [m[:, k:], np.full((pop, k), NEG, cs.dtype)], axis=1)
        levels.append(np.minimum(m, shifted))
        k *= 2
    target = prev - 1
    rows = np.arange(pop)[:, None]
    pos = np.broadcast_to(np.arange(L), (pop, L)).copy()
    for lev in reversed(range(len(levels))):
        step = 1 << lev
        block_min = np.where(
            pos < L, levels[lev][rows, np.minimum(pos, L - 1)], NEG)
        pos = np.where(block_min > target, pos + step, pos)
    return (np.minimum(pos, L - 1) + 1).astype(np.int32)


def _depths_np(ends: np.ndarray, length: np.ndarray) -> np.ndarray:
    """Numpy port of ``gp.tree.prefix_depths`` given the ends —
    ``depth[j] = j − #{live i : end_i ≤ j}``."""
    pop, L = ends.shape
    live = np.arange(L)[None, :] < length[:, None]
    rows = np.broadcast_to(np.arange(pop)[:, None], (pop, L))
    hist = np.zeros((pop, L + 1), np.int32)
    np.add.at(hist, (rows, np.clip(np.where(live, ends, L), 0, L)),
              live.astype(np.int32))
    closed_by = np.cumsum(hist, axis=1)[:, :-1]
    return (np.arange(L)[None, :] - closed_by).astype(np.int32)


def build_grouped_schedule(pset: PrimitiveSet, nodes: np.ndarray,
                           consts: np.ndarray, length: np.ndarray,
                           ends: np.ndarray, depths: np.ndarray,
                           mask: Sequence[int], chunk: int) -> dict:
    """Compile a concrete population into an opcode-major instruction
    schedule (host side, numpy).

    Every live operator slot becomes one instruction; instructions are
    sorted by ``(depth desc, opcode)`` and each ``(depth, opcode)`` run
    is padded to a multiple of ``chunk`` so every chunk is pure (single
    opcode). Children are strictly deeper than their parents, so chunk
    order is a valid evaluation order. Operands reference the shared
    value buffer: rows ``0..n_args-1`` hold the input arguments,
    row ``n_args + position`` holds instruction ``position``'s result;
    constant operands are inlined. The chunk count is rounded up on the
    size lattice (:func:`_round_size`) so schedules hit a bounded set
    of compiled shapes; pad chunks run opcode 0 on argument row 0 and
    write only their own rows (never referenced).
    """
    n_ops, n_args = pset.n_ops, pset.n_args
    max_ar = max(pset.max_arity, 1)
    const_id = pset.const_id
    pop, ML = nodes.shape
    branch_of = {op: b for b, op in enumerate(mask)}

    live = np.arange(ML)[None, :] < length[:, None]
    is_op = live & (nodes < n_ops)
    ti, si = np.nonzero(is_op)
    opc = nodes[ti, si]
    dep = depths[ti, si]
    order = np.lexsort((opc, -dep))
    ti, si, opc, dep = ti[order], si[order], opc[order], dep[order]
    ni = len(ti)

    if ni:
        grp = np.empty(ni, np.int64)
        grp[0] = 0
        grp[1:] = np.cumsum((dep[1:] != dep[:-1]) | (opc[1:] != opc[:-1]))
        counts = np.bincount(grp)
        padded = -(-counts // chunk) * chunk
        offs = np.concatenate([[0], np.cumsum(padded)])
        within = np.arange(ni) - np.concatenate(
            [[0], np.cumsum(counts)])[grp]
        posn = offs[grp] + within
        nchunks = int(offs[-1]) // chunk
    else:
        posn = np.zeros(0, np.int64)
        nchunks = 0
    nchunks = _round_chunks(nchunks)
    total = nchunks * chunk

    # value-row index per (tree, slot): op slots -> n_args + position,
    # argument slots -> their argument row; constants stay inline
    val_row = np.zeros((pop, ML), np.int32)
    val_row[ti, si] = n_args + posn
    arg_sites = live & (nodes >= n_ops) & (nodes < const_id)
    val_row[arg_sites] = nodes[arg_sites] - n_ops
    const_sites = live & (nodes >= const_id)

    chunk_ops = np.zeros(nchunks, np.int32)
    if ni:
        chunk_ops[posn // chunk] = np.vectorize(branch_of.get)(opc)

    src_idx = np.zeros((total, max_ar), np.int32)
    src_const = np.zeros((total, max_ar), np.float32)
    src_isc = np.zeros((total, max_ar), bool)
    if ni:
        # children: first child = slot+1, next siblings at subtree ends
        child = np.minimum(si + 1, ML - 1)
        for j in range(max_ar):
            cc = const_sites[ti, child]
            src_idx[posn, j] = val_row[ti, child]
            src_isc[posn, j] = cc
            src_const[posn, j] = np.where(cc, consts[ti, child], 0.0)
            child = np.minimum(ends[ti, child], ML - 1)

    root_live = length > 0
    root_idx = val_row[:, 0].astype(np.int32)
    root_isc = const_sites[:, 0] | ~root_live
    root_const = np.where(root_live, consts[:, 0], 0.0).astype(np.float32)
    return {
        "chunk_ops": chunk_ops, "src_idx": src_idx,
        "src_const": src_const, "src_isc": src_isc,
        "root_idx": root_idx, "root_const": root_const,
        "root_isc": root_isc, "n_instructions": ni, "nchunks": nchunks,
    }


def _grouped_eval_builder(pset: PrimitiveSet, mask: Tuple[int, ...],
                          chunk: int) -> Callable:
    """The jitted chunk-loop evaluator for one live mask: sequential
    ``lax.switch`` over pure-opcode chunks, returning the filled value
    buffer. The ONLY data-dependent static in its signature is the
    chunk count (latticed by :func:`_round_chunks`); root extraction is
    done eagerly by the dispatcher so population-size classes never
    re-specialize this function."""
    n_args = pset.n_args
    max_ar = max(pset.max_arity, 1)
    branches = [
        (lambda f=pset.primitives[op].fn, a=pset.primitives[op].arity:
         (lambda ops: f(*ops[:a])))()
        for op in mask
    ] or [lambda ops: ops[0]]

    @jax.jit
    def evaluate(chunk_ops, src_idx, src_const, src_isc, X):
        P = X.shape[0]
        nchunks = chunk_ops.shape[0]
        argsT = X.T.astype(jnp.float32)
        buf = jnp.zeros((n_args + nchunks * chunk, P), jnp.float32)
        buf = lax.dynamic_update_slice_in_dim(buf, argsT, 0, axis=0)

        def step(c, buf):
            base = c * chunk
            si = lax.dynamic_slice_in_dim(src_idx, base, chunk)
            sc = lax.dynamic_slice_in_dim(src_const, base, chunk)
            sb = lax.dynamic_slice_in_dim(src_isc, base, chunk)
            ops_in = [jnp.where(sb[:, j, None], sc[:, j, None],
                                buf[si[:, j]]) for j in range(max_ar)]
            res = lax.switch(chunk_ops[c], branches, ops_in)
            return lax.dynamic_update_slice_in_dim(
                buf, res, n_args + base, axis=0)

        return lax.fori_loop(0, nchunks, step, buf)

    return evaluate


def _grouped_eval_kernel_builder(pset: PrimitiveSet,
                                 mask: Tuple[int, ...],
                                 chunk: int) -> Callable:
    """TPU path: same schedule, evaluated by the Pallas fused
    gather-dispatch-scatter kernel (one kernel launch for the whole
    chunk sequence — see ops.kernels.gp_grouped_dispatch)."""
    from deap_tpu.ops.kernels import gp_grouped_dispatch

    n_args = pset.n_args
    fns = [(pset.primitives[op].fn, pset.primitives[op].arity)
           for op in mask] or [(lambda a: a, 1)]

    @jax.jit
    def evaluate(chunk_ops, src_idx, src_const, src_isc, X):
        P = X.shape[0]
        argsT = X.T.astype(jnp.float32)
        nrows = n_args + chunk_ops.shape[0] * chunk
        buf = jnp.zeros((nrows, P), jnp.float32)
        buf = lax.dynamic_update_slice_in_dim(buf, argsT, 0, axis=0)
        return gp_grouped_dispatch(buf, chunk_ops, src_idx, src_const,
                                   src_isc, fns, chunk=chunk,
                                   n_args=n_args)

    return evaluate


# --------------------------------------------------------- batch dispatch ----

def make_batch_interpreter(pset: PrimitiveSet, max_len: int,
                           mode: str = "scan",
                           specialize: str = "auto",
                           dedup: Optional[bool] = None,
                           points_tile: Optional[int] = None,
                           chunk: int = DEFAULT_CHUNK) -> Callable:
    """Build ``interpret(genomes, X) -> f32[pop, points]`` over a whole
    population — the fast path for fitness evaluation.

    Unlike ``vmap(make_interpreter(...))``, this computes the
    population's active length ``T = max(length)`` and bounds the
    interpreter passes to ``T`` slots instead of the full ``max_len``
    genome width.  ``T`` is closed over the vmapped per-tree call, so
    vmap keeps it unbatched and every buffer write stays batch-uniform
    (the contract in :func:`run_data_pass`).

    :param mode: ``'scan'`` — serial slot walk (two-pass, the portable
        default); ``'sweep'`` — level-synchronous
        (:func:`run_sweep_pass`): ``max-height+1`` parallel sweeps over
        all slots; ``'grouped'`` — opcode-major chunked dispatch (each
        live primitive evaluated exactly once per site; requires
        concrete genomes, falls back to ``scan`` under tracing; on TPU
        the chunk loop runs as one Pallas kernel).
    :param specialize: ``'auto'`` — when called with concrete (eager)
        genomes, compile the select-chain for the live opcode subset
        only, rounded monotonically (mask lattice) so recompiles are
        bounded by ``n_ops``; ``'none'`` — always the full vocabulary
        (the pre-specialization behaviour).
    :param dedup: evaluate each distinct genome once and gather results
        back (concrete path only; bit-identical). Default: on when
        ``specialize='auto'``.
    :param points_tile: evaluate the points axis in tiles of this many
        rows so the ``out[T, points]`` buffer stays cache-resident at
        large point counts (both paths; bit-identical — points never
        interact).
    :param chunk: grouped-mode instruction block size.

    All modes/specializations return bit-identical results (pinned by
    tests/test_gp_dispatch.py); pick by measurement — BENCH_GP.json
    holds the per-component deltas measured by ``bench.py --gp-race``,
    and ``mode='auto'`` asks the dispatch tuner
    (:func:`deap_tpu.tuning.resolve`: ``DEAP_TPU_TUNE_GP_MODE`` env →
    cached probe winner → ``'scan'``; probing itself happens where a
    training set is in hand — :func:`deap_tpu.gp.loop
    .resolve_gp_mode`).
    """
    if mode == "auto":
        from deap_tpu import tuning

        mode = tuning.resolve(
            "gp_mode", bucket=(tuning.shape_bucket(max_len),),
            default="scan",
            candidates={"scan": None, "sweep": None, "grouped": None},
            check=None, program="gp_interpreter")
    if mode not in ("scan", "sweep", "grouped"):
        raise ValueError(f"unknown interpreter mode {mode!r}")
    if specialize not in ("auto", "none"):
        raise ValueError(f"unknown specialize policy {specialize!r}")
    dedup = (specialize == "auto") if dedup is None else dedup

    def build():
        return _build_batch_dispatcher(pset, max_len, mode, specialize,
                                       dedup, points_tile, chunk)

    return _cached_factory(
        pset, ("batch", max_len, mode, specialize, dedup, points_tile,
               chunk), build)


def _traced_batch(pset: PrimitiveSet, max_len: int, mode: str,
                  mask: Optional[Tuple[int, ...]] = None) -> Callable:
    """The pure traced population interpreter (usable inside user jit):
    scan or sweep over the live prefix, optionally mask-specialized."""
    prim_rows = _prim_rows_builder(pset, mask)
    arity = pset.arity_table()
    ML_cap = max_len

    def interpret_batch(genomes, X):
        ML = min(genomes["nodes"].shape[-1], ML_cap)
        T = jnp.clip(jnp.max(genomes["length"]), 1, ML).astype(jnp.int32)

        if mode == "sweep":
            from deap_tpu.gp.tree import prefix_depths

            def height_of(g):
                d = prefix_depths(g["nodes"][:ML], g["length"], arity)
                live = jnp.arange(ML) < g["length"]
                return jnp.max(jnp.where(live, d, 0))

            D = jnp.clip(jax.vmap(height_of)(genomes).max() + 1,
                         1, T).astype(jnp.int32)

            def one(g):
                return run_sweep_pass(pset, max_len, g, X, prim_rows,
                                      n_sweeps=D, max_active=T)
        else:
            def one(g):
                return run_data_pass(pset, max_len, g, X, prim_rows,
                                     max_active=T)

        return jax.vmap(one)(genomes)

    return interpret_batch


def _points_pad(X, tile: int):
    P = X.shape[0]
    nt = -(-P // tile)
    pad = nt * tile - P
    if pad:
        X = jnp.concatenate([X, jnp.broadcast_to(X[:1], (pad,) + X.shape[1:])])
    return X, nt, P


def _build_batch_dispatcher(pset: PrimitiveSet, max_len: int, mode: str,
                            specialize: str, dedup: bool,
                            points_tile: Optional[int],
                            chunk: int) -> Callable:
    pset.arity_table()  # warm the table cache outside any trace
    base_mode = "scan" if mode == "grouped" else mode
    base = _traced_batch(pset, max_len, base_mode)
    if points_tile:
        base_untiled = base

        def base(genomes, X):
            Xp, nt, P = _points_pad(X, points_tile)
            tiles = Xp.reshape(nt, points_tile, -1)
            preds = lax.map(lambda xt: base_untiled(genomes, xt), tiles)
            return jnp.moveaxis(preds, 0, 1).reshape(
                genomes["length"].shape[0], nt * points_tile)[:, :P]

    if specialize == "none":
        return base

    state = {"mask": (), "journaled": None}
    arity_np = np.asarray([p.arity for p in pset.primitives]
                          + [0] * (pset.vocab - pset.n_ops), np.int32)

    def _mask_for(nodes_np, length_np):
        used = _used_ops(pset.n_ops, nodes_np, length_np)
        mask = tuple(sorted(set(state["mask"]) | set(used)))
        state["mask"] = mask
        return mask

    def _jit_traced(mask, key):
        return _cached_factory(
            pset, key + (mask,),
            lambda: jax.jit(_traced_batch(pset, max_len, base_mode,
                                          mask)))

    def _grouped_fn(mask):
        backend = jax.default_backend()
        if backend == "tpu":
            return _cached_factory(
                pset, ("grpk", max_len, chunk, mask),
                lambda: _grouped_eval_kernel_builder(pset, mask, chunk))
        return _cached_factory(
            pset, ("grp", max_len, chunk, mask),
            lambda: _grouped_eval_builder(pset, mask, chunk))

    def _journal(mask, extra):
        tag = (mask,) + tuple(sorted(extra.items()))
        if state["journaled"] != tag:
            state["journaled"] = tag
            from deap_tpu.telemetry.journal import broadcast
            # n_lanes=1: this dispatcher serves one population; the
            # batched serving engine journals its own gp_dispatch rows
            # with its lane count (same schema, auditable together)
            broadcast("gp_dispatch", mode=mode,
                      mask=[pset.primitives[i].name for i in mask],
                      mask_popcount=len(mask), n_lanes=1,
                      **extra)

    def _concrete_unique(genomes, X):

        nodes_np = np.asarray(genomes["nodes"])[:, :max_len]
        consts_np = np.asarray(genomes["consts"])[:, :max_len]
        length_np = np.asarray(genomes["length"])
        pop = nodes_np.shape[0]
        mask = _mask_for(nodes_np, length_np)

        first = inv = None
        if dedup:
            first, inv = _dedup_rows(nodes_np, consts_np, length_np)

        if mode == "grouped":
            # the grouped evaluator's only shape class is the chunk
            # count, so the deduped subset needs no padding here
            if dedup:
                nodes_np, consts_np = nodes_np[first], consts_np[first]
                length_np = length_np[first]
            ends = _ends_np(nodes_np, length_np, arity_np)
            depths = _depths_np(ends, length_np)
            sched = build_grouped_schedule(
                pset, nodes_np, consts_np, length_np, ends, depths,
                mask, chunk)
            fn = _grouped_fn(mask)
            args = [jnp.asarray(sched[k]) for k in
                    ("chunk_ops", "src_idx", "src_const", "src_isc")]
            _journal(mask, {"nchunks": sched["nchunks"],
                            "n_unique": len(first) if dedup else pop})
            ri, rc, rb = (sched["root_idx"], sched["root_const"],
                          sched["root_isc"])
            if dedup:
                # latticed root count: the eager root gather otherwise
                # compiles per exact unique-count shape every call
                nr = min(_round_size(len(ri)), pop)
                ri, rc, rb = (np.resize(ri, nr), np.resize(rc, nr),
                              np.resize(rb, nr))
            root_isc = jnp.asarray(rb)[:, None]
            root_const = jnp.asarray(rc)[:, None]
            root_idx = jnp.asarray(ri)
            if points_tile:
                Xp, nt, P = _points_pad(X, points_tile)
                outs = [fn(*args, Xp[t * points_tile:
                                     (t + 1) * points_tile])
                        for t in range(nt)]
                buf = jnp.concatenate(outs, axis=1)[:, :P]
            else:
                buf = fn(*args, X)
            preds = jnp.where(root_isc, root_const, buf[root_idx])
        else:
            if dedup:
                # jitted per sub-batch shape: pad the unique count on
                # the size lattice so shape classes stay bounded
                nu = _round_size(len(first), floor=min(8, pop))
                sel = np.resize(first, min(nu, pop))
                nodes_np, consts_np = nodes_np[sel], consts_np[sel]
                length_np = length_np[sel]
            sub = {"nodes": jnp.asarray(nodes_np),
                   "consts": jnp.asarray(consts_np),
                   "length": jnp.asarray(length_np)}
            fn = _jit_traced(mask, ("batchj", max_len, base_mode,
                                    bool(points_tile), points_tile))
            _journal(mask, {"n_unique": len(first) if dedup else pop})
            if points_tile:
                Xp, nt, P = _points_pad(X, points_tile)
                outs = [fn(sub, Xp[t * points_tile:(t + 1) * points_tile])
                        for t in range(nt)]
                preds = jnp.concatenate(outs, axis=1)[:, :P]
            else:
                preds = fn(sub, X)

        if dedup:
            return preds, jnp.asarray(inv)
        return preds[:pop], None

    def interpret_batch(genomes, X):
        leaves = [genomes["nodes"], genomes["consts"],
                  genomes["length"], X]
        if not _is_concrete(*leaves):
            return base(genomes, X)
        preds, inv = _concrete_unique(genomes, X)
        return preds if inv is None else preds[inv]

    def interpret_unique(genomes, X):
        """(preds, inverse) without the un-dedup expansion: callers
        reducing preds to per-tree scalars (fitness) should reduce
        FIRST and gather the scalars through ``inverse`` — that skips
        a [pop, points] gather per evaluation. ``inverse`` is None
        when nothing was deduplicated (use preds row-for-row)."""
        leaves = [genomes["nodes"], genomes["consts"],
                  genomes["length"], X]
        if not _is_concrete(*leaves):
            return base(genomes, X), None
        return _concrete_unique(genomes, X)

    interpret_batch.unique = interpret_unique
    return interpret_batch


def make_population_evaluator(pset: PrimitiveSet, max_len: int,
                              loss: Callable,
                              mode: str = "scan",
                              **dispatch_kwargs) -> Callable:
    """``evaluate(genomes, X, y) -> f32[pop]``-style batched evaluator:
    interpret every tree on every datapoint and reduce with ``loss(pred,
    X, ...)``. The usual symbolic-regression fitness (mean squared error
    over the sample points, examples/gp/symbreg.py:55-61) is
    ``loss=lambda pred, y: jnp.mean((pred - y) ** 2)``.

    ``mode`` and the specialization knobs are forwarded to
    :func:`make_batch_interpreter` — keep the default ``"scan"`` inside
    jit; eager callers get live-vocab masking and unique-genome
    dispatch automatically (``specialize='auto'``), and may pick
    ``mode='grouped'`` for opcode-major evaluation.
    """
    interp = make_batch_interpreter(pset, max_len, mode=mode,
                                    **dispatch_kwargs)
    unique = getattr(interp, "unique", None)

    def evaluate(genomes, X, y):
        if unique is None:
            preds = interp(genomes, X)                      # [pop, points]
            return jax.vmap(lambda p: loss(p, y))(preds)
        # reduce on the UNIQUE rows, then expand the per-tree scalars:
        # skips a [pop, points] un-dedup gather per evaluation
        preds, inv = unique(genomes, X)
        vals = jax.vmap(lambda p: loss(p, y))(preds)
        return vals if inv is None else vals[inv]

    return evaluate
