"""Batched GP evaluation — a stack machine over prefix arrays.

This replaces the reference's per-individual string codegen + Python
``eval`` (/root/reference/deap/gp.py:462-487, the most TPU-hostile stack
in the reference per SURVEY.md §3.3) with a vectorised prefix-tree
interpreter: one pass over node slots (a ``lax.scan``, or a
``fori_loop`` with a dynamic trip count on the batch path), operating
on a stack of *data vectors*, ``vmap``-batched over the population.
Evaluating a population of trees on all datapoints is a single XLA
program with no per-individual dispatch, and — unlike the reference,
which hits a MemoryError past depth ~90 via nested lambda eval
(gp.py:481-487) — cost is O(max_len · vocab · points) worst case, or
O(max_active · vocab · points) via :func:`make_batch_interpreter`,
which bounds both passes to the population's largest live prefix
``T = max(length)``.

Execution model — two passes over the prefix:

1. **Child-table pre-pass (ints only).** Walk the prefix right-to-left
   with a stack of *slot indices*: for each operator slot record which
   slots hold its operands. This touches only ``int32[max_len]``
   arrays, so its per-tree dynamic pushes cost nothing.
2. **Data pass.** Walk slots right-to-left filling an output buffer
   ``out[max_len, points]``: every primitive is evaluated on the
   slots' operand rows (vocab is small — the VPU eats the redundancy),
   the node id selects the row, and the result lands at ``out[slot]``.

The pre-pass exists so the data pass writes at a **batch-uniform**
index (the scan's own slot counter): under ``vmap`` a per-tree write
position turns ``dynamic_update_slice`` into a scatter, which forces
XLA to copy the whole data buffer every step — measured ~250× slower
than the arithmetic itself. With uniform write positions the buffer
updates alias in place and only the (read-only) operand *gathers* are
per-tree. In prefix order children always sit at higher slots than
their parent, so right-to-left slot order evaluates children first for
every tree regardless of its length.
"""

from __future__ import annotations

from functools import partial
from typing import Callable
from weakref import WeakKeyDictionary

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu.gp.pset import PrimitiveSet

#: per-pset caches: primitive dispatch closures and built interpreters,
#: keyed weakly so a dropped pset releases everything. Repeated
#: ``make_interpreter``/``make_batch_interpreter`` calls on the same
#: set hand back the SAME callable — identity-stable closures keep
#: ``jax.jit`` caches warm across toolbox rebuilds (each fresh closure
#: used to force a full retrace of every downstream jit).
_PRIM_ROWS_CACHE: "WeakKeyDictionary" = WeakKeyDictionary()
_INTERPRETER_CACHE: "WeakKeyDictionary" = WeakKeyDictionary()


def child_table(nodes: jnp.ndarray, length, arity: jnp.ndarray,
                max_ar: int, max_active=None) -> jnp.ndarray:
    """Child-slot table for a prefix genome — the int-only pre-pass
    shared by this module's interpreter and the ADF branch interpreter
    (gp/adf.py).

    Walks the prefix right-to-left with a stack of slot indices; entry
    ``[slot, i]`` of the returned ``int32[ML, max_ar]`` is the slot
    holding operand *i* of the node at ``slot`` (garbage, never
    referenced, for terminals and padding).

    ``max_active`` (a traced scalar ≥ every tree's ``length``) bounds
    the walk to the population's live prefix instead of the full genome
    width — see :func:`run_data_pass` for the batching contract.
    """
    ML = nodes.shape[0]
    ar_all = jnp.where(jnp.arange(ML) < length, arity[nodes], 0)

    def pre(carry, rt):
        stack, sp = carry
        valid = rt < length
        children = jnp.stack([
            lax.dynamic_index_in_dim(stack, sp - 1 - i, keepdims=False)
            for i in range(max_ar)])
        new_sp = jnp.where(valid, sp - ar_all[rt] + 1, sp)
        pushed = lax.dynamic_update_index_in_dim(
            stack, rt, new_sp - 1, axis=0)
        stack = jnp.where(valid, pushed, stack)
        return (stack, new_sp), children

    if max_active is None:
        _, ch = lax.scan(
            pre, (jnp.zeros(ML + max_ar, jnp.int32), jnp.int32(0)),
            jnp.arange(ML - 1, -1, -1))
        return ch[::-1]

    # dynamic trip count: only slots < max_active can be live, so the
    # right-to-left walk may start at max_active-1.  The write position
    # rt stays batch-uniform as long as max_active is unbatched under
    # vmap (a population-level reduction closed over per-tree calls).
    T = max_active

    def body(t, carry):
        stack, sp, ch = carry
        rt = T - 1 - t
        (stack, sp), children = pre((stack, sp), rt)
        ch = lax.dynamic_update_index_in_dim(ch, children, rt, axis=0)
        return stack, sp, ch

    _, _, ch = lax.fori_loop(
        0, T, body,
        (jnp.zeros(ML + max_ar, jnp.int32), jnp.int32(0),
         jnp.zeros((ML, max_ar), jnp.int32)))
    return ch


def run_data_pass(pset: PrimitiveSet, max_len: int, genome, X,
                  prim_rows: Callable, max_active=None) -> jnp.ndarray:
    """Shared two-pass evaluation core (this module's interpreter and
    the ADF branch interpreter in gp/adf.py).

    ``prim_rows(ops_in) -> [rows]`` evaluates every primitive on the
    operand vectors (the ADF interpreter dispatches call nodes into
    other branches here); everything else — child table, output buffer,
    row selection, padding semantics — is identical across both.
    Returns the root's value vector ``f32[points]``.

    ``max_active`` bounds both passes to the live prefix: a traced
    int32 ≥ every tree's ``length``.  With it the cost drops from
    O(max_len·vocab·points) to O(max_active·vocab·points) — early GP
    generations hold trees of 3-15 nodes in 64-slot genomes, so this
    is the difference between paying for the genome *width* and paying
    for the population's actual *size* (the reference's direct ``eval``
    of small trees, gp.py:462-487, only ever pays the latter).
    Batching contract: ``max_active`` must be UNBATCHED under ``vmap``
    (a population-level ``max``, closed over or passed with
    ``in_axes=None``) so every write index stays batch-uniform; a
    per-tree value would turn the output-buffer update into a scatter
    (see module docstring).
    """
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)
    const_row = pset.n_ops + pset.n_args

    nodes, consts, length = (genome["nodes"], genome["consts"],
                             genome["length"])
    # genome arrays may be wider than this interpreter's max_len
    # (semantic operators build wide offspring but cap ``length``,
    # gp/semantic.py _keep_if_fits) or narrower; only the first
    # min(width, max_len) slots can hold real nodes
    ML = min(nodes.shape[0], max_len)
    nodes = nodes[:ML]
    consts = consts[:ML]
    P = X.shape[0]
    argsT = X.T.astype(jnp.float32)                # [n_args, P]
    C = child_table(nodes, length, arity, max_ar,
                    max_active=max_active)         # [ML, max_ar]

    # pass 2: fill the output buffer, children before parents
    def step(out, rt):
        # padded slots act as inert constants (never referenced by
        # any real parent's child table)
        node = jnp.where(rt < length, nodes[rt], jnp.int32(const_row))
        cr = C[rt]
        ops_in = [
            lax.dynamic_index_in_dim(out, cr[i], keepdims=False)
            for i in range(max_ar)
        ]
        rows = prim_rows(ops_in)
        rows.extend(argsT)                          # argument terminals
        # every constant-family id (fixed terminal or ERC) shares the
        # one constant row
        row = jnp.minimum(node, jnp.int32(const_row))
        # select-chain instead of stack+gather: XLA fuses the whole
        # chain into one elementwise pass over P, where stacking would
        # materialise a [vocab, P] buffer per tree per step (measured
        # ~2× slower on CPU at pop=4096, pts=256)
        res = jnp.broadcast_to(consts[rt], (P,))    # constant default
        for i, r in enumerate(rows):
            res = jnp.where(row == i, r, res)
        return lax.dynamic_update_index_in_dim(out, res, rt, axis=0)

    out0 = jnp.zeros((ML, P), jnp.float32)
    if max_active is None:
        out, _ = lax.scan(lambda o, rt: (step(o, rt), None), out0,
                          jnp.arange(ML - 1, -1, -1))
    else:
        T = max_active
        out = lax.fori_loop(0, T, lambda t, o: step(o, T - 1 - t), out0)
    return out[0]


def _prim_rows_builder(pset: PrimitiveSet) -> Callable:
    """The plain-primitive dispatch shared by both interpreter
    factories (the ADF interpreter substitutes its own, gp/adf.py).
    Cached per pset (keyed on the operator roster, so a set extended
    afterwards rebuilds) — see the module caches above."""
    if pset.has_adf:
        raise ValueError(
            "primitive set contains ADF calls; use "
            "deap_tpu.gp.adf.make_adf_interpreter")
    cached = _PRIM_ROWS_CACHE.get(pset)
    if cached is not None and cached[0] == pset.n_ops:
        return cached[1]
    prims = list(pset.primitives)

    def prim_rows(ops_in):
        return [p.fn(*ops_in[: p.arity]) for p in prims]

    _PRIM_ROWS_CACHE[pset] = (pset.n_ops, prim_rows)
    return prim_rows


def _cached_factory(pset: PrimitiveSet, key, build: Callable) -> Callable:
    """Return the interpreter cached under ``key`` for ``pset``, or
    build and remember it. The cache entry also pins the operator
    count: growing the set invalidates every interpreter built on it."""
    entry = _INTERPRETER_CACHE.setdefault(pset, {})
    full_key = (pset.n_ops,) + key
    fn = entry.get(full_key)
    if fn is None:
        stale = [k for k in entry if k[0] != pset.n_ops]
        for k in stale:
            del entry[k]
        fn = build()
        entry[full_key] = fn
        # an interpreter rebuild invalidates downstream jax.jit caches —
        # exactly the silent-recompile trigger the telemetry journal
        # exists to surface; no-op unless a journal is open
        from deap_tpu.telemetry.journal import broadcast
        broadcast("gp_interpreter_build", key=repr(full_key),
                  n_stale_evicted=len(stale))
    return fn


def make_interpreter(pset: PrimitiveSet, max_len: int) -> Callable:
    """Build ``evaluate(genome, X) -> f32[points]`` for one tree.

    ``genome`` is the dict ``{"nodes": int32[max_len], "consts":
    f32[max_len], "length": int32}``; ``X`` is ``f32[points, n_args]``.
    vmap over genomes for populations, over X for multiple datasets.

    Repeated calls with the same ``(pset, max_len)`` return the SAME
    function object: the primitive dispatch, arity table and evaluator
    closure are built once per set, so rebuilding a toolbox does not
    re-derive the rows or invalidate downstream ``jax.jit`` caches.
    """
    def build():
        prim_rows = _prim_rows_builder(pset)
        pset.arity_table()  # warm the per-pset table cache at build

        def interpret(genome, X):
            return run_data_pass(pset, max_len, genome, X, prim_rows)

        return interpret

    return _cached_factory(pset, ("interp", max_len), build)


def run_sweep_pass(pset: PrimitiveSet, max_len: int, genome, X,
                   prim_rows: Callable, n_sweeps,
                   max_active=None) -> jnp.ndarray:
    """Level-synchronous evaluation: instead of walking slots serially
    (``run_data_pass``), every live slot is (re)evaluated **in
    parallel** each sweep; after ``s`` sweeps every node of height
    < ``s`` holds its final value, so ``n_sweeps = max tree height + 1``
    sweeps suffice.  Trades sweeps× redundant flops for eliminating the
    per-slot serial loop entirely — each sweep is one fused gather +
    elementwise pass over ``[slots, points]``, the shape the VPU (and a
    CPU's vector units) actually like.  ``n_sweeps`` must be unbatched
    under ``vmap`` (a population-level reduction), like
    ``run_data_pass``'s ``max_active``.
    """
    arity = pset.arity_table()
    max_ar = max(pset.max_arity, 1)
    const_row = pset.n_ops + pset.n_args

    nodes, consts, length = (genome["nodes"], genome["consts"],
                             genome["length"])
    ML = min(nodes.shape[0], max_len)
    nodes = nodes[:ML]
    consts = consts[:ML]
    P = X.shape[0]
    argsT = X.T.astype(jnp.float32)                 # [n_args, P]
    C = child_table(nodes, length, arity, max_ar,
                    max_active=max_active)          # [ML, max_ar]

    node = jnp.where(jnp.arange(ML) < length, nodes, jnp.int32(const_row))
    row = jnp.minimum(node, jnp.int32(const_row))   # [ML]
    const_plane = jnp.broadcast_to(consts[:, None], (ML, P))

    def sweep(out):
        ops_in = [jnp.take(out, C[:, i], axis=0) for i in range(max_ar)]
        rows = prim_rows(ops_in)                    # each [ML, P]
        rows.extend(jnp.broadcast_to(a[None, :], (ML, P)) for a in argsT)
        res = const_plane
        for i, r in enumerate(rows):
            res = jnp.where((row == i)[:, None], r, res)
        return res

    out = lax.fori_loop(0, n_sweeps, lambda s, o: sweep(o),
                        jnp.zeros((ML, P), jnp.float32))
    return out[0]


def make_batch_interpreter(pset: PrimitiveSet, max_len: int,
                           mode: str = "scan") -> Callable:
    """Build ``interpret(genomes, X) -> f32[pop, points]`` over a whole
    population — the fast path for fitness evaluation.

    Unlike ``vmap(make_interpreter(...))``, this computes the
    population's active length ``T = max(length)`` and bounds both
    interpreter passes to ``T`` slots instead of the full ``max_len``
    genome width.  ``T`` is closed over the vmapped per-tree call, so
    vmap keeps it unbatched and every buffer write stays batch-uniform
    (the contract in :func:`run_data_pass`).  Early generations (trees
    of 3-15 nodes in 64-slot genomes) evaluate ~4-20× less work; cost
    tracks bloat exactly like the reference's direct ``eval`` of the
    current trees (gp.py:462-487) rather than the genome width.

    ``mode='sweep'`` switches the data pass to the level-synchronous
    form (:func:`run_sweep_pass`): ``max-height+1`` parallel sweeps
    over all slots instead of ``T`` serial steps.  Results are
    identical; pick by measurement.  Measured (pop=4096, pts=256,
    vocab 10, one CPU core): scan 136/270/327 ms vs sweep
    1268/2261/2848 ms on small/mid/large trees — the sweeps' full-width
    × vocab redundancy (every slot re-evaluates every primitive every
    sweep, transcendentals included) buries the serial-step savings on
    CPU; the mode exists for accelerator measurement, where wide fused
    elementwise passes are closer to free and serial scan steps are
    not.
    """
    if mode not in ("scan", "sweep"):
        raise ValueError(f"unknown interpreter mode {mode!r}")

    def build():
        return _build_batch_interpreter(pset, max_len, mode)

    return _cached_factory(pset, ("batch", max_len, mode), build)


def _build_batch_interpreter(pset: PrimitiveSet, max_len: int,
                             mode: str) -> Callable:
    prim_rows = _prim_rows_builder(pset)
    ML_cap = max_len
    arity = pset.arity_table()

    def interpret_batch(genomes, X):
        ML = min(genomes["nodes"].shape[-1], ML_cap)
        T = jnp.clip(jnp.max(genomes["length"]), 1, ML).astype(jnp.int32)

        if mode == "sweep":
            from deap_tpu.gp.tree import prefix_depths

            def height_of(g):
                d = prefix_depths(g["nodes"][:ML], g["length"], arity)
                live = jnp.arange(ML) < g["length"]
                return jnp.max(jnp.where(live, d, 0))

            D = jnp.clip(jax.vmap(height_of)(genomes).max() + 1,
                         1, T).astype(jnp.int32)

            def one(g):
                return run_sweep_pass(pset, max_len, g, X, prim_rows,
                                      n_sweeps=D, max_active=T)
        else:
            def one(g):
                return run_data_pass(pset, max_len, g, X, prim_rows,
                                     max_active=T)

        return jax.vmap(one)(genomes)

    return interpret_batch


def make_population_evaluator(pset: PrimitiveSet, max_len: int,
                              loss: Callable,
                              mode: str = "scan") -> Callable:
    """``evaluate(genomes, X, y) -> f32[pop]``-style batched evaluator:
    interpret every tree on every datapoint and reduce with ``loss(pred,
    X, ...)``. The usual symbolic-regression fitness (mean squared error
    over the sample points, examples/gp/symbreg.py:55-61) is
    ``loss=lambda pred, y: jnp.mean((pred - y) ** 2)``.

    ``mode`` is forwarded to :func:`make_batch_interpreter` — keep the
    default ``"scan"`` on CPU; ``"sweep"`` is the level-synchronous
    variant for accelerator measurement.
    """
    interp = make_batch_interpreter(pset, max_len, mode=mode)

    def evaluate(genomes, X, y):
        preds = interp(genomes, X)                          # [pop, points]
        return jax.vmap(lambda p: loss(p, y))(preds)

    return evaluate
