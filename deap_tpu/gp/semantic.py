"""Geometric semantic GP operators (Moraglio et al. 2012).

Counterpart of the reference's ``mutSemantic``/``cxSemantic``
(/root/reference/deap/gp.py:1215-1329): offspring are built *syntactically*
as arithmetic combinations of the parents and fresh random trees —

- mutation:  child = parent + ms · (lf(tr1) − lf(tr2))
- crossover: child1 = ind1·lf(tr) + (1 − lf(tr))·ind2 (and symmetrically)

— where ``lf`` is the logistic function squashing the random trees into
(0, 1). Like the reference, the operators require ``add``/``sub``/``mul``
/``lf`` primitives to exist in the set (gp.py:1244-1245, 1306-1307).

On fixed-width prefix arrays the construction is a pure segment
concatenation; when the composed program would exceed ``max_len`` the
parent is returned unchanged (the array-width analog of the unbounded
list growth that makes reference GSGP runs explode in memory).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

import jax
import jax.numpy as jnp

from deap_tpu.gp.pset import PrimitiveSet
from deap_tpu.gp.tree import Genome


def logistic(x: jnp.ndarray) -> jnp.ndarray:
    """lf(x) = 1 / (1 + e^{-x}) (the doctest helper at gp.py:1231)."""
    return jax.nn.sigmoid(x)


def add_semantic_primitives(pset: PrimitiveSet) -> PrimitiveSet:
    """Ensure the add/sub/mul/lf vocabulary the semantic operators
    require; missing ones are appended, plus a fixed literal terminal
    for the injected ms / 1.0 constants.

    Call this BEFORE generating any genomes: appending primitives or
    terminals renumbers node ids, so genomes generated from the set's
    earlier layout would silently decode wrongly afterwards."""
    names = {p.name for p in pset.primitives}
    if "add" not in names:
        pset.add_primitive(jnp.add, 2, "add", "({0} + {1})")
    if "sub" not in names:
        pset.add_primitive(jnp.subtract, 2, "sub", "({0} - {1})")
    if "mul" not in names:
        pset.add_primitive(jnp.multiply, 2, "mul", "({0} * {1})")
    if "lf" not in names:
        pset.add_primitive(logistic, 1, "lf")
    if pset.n_consts == 0:
        # dedicated literal slot, distinct from any ERC id so
        # mut_ephemeral never resamples injected constants
        pset.add_terminal(1.0, "1.0")
    return pset


def _prim_id(pset: PrimitiveSet, name: str) -> int:
    for i, p in enumerate(pset.primitives):
        if p.name == name:
            return i
    raise ValueError(
        f"a {name!r} function is required in order to perform semantic "
        "variation (gp.py:1244-1245); call add_semantic_primitives(pset)")


def _literal_id(pset: PrimitiveSet) -> int:
    """A *fixed-terminal* node id usable as an inline literal (its value
    lives in the parallel consts array). An ERC id would be resampled by
    ``mut_ephemeral`` (tree.py targets ``nodes == erc_id``), silently
    rewriting the injected ms / 1.0 constants — so a fixed terminal is
    required; ``add_semantic_primitives`` provides one."""
    if pset.n_consts == 0:
        raise ValueError(
            "semantic operators need a fixed terminal to host literal "
            "constants; call add_semantic_primitives(pset) before "
            "generating genomes")
    return pset.const_id


def _scalar(node_id, value=0.0):
    return (jnp.asarray([node_id], jnp.int32),
            jnp.asarray([value], jnp.float32), jnp.int32(1))


def _seg(g: Genome):
    return (g["nodes"], g["consts"], g["length"])


def _concat(max_len: int, parts: List[Tuple]) -> Genome:
    """Concatenate (nodes, consts, length) segments into one prefix
    array of width ``max_len`` (slots past the total are padding)."""
    k = jnp.arange(max_len)
    nodes = jnp.zeros((max_len,), jnp.int32)
    consts = jnp.zeros((max_len,), jnp.float32)
    off = jnp.int32(0)
    for n_src, c_src, ln in parts:
        src = jnp.clip(k - off, 0, n_src.shape[0] - 1)
        in_seg = (k >= off) & (k < off + ln)
        nodes = jnp.where(in_seg, n_src[src], nodes)
        consts = jnp.where(in_seg, c_src[src], consts)
        off = off + ln
    return {"nodes": nodes, "consts": consts, "length": off}


def _pad_to(g: Genome, max_len: int) -> Genome:
    """Widen a genome's arrays to ``max_len`` slots (semantic offspring
    are wider than their parents by construction)."""
    width = g["nodes"].shape[0]
    if width > max_len:
        raise ValueError(
            f"parent width {width} exceeds operator max_len {max_len}")
    if width == max_len:
        return g
    pad = max_len - width
    return {
        "nodes": jnp.pad(g["nodes"], (0, pad)),
        "consts": jnp.pad(g["consts"], (0, pad)),
        "length": g["length"],
    }


def _keep_if_fits(new: Genome, old: Genome, max_len: int) -> Genome:
    ok = new["length"] <= max_len
    old = _pad_to(old, max_len)
    return jax.tree_util.tree_map(
        lambda a, b: jnp.where(ok, a, b), new, old)


def make_mut_semantic(pset: PrimitiveSet, expr: Callable, max_len: int,
                      ms: Optional[float] = None) -> Callable:
    """Semantic mutation (mutSemantic, gp.py:1215-1268):
    ``child = add(parent, mul(ms, sub(lf(tr1), lf(tr2))))`` with ``tr1``,
    ``tr2`` fresh trees from ``expr`` and ``ms`` the mutation step —
    drawn uniformly from (0, 2) per application when not fixed, as in
    the reference (gp.py:1252-1253)."""
    add_i = _prim_id(pset, "add")
    sub_i = _prim_id(pset, "sub")
    mul_i = _prim_id(pset, "mul")
    lf_i = _prim_id(pset, "lf")
    lit = _literal_id(pset)

    def mut(key: jax.Array, g: Genome) -> Genome:
        k1, k2, k_ms = jax.random.split(key, 3)
        tr1 = expr(k1)
        tr2 = expr(k2)
        ms_v = (jax.random.uniform(k_ms, (), minval=0.0, maxval=2.0)
                if ms is None else jnp.float32(ms))
        new = _concat(max_len, [
            _scalar(add_i),
            _seg(g),
            _scalar(mul_i),
            (jnp.asarray([lit], jnp.int32), ms_v[None], jnp.int32(1)),
            _scalar(sub_i),
            _scalar(lf_i), _seg(tr1),
            _scalar(lf_i), _seg(tr2),
        ])
        return _keep_if_fits(new, g, max_len)

    return mut


def make_cx_semantic(pset: PrimitiveSet, expr: Callable,
                     max_len: int) -> Callable:
    """Semantic crossover (cxSemantic, gp.py:1270-1329):
    ``child1 = add(mul(ind1, lf(tr)), mul(sub(1, lf(tr)), ind2))`` and
    symmetrically for child2, with ONE shared random tree ``tr`` per
    mating, as in the reference."""
    add_i = _prim_id(pset, "add")
    sub_i = _prim_id(pset, "sub")
    mul_i = _prim_id(pset, "mul")
    lf_i = _prim_id(pset, "lf")
    lit = _literal_id(pset)

    def one_child(a: Genome, b: Genome, tr: Genome) -> Genome:
        return _concat(max_len, [
            _scalar(add_i), _scalar(mul_i),
            _seg(a),
            _scalar(lf_i), _seg(tr),
            _scalar(mul_i), _scalar(sub_i), _scalar(lit, 1.0),
            _scalar(lf_i), _seg(tr),
            _seg(b),
        ])

    def cx(key: jax.Array, g1: Genome, g2: Genome) -> Tuple[Genome, Genome]:
        tr = expr(key)
        c1 = _keep_if_fits(one_child(g1, g2, tr), g1, max_len)
        c2 = _keep_if_fits(one_child(g2, g1, tr), g2, max_len)
        return c1, c2

    return cx
