"""Primitive sets — the GP instruction vocabulary as static tables.

Counterpart of the reference's ``PrimitiveSet`` / ``PrimitiveSetTyped``
(/root/reference/deap/gp.py:260-456), re-designed for tensor trees: the
set compiles to static arrays (arity table, terminal masks, constant
pool) consumed by the batched interpreter and the on-device tree
operators. Where the reference stores Python callables evaluated through
string codegen + ``eval`` (gp.py:462-487), primitives here are jnp
element-wise functions applied to stack slices — no codegen, no eval,
jit-safe.

Node-id encoding for a set with ``n_ops`` operators, ``n_args`` inputs
and a constant pool:

- ``0 .. n_ops-1``       — operators (arity from ``arity_table``)
- ``n_ops .. n_ops+n_args-1`` — input arguments ARG0..ARGn
- ``n_ops+n_args .. +n_consts-1`` — fixed constant terminals
- ``n_ops+n_args+n_consts``        — the ephemeral constant (ERC)

Every constant-family node reads its value from the parallel ``consts``
array (covering the reference's fixed terminals and ephemerals,
gp.py:187-257); distinct ids let ``mut_ephemeral`` target only ERCs
while the interpreter collapses all of them onto one shared stack row.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class _Primitive:
    name: str
    fn: Optional[Callable]  # (a, b, c ...) element-wise jnp function
    arity: int
    fmt: Optional[str] = None  # e.g. "({0} + {1})" for pretty printing
    adf: Optional[int] = None  # branch index when this is an ADF call

    def format(self, *args: str) -> str:
        if self.fmt:
            return self.fmt.format(*args)
        return f"{self.name}({', '.join(args)})"


class PrimitiveSet:
    """Untyped strongly-vectorised primitive set.

    :param name: set name (kept for parity with gp.py:447-456).
    :param arity: number of input arguments (the reference's ``in_types``
        count for untyped sets).
    :param prefix: argument name prefix (``ARG0``, ``ARG1``, ...).
    """

    def __init__(self, name: str, arity: int, prefix: str = "ARG"):
        self.name = name
        self.n_args = arity
        self.arg_names = [f"{prefix}{i}" for i in range(arity)]
        self.primitives: List[_Primitive] = []
        self.const_values: List[float] = []     # fixed terminal pool
        self.const_names: List[str] = []
        self.erc_sampler: Optional[Callable] = None
        self.erc_name: Optional[str] = None

    # ------------------------------------------------------------ builder ----

    def add_primitive(self, fn: Callable, arity: int,
                      name: Optional[str] = None,
                      fmt: Optional[str] = None) -> None:
        """Register an operator (gp.py:339-360). ``fn`` must be an
        element-wise jnp function of ``arity`` arrays."""
        assert arity >= 1, "arity should be >= 1"
        self.primitives.append(
            _Primitive(name or fn.__name__, fn, arity, fmt))

    def add_adf(self, name: str, arity: int, branch: int) -> None:
        """Register an Automatically Defined Function call (the tensor
        counterpart of ``PrimitiveSetTyped.addADF``, gp.py:414-423):
        node invokes branch ``branch`` of the same individual on its
        ``arity`` operand vectors. Only :func:`deap_tpu.gp.adf.
        make_adf_interpreter` understands these nodes."""
        assert arity >= 1, "ADFs take at least one argument"
        self.primitives.append(_Primitive(name, None, arity, None, branch))

    @property
    def has_adf(self) -> bool:
        return any(p.adf is not None for p in self.primitives)

    def add_terminal(self, value: float, name: Optional[str] = None) -> None:
        """Register a constant terminal (gp.py:362-382). Stored in the
        constant pool; sampled uniformly among fixed terminals."""
        self.const_values.append(float(value))
        self.const_names.append(name if name is not None else repr(value))

    def add_ephemeral_constant(self, name: str,
                               sampler: Callable[[jax.Array], jnp.ndarray]) -> None:
        """Register an ephemeral random constant (gp.py:384-414):
        ``sampler(key) -> scalar`` drawn fresh for every ERC node."""
        if self.erc_sampler is not None:
            raise ValueError("one ephemeral constant pool per set")
        self.erc_sampler = sampler
        self.erc_name = name

    def rename_arguments(self, **kwargs: str) -> None:
        """Rename ARGi (gp.py:418-428): ``pset.rename_arguments(ARG0='x')``."""
        for key, val in kwargs.items():
            if key.startswith("ARG"):
                self.arg_names[int(key[3:])] = val

    # ------------------------------------------------------------- tables ----

    @property
    def n_ops(self) -> int:
        return len(self.primitives)

    @property
    def n_consts(self) -> int:
        return len(self.const_values)

    @property
    def has_erc(self) -> bool:
        return self.erc_sampler is not None

    @property
    def const_id(self) -> int:
        """First constant-family node id; every id >= this reads the
        ``consts`` array (one shared interpreter row)."""
        return self.n_ops + self.n_args

    @property
    def erc_id(self) -> int:
        """Node id of the ephemeral constant (valid only if has_erc)."""
        return self.n_ops + self.n_args + self.n_consts

    @property
    def vocab(self) -> int:
        return self.n_ops + self.n_args + self.n_consts + (1 if self.has_erc else 0)

    @property
    def max_arity(self) -> int:
        return max((p.arity for p in self.primitives), default=0)

    @property
    def n_terminal_choices(self) -> int:
        """Distinct terminal draws: args + fixed consts + ERC
        (the denominator of the reference's terminalRatio, gp.py:306)."""
        return self.n_args + self.n_consts + (1 if self.has_erc else 0)

    @property
    def terminal_ratio(self) -> float:
        """terminals / (terminals + primitives) (gp.py:303-308)."""
        t = self.n_terminal_choices
        return t / (t + self.n_ops)

    def arity_table(self) -> jnp.ndarray:
        """int32[vocab] — operator arities then zeros for terminals.

        Built once and cached against the vocabulary state: the
        interpreters fetch this on every evaluation pass, and handing
        back the same device array keeps eager calls from re-uploading
        it and retraces from re-baking a fresh constant. A set extended
        after the first call (more primitives/terminals) rebuilds."""
        key = (self.n_ops, self.vocab,
               tuple(p.arity for p in self.primitives))
        cached = getattr(self, "_arity_table_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        n_term = self.vocab - self.n_ops
        table = jnp.asarray(
            [p.arity for p in self.primitives] + [0] * n_term, jnp.int32)
        if isinstance(table, jax.core.Tracer) or not (
                jax.core.trace_state_clean()):
            # first call happened under a trace: the array belongs to
            # that trace — handing it to a later caller would leak a
            # tracer, so serve it uncached
            return table
        self._arity_table_cache = (key, table)
        return table

    def sample_terminal(self, key: jax.Array) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Uniform terminal draw → (node_id, const_value)."""
        k_c, k_v = jax.random.split(key)
        n_t = self.n_terminal_choices
        choice = jax.random.randint(k_c, (), 0, n_t)
        node = self.n_ops + choice                 # ids are laid out in order
        if self.n_consts:
            pool = jnp.asarray(self.const_values, jnp.float32)
            fixed = pool[jnp.clip(choice - self.n_args, 0, self.n_consts - 1)]
        else:
            fixed = jnp.float32(0.0)
        if self.has_erc:
            erc = self.erc_sampler(k_v)
            value = jnp.where(choice == self.n_args + self.n_consts, erc, fixed)
        else:
            value = fixed
        return node.astype(jnp.int32), jnp.asarray(value, jnp.float32)

    def sample_op(self, key: jax.Array,
                  max_arity: Optional[int] = None) -> jnp.ndarray:
        """Uniform operator draw; ``max_arity`` restricts to ops whose
        arity fits the remaining space."""
        if max_arity is None or max_arity >= self.max_arity:
            return jax.random.randint(key, (), 0, self.n_ops, jnp.int32)
        ok = np.asarray([p.arity <= max_arity for p in self.primitives])
        idx = np.flatnonzero(ok)
        pick = jax.random.randint(key, (), 0, len(idx))
        return jnp.asarray(idx, jnp.int32)[pick]

    # ------------------------------------------------------------ display ----

    def node_name(self, node_id: int, const: float = 0.0) -> str:
        if node_id < self.n_ops:
            return self.primitives[node_id].name
        if node_id < self.const_id:
            return self.arg_names[node_id - self.n_ops]
        if node_id < self.erc_id:
            return self.const_names[node_id - self.const_id]
        return repr(round(float(const), 6))


# ------------------------------------------------------- stock primitives ----

def protected_div(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """x/y with 1 where y == 0 (the reference's protectedDiv pattern,
    examples/gp/symbreg.py:33-37)."""
    return jnp.where(b == 0.0, 1.0, a / jnp.where(b == 0.0, 1.0, b))


def math_set(n_args: int = 1, erc_low: float = -1.0, erc_high: float = 1.0,
             trig: bool = True, erc: bool = True,
             name: str = "MAIN") -> PrimitiveSet:
    """The canonical symbolic-regression vocabulary
    (examples/gp/symbreg.py:40-51: add/sub/mul/protectedDiv/neg/cos/sin +
    uniform ERC)."""
    ps = PrimitiveSet(name, n_args)
    ps.add_primitive(jnp.add, 2, "add", "({0} + {1})")
    ps.add_primitive(jnp.subtract, 2, "sub", "({0} - {1})")
    ps.add_primitive(jnp.multiply, 2, "mul", "({0} * {1})")
    ps.add_primitive(protected_div, 2, "protectedDiv", "({0} / {1})")
    ps.add_primitive(jnp.negative, 1, "neg", "(-{0})")
    if trig:
        ps.add_primitive(jnp.cos, 1, "cos")
        ps.add_primitive(jnp.sin, 1, "sin")
    if erc:
        ps.add_ephemeral_constant(
            "rand101", lambda k: jax.random.uniform(
                k, (), minval=erc_low, maxval=erc_high))
    return ps


def bool_set(n_args: int, name: str = "BOOL") -> PrimitiveSet:
    """Boolean vocabulary over {0.0, 1.0} floats — the untyped tensor
    formulation of the reference's parity/multiplexer sets
    (examples/gp/parity.py:46-57, examples/gp/multiplexer.py:45-57)."""
    ps = PrimitiveSet(name, n_args)
    ps.add_primitive(lambda a, b: a * b, 2, "and_", "({0} & {1})")
    ps.add_primitive(lambda a, b: jnp.minimum(a + b, 1.0), 2, "or_",
                     "({0} | {1})")
    ps.add_primitive(lambda a: 1.0 - a, 1, "not_", "(~{0})")
    ps.add_primitive(lambda a, b: jnp.abs(a - b), 2, "xor_", "({0} ^ {1})")
    ps.add_primitive(lambda c, a, b: jnp.where(c > 0.5, a, b), 3,
                     "if_then_else")
    ps.add_terminal(0.0, "False")
    ps.add_terminal(1.0, "True")
    return ps
