"""Logbook — host-side chronological record with chapters and incremental
column-aligned stream printing.

Counterpart of /root/reference/deap/tools/support.py:261-487. Lives on
the host: algorithms return stacked per-generation arrays from their
scan and :func:`logbook_from_records` materialises them here. Also fully
usable imperatively (``record(gen=..., nevals=..., **stats)``), exactly
like the reference.
"""

from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np


def _scalar(x):
    """Collapse 0-d arrays to native Python scalars (so ``%g``
    formatting and JSON serialisation see floats/ints, not numpy
    types); pass everything else through unchanged."""
    a = np.asarray(x)
    if a.ndim == 0:
        return a.item()
    return a


class Logbook(list):
    def __init__(self):
        super().__init__()
        self.buffindex = 0
        self.chapters: Dict[str, "Logbook"] = {}
        self.columns_len: List[int] | None = None
        self.header: Sequence[str] | None = None
        self.log_header = True

    def record(self, **infos: Any) -> None:
        """Append one entry; dict-valued entries become chapters
        (support.py:335-349)."""
        apply_to_all = {k: v for k, v in infos.items() if not isinstance(v, dict)}
        for key, value in list(infos.items()):
            if isinstance(value, dict):
                chapter_infos = dict(value)
                chapter_infos.update(apply_to_all)
                if key not in self.chapters:
                    self.chapters[key] = Logbook()
                    self.chapters[key].columns_len = None
                self.chapters[key].record(**chapter_infos)
                del infos[key]
        self.append({k: _scalar(v) for k, v in infos.items()})

    def select(self, *names: str):
        """Columns as lists, in entry order (support.py:360-372)."""
        if len(names) == 1:
            return [entry.get(names[0], None) for entry in self]
        return tuple([entry.get(name, None) for entry in self] for name in names)

    def pop(self, index: int = 0):
        """Remove and return entry ``index``, keeping ``stream``'s
        not-yet-printed window consistent: only removing an entry that
        was *already streamed* shifts the buffer index. Negative
        indexes are normalised first — the raw comparison would treat
        ``pop(-1)`` (usually an unstreamed tail entry) as
        already-streamed and wrongly re-stream an old entry
        (support.py:351-358 has the same latent bug)."""
        if index < 0:
            index += len(self)
        if self.buffindex > index:
            self.buffindex -= 1
        return super().pop(index)

    @property
    def stream(self) -> str:
        """Text of the entries recorded since the last access, with a
        header on first use (support.py:383-399)."""
        startindex, self.buffindex = self.buffindex, len(self)
        return self.__str__(startindex)

    def _txt(self, startindex: int) -> List[List[str]]:
        columns = list(self.header) if self.header else sorted(
            self[0].keys() if self else [])
        if not self.columns_len or len(self.columns_len) != len(columns):
            self.columns_len = [len(c) for c in columns]

        chapters_txt = {}
        offsets = {}
        for name, chapter in self.chapters.items():
            chapters_txt[name] = chapter._txt(startindex)
            if startindex == 0:
                offsets[name] = len(chapters_txt[name]) - len(self)

        str_matrix = []
        for i, line in enumerate(self[startindex:], startindex):
            str_line = []
            for j, name in enumerate(columns):
                if name in chapters_txt:
                    column = chapters_txt[name][i + offsets.get(name, 0)]
                else:
                    value = line.get(name, "")
                    if isinstance(value, float):
                        column = "%g" % value
                    else:
                        column = str(value)
                self.columns_len[j] = max(self.columns_len[j], len(column))
                str_line.append(column)
            str_matrix.append(str_line)

        if startindex == 0 and self.log_header:
            header = []
            nlines = 1
            if len(self.chapters) > 0:
                nlines += max(map(len, chapters_txt.values())) - len(self) + 1
            header = [[] for _ in range(nlines)]
            for j, name in enumerate(columns):
                if name in chapters_txt:
                    length = max(len(line.expandtabs()) for line in
                                 chapters_txt[name][0].split("\n")) if chapters_txt[name] else len(name)
                    blanks = nlines - 2 - offsets.get(name, 0)
                    for i in range(blanks):
                        header[i].append(" " * length)
                    header[blanks].append(name.center(length))
                    header[blanks + 1].append("-" * length)
                    for i in range(offsets.get(name, 0)):
                        header[blanks + 2 + i].append(
                            chapters_txt[name][i])
                else:
                    length = max(len(name), self.columns_len[j])
                    for line in header[:-1]:
                        line.append(" " * length)
                    header[-1].append(name)
            str_matrix = header + str_matrix

        template = "\t".join("{%i:<%i}" % (i, l) for i, l in
                             enumerate(self.columns_len))
        text = [template.format(*line) for line in str_matrix]
        return text

    def __str__(self, startindex: int = 0) -> str:
        text = self._txt(startindex)
        return "\n".join(text)


def logbook_from_records(records, header=None) -> Logbook:
    """Build a Logbook from a pytree of stacked per-generation arrays,
    as produced by a scanned algorithm: each leaf has leading axis ngen."""
    import jax

    logbook = Logbook()
    if header:
        logbook.header = header
    leaves, treedef = jax.tree_util.tree_flatten(records)
    if not leaves:
        return logbook
    leaves = [np.asarray(l) for l in leaves]
    n = leaves[0].shape[0]
    for i in range(n):
        entry = jax.tree_util.tree_unflatten(treedef, [l[i] for l in leaves])
        logbook.record(**entry)
    return logbook
