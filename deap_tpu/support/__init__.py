from deap_tpu.support.stats import Statistics, MultiStatistics
from deap_tpu.support.logbook import Logbook
from deap_tpu.support.hof import HallOfFame, hof_init, hof_update, hof_best
from deap_tpu.support.pareto import ParetoArchive, pareto_init, pareto_update
from deap_tpu.support.history import (
    History,
    Lineage,
    lineage_init,
    lineage_step,
    pair_parents,
)
from deap_tpu.support.profiling import (
    SpanRecorder,
    annotate,
    get_span_recorder,
    set_span_recorder,
    span,
    sync,
    timed_generations,
    timed_phases,
    trace,
)
from deap_tpu.support.checkpoint import (
    AsyncCheckpointWriter,
    CheckpointCorruptError,
    CheckpointFormatError,
    Checkpointer,
    allow_compat_restore,
    checkpoint_meta,
    restore_state,
    save_state,
    set_compat_restore,
    verify_checkpoint,
)
from deap_tpu.support import compilecache

__all__ = [
    "Statistics",
    "MultiStatistics",
    "Logbook",
    "HallOfFame",
    "hof_init",
    "hof_update",
    "hof_best",
    "ParetoArchive",
    "pareto_init",
    "pareto_update",
    "History",
    "Lineage",
    "trace",
    "annotate",
    "span",
    "sync",
    "SpanRecorder",
    "set_span_recorder",
    "get_span_recorder",
    "timed_generations",
    "timed_phases",
    "lineage_init",
    "lineage_step",
    "pair_parents",
    "AsyncCheckpointWriter",
    "CheckpointCorruptError",
    "CheckpointFormatError",
    "Checkpointer",
    "allow_compat_restore",
    "checkpoint_meta",
    "compilecache",
    "save_state",
    "restore_state",
    "set_compat_restore",
    "verify_checkpoint",
]
