"""Genealogy tracking — the lineage counterpart of ``tools.History``.

The reference's ``History`` (/root/reference/deap/tools/support.py:21-152)
works by decorating ``mate``/``mutate`` so every produced individual gets
a fresh integer id and a record of its parents' ids
(support.py:105-121), building a NetworkX-compatible genealogy dict
replayable via ``getGenealogy`` (support.py:123-152).

The tensor formulation keeps ids *on device* as a per-individual extra
array (SURVEY.md §5.1: "a lineage array (parent indices per generation)
kept on device"): each generation, selection produces an index vector
into the previous population; :func:`lineage_step` turns that into fresh
child ids plus a ``[n, max_parents]`` parent-id record, all as array ops
inside the jit'd step. The host-side :class:`History` accumulates those
records (one small transfer per generation, alongside the logbook) into
the same genealogy-dict structure the reference exposes.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct


@struct.dataclass
class Lineage:
    """Device-resident lineage state.

    - ``ids``: int32[n] — the current population's individual ids.
    - ``next_id``: int32 — the next unassigned id (ids start at 1, like
      the reference's ``index`` counter, support.py:100-103).
    """

    ids: jnp.ndarray
    next_id: jnp.ndarray


def lineage_init(n: int) -> Lineage:
    """Assign ids 1..n to the founding population (the reference's
    ``history.update(population)`` on generation 0, where founders get
    themselves as their only 'parent', support.py:105-121)."""
    return Lineage(
        ids=jnp.arange(1, n + 1, dtype=jnp.int32),
        next_id=jnp.int32(n + 1),
    )


def lineage_step(
    lineage: Lineage, parent_idx: jnp.ndarray
) -> Tuple[Lineage, jnp.ndarray]:
    """Advance one generation.

    ``parent_idx``: int32[n_children, max_parents] — rows of indices into
    the *previous* population (e.g. for varAnd pairs, each child lists
    both crossover parents; clones list one parent twice). Returns the
    new lineage (fresh consecutive ids for every child) and the
    ``int32[n_children, max_parents]`` parent-*id* record to hand to
    :meth:`History.record`.
    """
    parent_idx = jnp.asarray(parent_idx, jnp.int32)
    if parent_idx.ndim == 1:      # one parent per child (mutation/clone step)
        parent_idx = parent_idx[:, None]
    n_children = parent_idx.shape[0]
    parent_ids = jnp.take(lineage.ids, parent_idx, axis=0)
    child_ids = lineage.next_id + jnp.arange(n_children, dtype=jnp.int32)
    return Lineage(ids=child_ids, next_id=lineage.next_id + n_children), parent_ids


def pair_parents(sel_idx: jnp.ndarray, cx_mask: jnp.ndarray) -> jnp.ndarray:
    """Build the varAnd parent-index matrix from a selection index vector.

    Mirrors who-mates-with-whom in the reference's ``varAnd``
    (/root/reference/deap/algorithms.py:68-76): consecutive pairs
    (0,1), (2,3), ... cross with probability cxpb. ``cx_mask``:
    bool[n//2] — which pairs actually crossed. Children that crossed get
    both pair members as parents; others get their own slot twice.
    """
    sel_idx = jnp.asarray(sel_idx, jnp.int32)
    n = sel_idx.shape[0]
    partner = jnp.arange(n, dtype=jnp.int32) ^ 1  # 0<->1, 2<->3, ...
    partner = jnp.where(partner < n, partner, jnp.arange(n, dtype=jnp.int32))
    # an odd trailing individual has no pair, hence never crosses
    crossed = jnp.zeros((n,), bool).at[: 2 * cx_mask.shape[0]].set(
        jnp.repeat(cx_mask, 2)[:n])
    other = jnp.where(crossed, sel_idx[partner], sel_idx)
    return jnp.stack([sel_idx, other], axis=1)


class History:
    """Host-side genealogy accumulator (support.py:21-152 counterpart).

    ``genealogy_tree`` maps child id → tuple of parent ids;
    ``genealogy_history`` maps generation → array of child ids born that
    generation. Feed it the per-generation ``parent_ids`` records emitted
    by :func:`lineage_step` (scanned runs can hand over the whole stacked
    ``[gens, n, p]`` array at once via :meth:`record_scan`).
    """

    def __init__(self) -> None:
        self.genealogy_tree: Dict[int, Tuple[int, ...]] = {}
        self.genealogy_history: Dict[int, np.ndarray] = {}
        self._next_id = 1
        self._gen = 0

    def found(self, n: int) -> None:
        """Register the founding population (ids 1..n, no parents)."""
        ids = np.arange(self._next_id, self._next_id + n)
        for i in ids:
            self.genealogy_tree[int(i)] = ()
        self.genealogy_history[self._gen] = ids
        self._next_id += n

    def record(self, parent_ids: np.ndarray) -> None:
        """Record one generation: row i of ``parent_ids`` lists the parent
        ids of that generation's i-th child. A 1-D array means one parent
        per child (the same convention as ``lineage_step``)."""
        parent_ids = np.asarray(parent_ids)
        if parent_ids.ndim == 1:
            parent_ids = parent_ids[:, None]
        n = parent_ids.shape[0]
        self._gen += 1
        ids = np.arange(self._next_id, self._next_id + n)
        for i, row in zip(ids, parent_ids):
            uniq = tuple(dict.fromkeys(int(p) for p in row))
            self.genealogy_tree[int(i)] = uniq
        self.genealogy_history[self._gen] = ids
        self._next_id += n

    def record_scan(self, stacked_parent_ids: np.ndarray) -> None:
        """Record a whole scanned run: ``[gens, n, max_parents]``."""
        for gen_rec in np.asarray(stacked_parent_ids):
            self.record(gen_rec)

    def get_genealogy(self, ind_id: int, max_depth: float = float("inf")) -> Dict[int, Tuple[int, ...]]:
        """Ancestor subgraph of ``ind_id`` up to ``max_depth`` generations
        (the reference's ``getGenealogy``, support.py:123-152 — which
        recurses per parent reference and re-walks shared ancestors).

        Iterative BFS with an explicit visited set: every node is
        expanded at most once, so diamond-shaped lineages (one ancestor
        reachable along several lines — ubiquitous once crossover
        recombines relatives) cost O(nodes + edges), not O(paths),
        and deep lineages cannot hit the recursion limit. A shared
        ancestor sitting at several different depths is expanded at its
        *shallowest* occurrence, which is what bounds ``max_depth``
        correctly. Pinned on a diamond in
        tests/test_checkpoint_history.py."""
        out: Dict[int, Tuple[int, ...]] = {}
        seen = {int(ind_id)}  # enqueued-ever: memo across shared ancestors
        frontier = [int(ind_id)]
        depth = 0
        while frontier and depth < max_depth:
            nxt: List[int] = []
            for cid in frontier:
                parents = self.genealogy_tree.get(cid, ())
                if parents:
                    out[cid] = parents
                    for p in parents:
                        if p not in seen:
                            seen.add(p)
                            nxt.append(p)
            frontier = nxt
            depth += 1
        return out
