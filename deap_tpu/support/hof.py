"""HallOfFame — fixed-capacity best-ever archive, resident on device.

Counterpart of /root/reference/deap/tools/support.py:490-588: a sorted,
bounded archive of the best individuals ever seen, with duplicate
suppression (the reference's ``similar=operator.eq``). Implemented so
``hof_update`` can run inside a scanned generation step: the population's
top-k rows are merged with the archive, lex-sorted, genome-deduplicated
and truncated — all static shapes.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from deap_tpu.core.fitness import FitnessSpec, lex_sort_desc
from deap_tpu.core.population import Population


@struct.dataclass
class HallOfFame:
    genomes: Any
    fitness: jnp.ndarray  # [k, nobj]
    filled: jnp.ndarray   # [k] bool
    spec: FitnessSpec = struct.field(pytree_node=False, default=FitnessSpec((1.0,)))

    @property
    def maxsize(self) -> int:
        return self.filled.shape[0]

    @property
    def wvalues(self) -> jnp.ndarray:
        w = self.fitness * self.spec.warray
        return jnp.where(self.filled[:, None], w, -jnp.inf)


def hof_init(maxsize: int, pop: Population) -> HallOfFame:
    """Empty archive shaped like (maxsize copies of) one individual."""
    take0 = lambda a: jnp.zeros((maxsize,) + a.shape[1:], a.dtype)
    return HallOfFame(
        genomes=jax.tree_util.tree_map(take0, pop.genomes),
        fitness=jnp.zeros((maxsize, pop.nobj), pop.fitness.dtype),
        filled=jnp.zeros(maxsize, bool),
        spec=pop.spec,
    )


def _genome_hash(genomes) -> jnp.ndarray:
    """Cheap order-independent-free int32 hash per row (wrapping int
    arithmetic). Equal genomes always hash equal; used only as a sort
    tie-key so exact duplicates land adjacent — correctness never depends
    on collision-freedom."""
    from jax import lax

    leaves = jax.tree_util.tree_leaves(genomes)
    n = leaves[0].shape[0]
    h = jnp.zeros(n, jnp.int32)
    for leaf in leaves:
        flat = leaf.reshape(n, -1)
        if jnp.issubdtype(flat.dtype, jnp.floating):
            ints = lax.bitcast_convert_type(flat.astype(jnp.float32), jnp.int32)
        else:
            ints = flat.astype(jnp.int32)
        mult = (jnp.arange(flat.shape[1], dtype=jnp.int32) * jnp.int32(-1640531527)
                + jnp.int32(97))
        h = h * jnp.int32(31) + jnp.sum(ints * mult, axis=-1, dtype=jnp.int32)
    return h


def _adjacent_dup(sorted_w, sorted_h, sorted_genomes, sorted_valid):
    """dup[i]: row i is an exact-genome duplicate of row i-1. Because the
    pool is sorted by (wvalues, hash), all copies of a genome are
    contiguous (duplicates share fitness under deterministic evaluation),
    so adjacent comparison removes every copy but the first."""
    same = jnp.all(sorted_w[1:] == sorted_w[:-1], axis=-1)
    same &= sorted_h[1:] == sorted_h[:-1]
    for leaf in jax.tree_util.tree_leaves(sorted_genomes):
        flat = leaf.reshape(leaf.shape[0], -1)
        same &= jnp.all(flat[1:] == flat[:-1], axis=-1)
    same &= sorted_valid[1:] & sorted_valid[:-1]
    return jnp.concatenate([jnp.zeros(1, bool), same])


def duplicate_mask(genomes, w: jnp.ndarray, valid: jnp.ndarray) -> jnp.ndarray:
    """bool[n] in ORIGINAL order: row is an exact-genome duplicate of
    another (earlier in (w, hash) sort order) valid row. O(n log n) —
    the scalable dedup shared by hof_update and pareto_update."""
    h = _genome_hash(genomes)
    keys = (h,) + tuple(-w[:, j] for j in range(w.shape[1] - 1, -1, -1))
    order = jnp.lexsort(keys)
    sw = jnp.take(w, order, axis=0)
    sh = jnp.take(h, order)
    sv = jnp.take(valid, order)
    sg = jax.tree_util.tree_map(lambda a: jnp.take(a, order, axis=0), genomes)
    dup_sorted = _adjacent_dup(sw, sh, sg, sv)
    return jnp.zeros_like(valid).at[order].set(dup_sorted)


def hof_update(hof: HallOfFame, pop: Population, dedup: bool = True) -> HallOfFame:
    """Merge a population into the archive (support.py:517-543).

    Pool = archive ∪ full population, lex-sorted best-first with a genome
    hash as the final tie-key, adjacent-deduplicated on exact genome
    equality, truncated to ``maxsize``. O((n+k) log(n+k)) — no pairwise
    matrix, so it scales to 100k populations inside the scanned step.
    """
    k = hof.maxsize
    cat = lambda a, b: jnp.concatenate([a, b], axis=0)
    all_g = jax.tree_util.tree_map(cat, hof.genomes, pop.genomes)
    all_f = cat(hof.fitness, pop.fitness)
    all_valid = cat(hof.filled, pop.valid)

    w = all_f * hof.spec.warray
    w = jnp.where(all_valid[:, None], w, -jnp.inf)
    h = _genome_hash(all_g)
    # lexsort: last key is primary → (hash, w[nobj-1], ..., w[0]) negated
    keys = (h,) + tuple(-w[:, j] for j in range(w.shape[1] - 1, -1, -1))
    order = jnp.lexsort(keys)
    take = lambda a: jnp.take(a, order, axis=0)
    all_g = jax.tree_util.tree_map(take, all_g)
    all_f = take(all_f)
    all_valid = take(all_valid)
    w = take(w)
    h = take(h)

    keep = all_valid
    if dedup:
        keep = keep & ~_adjacent_dup(w, h, all_g, all_valid)

    perm = jnp.argsort(~keep, stable=True)[:k]
    return HallOfFame(
        genomes=jax.tree_util.tree_map(lambda a: jnp.take(a, perm, axis=0), all_g),
        fitness=jnp.take(all_f, perm, axis=0),
        filled=jnp.take(keep, perm),
        spec=hof.spec,
    )


def hof_best(hof: HallOfFame):
    """Best genome + fitness (the reference's ``hof[0]``)."""
    g = jax.tree_util.tree_map(lambda a: a[0], hof.genomes)
    return g, hof.fitness[0]
