"""Persistent XLA compilation cache wiring — cold-start economics.

First slice of ROADMAP item 5: a serving system dies on compile
latency, and every new (pop, genome-shape, opcode-mask, chunk-count)
tuple pays a fresh XLA compile. JAX ships a persistent compilation
cache (compiled executables keyed by computation fingerprint, written
to a directory) that turns the second process's cold start into a disk
read; this module is the one place that knows how to switch it on for
the pinned jax version, so the bench path (``bench.py`` honours
``DEAP_TPU_COMPILE_CACHE``; ``bench.py --coldstart`` measures the
cold-vs-warm ``time_to_first_generation`` delta) and any serving front
end share one opt-in.

Opt-in only: the cache trades disk for latency and changes no computed
result, but a shared default directory could cross-contaminate
benchmark environments — so nothing is enabled unless the caller (or
the environment variable) asks.
"""

from __future__ import annotations

import os
from typing import Optional

#: the environment opt-in bench.py and the examples honour
ENV_VAR = "DEAP_TPU_COMPILE_CACHE"


def enable(path: str, min_compile_time_secs: float = 0.0) -> str:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) and lower the persistence thresholds so even the small
    per-shape executables of the bench/serving lattices are cached.
    Config names that the pinned jax doesn't know are skipped — the
    cache then simply persists less, it never breaks."""
    import jax

    path = os.path.abspath(os.path.expanduser(str(path)))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for name, value in (
        ("jax_persistent_cache_min_compile_time_secs",
         float(min_compile_time_secs)),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        # 0.4.37 gates non-TPU executable caching behind this knob
        ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(name, value)
        except Exception:
            pass
    return path


def enable_from_env(var: str = ENV_VAR) -> Optional[str]:
    """Enable the cache iff ``$DEAP_TPU_COMPILE_CACHE`` names a
    directory; returns the resolved path (or ``None``). The bench
    entrypoints call this right after importing jax."""
    path = os.environ.get(var)
    if not path:
        return None
    return enable(path)
