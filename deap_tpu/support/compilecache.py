"""Persistent XLA compilation cache wiring — cold-start economics.

First slice of ROADMAP item 5: a serving system dies on compile
latency, and every new (pop, genome-shape, opcode-mask, chunk-count)
tuple pays a fresh XLA compile. JAX ships a persistent compilation
cache (compiled executables keyed by computation fingerprint, written
to a directory) that turns the second process's cold start into a disk
read; this module is the one place that knows how to switch it on for
the pinned jax version, so the bench path (``bench.py`` honours
``DEAP_TPU_COMPILE_CACHE``; ``bench.py --coldstart`` measures the
cold-vs-warm ``time_to_first_generation`` delta) and any serving front
end share one opt-in.

Opt-in only: the cache trades disk for latency and changes no computed
result, but a shared default directory could cross-contaminate
benchmark environments — so nothing is enabled unless the caller (or
the environment variable) asks.
"""

from __future__ import annotations

import os
from typing import Optional

#: the environment opt-in bench.py and the examples honour
ENV_VAR = "DEAP_TPU_COMPILE_CACHE"

#: the directory the programmatic opt-in resolved to (None = not
#: enabled through this module)
_enabled_path: Optional[str] = None


def enabled_path() -> Optional[str]:
    """Where the cache currently points (via this module), or None."""
    return _enabled_path


def enable(path: str, min_compile_time_secs: float = 0.0) -> str:
    """Point JAX's persistent compilation cache at ``path`` (created if
    missing) and lower the persistence thresholds so even the small
    per-shape executables of the bench/serving lattices are cached.
    Config names that the pinned jax doesn't know are skipped — the
    cache then simply persists less, it never breaks."""
    import jax

    path = os.path.abspath(os.path.expanduser(str(path)))
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    for name, value in (
        ("jax_persistent_cache_min_compile_time_secs",
         float(min_compile_time_secs)),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
        # 0.4.37 gates non-TPU executable caching behind this knob
        ("jax_persistent_cache_enable_xla_caches", "all"),
    ):
        try:
            jax.config.update(name, value)
        except Exception:
            pass
    global _enabled_path
    _enabled_path = path
    return path


def enable_compile_cache(path: Optional[str] = None,
                         min_compile_time_secs: float = 0.0) -> str:
    """The programmatic opt-in (closes ROADMAP item 5's API half):
    point the persistent XLA compile cache at ``path`` — default
    ``$DEAP_TPU_COMPILE_CACHE``, else ``~/.cache/deap_tpu/xla`` — and
    journal a ``compile_cache`` event into any open run journal so a
    serving run's cold-start economics are attributable. Idempotent:
    re-enabling the same directory is a no-op. Returns the resolved
    path.

    The serving scheduler calls this (``Scheduler(compile_cache=...)``)
    before its first compile; paired with
    :func:`deap_tpu.serving.prewarm`, the shape-bucket lattice then
    compiles once per *fleet*, not once per process."""
    if path is None:
        path = os.environ.get(ENV_VAR) or os.path.join(
            os.path.expanduser("~"), ".cache", "deap_tpu", "xla")
    resolved = os.path.abspath(os.path.expanduser(str(path)))
    if _enabled_path == resolved:
        return resolved
    resolved = enable(resolved,
                      min_compile_time_secs=min_compile_time_secs)
    try:
        from deap_tpu.telemetry.journal import broadcast
        broadcast("compile_cache", path=resolved)
    except Exception:
        pass
    return resolved


def sibling_cache_dir() -> Optional[str]:
    """Directory for sibling caches that should live — and be wiped —
    together with the compiled executables. The dispatch tuning cache
    (:mod:`deap_tpu.tuning`) stores its probe winners here when the
    compile cache is enabled, and the serialized-executable artifact
    store (:mod:`deap_tpu.support.artifacts`) defaults its directory
    under here too: the three artifacts that make a process warm-start
    (compiled programs, loadable executables, and the measured
    dispatch choices that select between them) stay one directory.
    None when the compile cache is off (the siblings then fall back to
    their own env vars or ``~/.cache/deap_tpu``)."""
    return _enabled_path


def enable_artifact_cache(path: Optional[str] = None):
    """Enable the serialized-executable artifact store — the sibling
    cache that persists **loaded executables** (via
    ``jax.experimental.serialize_executable``) so a restarted process
    deserializes instead of compiling. Defaults to living inside the
    enabled compile cache (see :func:`sibling_cache_dir`); thin
    delegation so callers that already import this module need no
    second import. Returns the active
    :class:`~deap_tpu.support.artifacts.ExecutableArtifactStore`."""
    from deap_tpu.support.artifacts import enable_artifact_store
    return enable_artifact_store(path)


def enable_from_env(var: str = ENV_VAR) -> Optional[str]:
    """Enable the cache iff ``$DEAP_TPU_COMPILE_CACHE`` names a
    directory; returns the resolved path (or ``None``). The bench
    entrypoints call this right after importing jax."""
    path = os.environ.get(var)
    if not path:
        return None
    return enable(path)
