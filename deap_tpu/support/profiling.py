"""Profiling / tracing hooks.

The reference has no profiler; its closest artifacts are the per-
generation ``nevals`` column (deap/algorithms.py:158,185) and the
historical ``examples/speed.txt`` timing harness. The TPU-native
equivalent (SURVEY.md §5.1) is the JAX profiler: xplane traces viewable
in TensorBoard/XProf, plus named-scope annotation of the evolutionary
phases so selection / variation / evaluation show up as labelled spans
on the device timeline.

Usage::

    from deap_tpu.support.profiling import trace, annotate, timed_generations

    with trace("/tmp/ea-trace"):          # whole-run xplane capture
        pop, logbook, hof = algorithms.ea_simple(...)

    @annotate("variation")                # label a phase inside jit
    def my_mate(key, g1, g2): ...

    for gen, state, dt in timed_generations(run_one_gen, pop, ngen=100):
        ...                               # host-side per-gen wall times

All three are thin, dependency-free wrappers: profiling must never
change the compiled program (annotations are metadata-only).
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Callable, Iterator, Tuple

import jax

__all__ = ["trace", "annotate", "span", "timed_generations",
           "timed_phases", "sync"]


def trace(log_dir: str, **kwargs):
    """Capture an xplane trace of everything run inside the context
    (``jax.profiler.trace``); open ``log_dir`` with TensorBoard's
    profile plugin / XProf. The TPU-native replacement for the
    reference's external timing harness."""
    return jax.profiler.trace(log_dir, **kwargs)


@contextlib.contextmanager
def span(name: str):
    """Inline named span — the context-manager form of :func:`annotate`
    for code that is not a whole function (a single collective inside a
    ``shard_map`` body, one phase of a fused step). Device ops traced
    inside the block carry ``name`` as a scope in xplane captures, so
    per-collective time is attributable in XProf; metadata-only, never
    changes the compiled program."""
    with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
        yield


def annotate(name: str) -> Callable:
    """Decorator: wrap a function in a named trace span
    (``jax.profiler.TraceAnnotation`` on host, ``jax.named_scope`` for
    device code) so it appears as a labelled region in profiles."""
    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
                return fn(*args, **kwargs)
        return wrapped
    return deco


def sync(tree: Any) -> Any:
    """Block until ``tree``'s arrays have materialised. On remote-
    attached TPU runtimes ``jax.block_until_ready`` can return before
    device execution finishes, so this additionally fetches one scalar
    from the first array — cheap, and an actual completion barrier."""
    jax.block_until_ready(tree)
    leaves = jax.tree_util.tree_leaves(tree)
    if leaves:
        jax.device_get(jax.numpy.ravel(leaves[0])[:1])
    return tree


def timed_phases(phases: dict, reps: int = 3) -> dict:
    """Host-side attribution harness: ``phases`` maps a label to a
    zero-arg thunk returning device arrays; each is run ``reps`` times
    under :func:`sync` and the minimum wall seconds per label returned.

    The differencing companion to the per-collective spans: build one
    thunk per pipeline variant (full sharded step, collective swapped
    for identity, partial_eval alone) and the pairwise deltas attribute
    wall time to a specific collective even when no xplane trace can be
    captured (e.g. the TPU relay is down and CPU host timing is all
    there is)."""
    out = {}
    for name, thunk in phases.items():
        sync(thunk())  # compile outside the timed reps
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            sync(thunk())
            best = min(best, time.perf_counter() - t0)
        out[name] = best
    return out


def timed_generations(step: Callable, state: Any, ngen: int,
                      *step_args: Any) -> Iterator[Tuple[int, Any, float]]:
    """Host-driven generation loop with honest per-generation wall
    times: yields ``(gen, state, seconds)``. For profiling only — the
    production path is one ``lax.scan`` with no host round trips; this
    trades that fusion for visibility (the analog of reading the
    reference's per-generation logbook timings)."""
    for gen in range(ngen):
        t0 = time.perf_counter()
        state = sync(step(state, *step_args))
        yield gen, state, time.perf_counter() - t0
