"""Profiling / tracing hooks.

The reference has no profiler; its closest artifacts are the per-
generation ``nevals`` column (deap/algorithms.py:158,185) and the
historical ``examples/speed.txt`` timing harness. The TPU-native
equivalent (SURVEY.md §5.1) is the JAX profiler: xplane traces viewable
in TensorBoard/XProf, plus named-scope annotation of the evolutionary
phases so selection / variation / evaluation show up as labelled spans
on the device timeline.

Usage::

    from deap_tpu.support.profiling import trace, annotate, timed_generations

    with trace("/tmp/ea-trace"):          # whole-run xplane capture
        pop, logbook, hof = algorithms.ea_simple(...)

    @annotate("variation")                # label a phase inside jit
    def my_mate(key, g1, g2): ...

    for gen, state, dt in timed_generations(run_one_gen, pop, ngen=100):
        ...                               # host-side per-gen wall times

All three are thin, dependency-free wrappers: profiling must never
change the compiled program (annotations are metadata-only).
"""

from __future__ import annotations

import contextlib
import functools
import random
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

import jax

__all__ = ["trace", "annotate", "span", "timed_generations",
           "timed_phases", "sync", "SpanRecorder", "set_span_recorder",
           "get_span_recorder", "device_memory_snapshot",
           "live_buffer_bytes"]


def trace(log_dir: str, **kwargs):
    """Capture an xplane trace of everything run inside the context
    (``jax.profiler.trace``); open ``log_dir`` with TensorBoard's
    profile plugin / XProf. The TPU-native replacement for the
    reference's external timing harness."""
    return jax.profiler.trace(log_dir, **kwargs)


class SpanRecorder:
    """Host-side wall-time aggregation of :func:`span` blocks.

    While installed (``with SpanRecorder() as rec:`` or
    :func:`set_span_recorder`), every ``span(name)`` entry/exit is also
    timed with ``time.perf_counter`` and accumulated per name —
    count / total / mean / p50 / p99 / max. This is the
    trace-independent fallback for the per-collective
    ``genome_shard/*`` spans: when no xplane capture is possible (relay
    down, headless CI), the recorder still yields numbers. Spans inside
    jit-compiled code fire once per trace, so for compiled collectives
    the recorded time is *trace* time — use :func:`timed_phases` to
    attribute execution time; spans on host paths record true wall
    time per call.

    Aggregates feed the run journal
    (``deap_tpu.telemetry.RunJournal.spans``). A bounded **uniform
    reservoir** (Vitter's algorithm R, ``max_samples`` per name) backs
    the percentiles: past the bound each new sample replaces a random
    held one with probability ``max_samples / count``, so the reservoir
    stays a uniform sample of the whole run — p50/p99/max keep moving
    on long runs instead of freezing on the first 4096 spans.
    count/total/mean are exact regardless (never sampled). The
    replacement RNG is seeded per recorder (``seed``), so identical
    span streams aggregate identically.

    Aggregation is thread-safe: the serving front end's request
    threads and the autoscaler's background prewarm thread run
    ``span(...)`` blocks concurrently with the driver, so
    :meth:`record`'s read-modify-write of the count/total/max dicts
    and the reservoir (whose algorithm-R branch is an index-then-
    assign pair) runs under one lock; :meth:`aggregates` takes the
    same lock so a mid-update snapshot can never pair a new count
    with an old total.
    """

    def __init__(self, max_samples: int = 4096, seed: int = 0):
        self.max_samples = int(max_samples)
        self._samples: Dict[str, list] = {}
        self._count: Dict[str, int] = {}
        self._total: Dict[str, float] = {}
        self._max: Dict[str, float] = {}
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self._prev: Optional["SpanRecorder"] = None

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            n = self._count.get(name, 0) + 1
            self._count[name] = n
            self._total[name] = self._total.get(name, 0.0) + seconds
            self._max[name] = max(self._max.get(name, seconds), seconds)
            bucket = self._samples.setdefault(name, [])
            if len(bucket) < self.max_samples:
                bucket.append(seconds)
            else:
                # algorithm R: keep each of the n samples seen so far
                # with equal probability max_samples / n
                j = self._rng.randrange(n)
                if j < self.max_samples:
                    bucket[j] = seconds

    def aggregates(self) -> Dict[str, Dict[str, float]]:
        """``{name: {count, total_s, mean_s, p50_s, p99_s, max_s}}``."""
        out: Dict[str, Dict[str, float]] = {}
        with self._lock:
            for name, n in self._count.items():
                total = self._total[name]
                samples = sorted(self._samples.get(name, ()))
                agg = {"count": n, "total_s": total, "mean_s": total / n}
                if samples:
                    m = len(samples)
                    agg["p50_s"] = samples[(m - 1) // 2]
                    agg["p99_s"] = samples[min(m - 1, int(0.99 * (m - 1)))]
                    # max is tracked exactly — the reservoir may have
                    # evicted the worst sample
                    agg["max_s"] = self._max[name]
                out[name] = agg
        return out

    def __enter__(self) -> "SpanRecorder":
        self._prev = set_span_recorder(self)
        return self

    def __exit__(self, *exc) -> None:
        set_span_recorder(self._prev)
        self._prev = None


# The active recorder — one slot, module-global: span() is called from
# inside shard_map bodies during tracing, where thread-locals tied to
# the caller would be invisible.
_RECORDER: list = [None]

# Lazily-resolved telemetry.tracing module — cached to keep span()'s
# hot path one list-index when the bridge is active, and to avoid an
# import cycle at module load (tracing is stdlib-only but lives in the
# telemetry package).
_TRACING: list = [None]


def _tracing_mod():
    if _TRACING[0] is None:
        from deap_tpu.telemetry import tracing as _tr
        _TRACING[0] = _tr
    return _TRACING[0]


def set_span_recorder(rec: Optional[SpanRecorder]) -> Optional[SpanRecorder]:
    """Install ``rec`` as the active span recorder (None disables);
    returns the previous one so callers can restore it."""
    prev = _RECORDER[0]
    _RECORDER[0] = rec
    return prev


def get_span_recorder() -> Optional[SpanRecorder]:
    return _RECORDER[0]


@contextlib.contextmanager
def span(name: str):
    """Inline named span — the context-manager form of :func:`annotate`
    for code that is not a whole function (a single collective inside a
    ``shard_map`` body, one phase of a fused step). Device ops traced
    inside the block carry ``name`` as a scope in xplane captures, so
    per-collective time is attributable in XProf; metadata-only, never
    changes the compiled program. When a :class:`SpanRecorder` is
    installed the block is additionally wall-timed on the host."""
    rec = _RECORDER[0]
    if rec is None:
        with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
            yield
        return
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
            yield
    finally:
        dt = time.perf_counter() - t0
        rec.record(name, dt)
        # bridge into the distributed-tracing plane: when the caller
        # is inside a request's trace context, the recorded span also
        # lands in the waterfall (sampled — these are detail spans)
        tr = _tracing_mod()
        if tr.current() is not None:
            tr.emit_current(f"span:{name}", dt)


def annotate(name: str) -> Callable:
    """Decorator: wrap a function in a named trace span
    (``jax.profiler.TraceAnnotation`` on host, ``jax.named_scope`` for
    device code) so it appears as a labelled region in profiles."""
    def deco(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            with jax.profiler.TraceAnnotation(name), jax.named_scope(name):
                return fn(*args, **kwargs)
        return wrapped
    return deco


def sync(tree: Any) -> Any:
    """Block until ``tree``'s arrays have materialised. On remote-
    attached TPU runtimes ``jax.block_until_ready`` can return before
    device execution finishes, so this additionally fetches one scalar
    from the first fetchable array — cheap, and an actual completion
    barrier.

    Robust to awkward trees: non-array leaves (python scalars, None
    from an optional carry) and zero-size arrays are skipped rather
    than raveled; committed / sharded arrays fetch a single element of
    their first addressable shard so the barrier never forces a
    cross-device gather of the whole array.
    """
    jax.block_until_ready(tree)
    for leaf in jax.tree_util.tree_leaves(tree):
        if not isinstance(leaf, jax.Array) or leaf.size == 0:
            continue
        try:
            shard = leaf.addressable_shards[0].data
            jax.device_get(jax.numpy.ravel(shard)[:1])
        except (AttributeError, IndexError, TypeError):
            # no addressable shards on this runtime (or an exotic array
            # type): fall back to raveling the array itself
            jax.device_get(jax.numpy.ravel(leaf)[:1])
        break
    return tree


def live_buffer_bytes() -> Dict[str, int]:
    """Bytes of live device arrays by platform (``jax.live_arrays``) —
    the cheap HBM-trajectory sample the flight recorder journals at
    segment boundaries. Counts each array's global ``nbytes`` once;
    deleted (donated-consumed) arrays are skipped."""
    out: Dict[str, int] = {}
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue
            platform = arr.devices().pop().platform
            out[platform] = out.get(platform, 0) + int(arr.nbytes)
        except Exception:
            continue
    return out


def device_memory_snapshot(path: Optional[str] = None) -> Dict[str, Any]:
    """One device-memory observation: live-array bytes per platform
    (always), plus — when ``path`` is given — the full
    ``jax.profiler.device_memory_profile()`` pprof protobuf written to
    that file for offline ``pprof``/XProf analysis. Returns a
    JSON-able dict (the flight recorder journals it verbatim as a
    ``device_memory`` event)."""
    snap: Dict[str, Any] = {"live_bytes": live_buffer_bytes()}
    if path is not None:
        try:
            blob = jax.profiler.device_memory_profile()
            with open(path, "wb") as fh:
                fh.write(blob)
            snap["profile_path"] = str(path)
            snap["profile_bytes"] = len(blob)
        except Exception as e:  # profile support varies per backend
            snap["profile_error"] = repr(e)[:200]
    return snap


def timed_phases(phases: dict, reps: int = 3) -> dict:
    """Host-side attribution harness: ``phases`` maps a label to a
    zero-arg thunk returning device arrays; each is run ``reps`` times
    under :func:`sync` and the minimum wall seconds per label returned.

    The differencing companion to the per-collective spans: build one
    thunk per pipeline variant (full sharded step, collective swapped
    for identity, partial_eval alone) and the pairwise deltas attribute
    wall time to a specific collective even when no xplane trace can be
    captured (e.g. the TPU relay is down and CPU host timing is all
    there is)."""
    out = {}
    for name, thunk in phases.items():
        sync(thunk())  # compile outside the timed reps
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            sync(thunk())
            best = min(best, time.perf_counter() - t0)
        out[name] = best
    return out


def timed_generations(step: Callable, state: Any, ngen: int,
                      *step_args: Any) -> Iterator[Tuple[int, Any, float]]:
    """Host-driven generation loop with honest per-generation wall
    times: yields ``(gen, state, seconds)``. For profiling only — the
    production path is one ``lax.scan`` with no host round trips; this
    trades that fusion for visibility (the analog of reading the
    reference's per-generation logbook timings)."""
    for gen in range(ngen):
        t0 = time.perf_counter()
        state = sync(step(state, *step_args))
        yield gen, state, time.perf_counter() - t0
