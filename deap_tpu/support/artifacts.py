"""AOT executable artifact store — the compile side of cold start.

The persistent XLA compile cache (:mod:`~deap_tpu.support.compilecache`)
makes the second process's *compiles* a disk read, but the restarted
driver still pays the whole compilation pipeline in front of the cache
lookup. ``jax.experimental.serialize_executable`` can persist the
**loaded executable itself** — deserializing one measured ~20× faster
than a cache-warm compile on the committed CPU config — which is what
turns a kill-9 restart's first generation from a compile wall into a
file read (ISSUE 18, ROADMAP item 4).

This module is the sibling cache of the compile cache (the PR 16
``sibling_cache_dir()`` pattern): one directory holding

- ``artifact_manifest.json`` — a **stdlib-only** JSON manifest mapping
  artifact keys to blob files, with a CRC32 and the environment stamps
  (jax version, backend, device kind) that gate reuse. Atomic
  read-merge-write like the tuning cache, so concurrent processes
  merge instead of clobbering.
- ``<key>.exec`` blob files — a pickled plain dict holding the
  serialized executable bytes plus the pickled in/out treedefs (kept
  as raw bytes, so the container itself loads without jax).

Keying: ``(backend, device kind, jax version, HLO hash)``. The HLO
hash is the observatory's existing program fingerprint (sha1 of the
lowered StableHLO text, :func:`deap_tpu.telemetry.costs.
_hlo_fingerprint`) — two processes asking XLA for the same program
agree on the key; any shape/closure/version change misses and falls
through to a fresh compile. Every consult is journaled
(``artifact_hit`` / ``artifact_miss``) so a restart's cold-start
economics are attributable from the journal alone.

Fallback contract: any failure — torn blob, CRC mismatch, stamp
mismatch, deserialize error — returns ``None`` and the caller compiles
exactly what it would have compiled with no store active. Results are
bit-identical either way (the deserialized executable IS the compiled
one; pinned by ``tests/test_artifacts.py``).
"""

from __future__ import annotations

import json
import os
import pickle
import tempfile
import threading
import time
import zlib
from typing import Any, Dict, Optional

__all__ = ["ExecutableArtifactStore", "active_store",
           "enable_artifact_store", "disable_artifact_store",
           "default_dir", "ARTIFACT_JOURNAL_KINDS"]

#: the environment opt-in (mirrors DEAP_TPU_COMPILE_CACHE)
ENV_VAR = "DEAP_TPU_ARTIFACT_CACHE"

#: manifest file-format stamp; bump on layout changes (readers skip
#: unknown formats rather than guessing)
MANIFEST_FORMAT = 1

#: journal kinds this module writes (documented in the
#: docs/advanced/telemetry.md kind table; drift-gated by
#: tests/test_artifacts.py)
ARTIFACT_JOURNAL_KINDS = ("artifact_hit", "artifact_miss")

MANIFEST_NAME = "artifact_manifest.json"

#: the active store — one slot, module-global (the instrumented seams
#: that consult it are constructed far from whoever enabled it)
_ACTIVE: list = [None]


def active_store() -> Optional["ExecutableArtifactStore"]:
    """The currently active artifact store, or None."""
    return _ACTIVE[0]


def default_dir() -> str:
    """Where the store lives when the caller names no path:
    ``$DEAP_TPU_ARTIFACT_CACHE``, else an ``artifacts/`` directory
    INSIDE the enabled compile cache (sibling artifacts live — and are
    wiped — together), else ``~/.cache/deap_tpu/artifacts``."""
    env = os.environ.get(ENV_VAR)
    if env:
        return env
    from deap_tpu.support.compilecache import sibling_cache_dir
    sib = sibling_cache_dir()
    if sib is not None:
        return os.path.join(sib, "artifacts")
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "deap_tpu", "artifacts")


def _broadcast(kind: str, **payload: Any) -> None:
    try:
        from deap_tpu.telemetry.journal import broadcast
        broadcast(kind, **payload)
    except Exception:
        pass


def _env_stamp() -> Dict[str, str]:
    """The reuse gate: a serialized executable is device- and
    version-specific, so entries written under any other (backend,
    device kind, jax version) triple are skipped, never loaded."""
    import jax

    try:
        backend = jax.default_backend()
        device_kind = jax.devices()[0].device_kind
    except Exception:
        backend, device_kind = "unknown", "unknown"
    return {"jax": jax.__version__, "backend": str(backend),
            "device_kind": str(device_kind)}


class ExecutableArtifactStore:
    """One artifact directory: manifest + serialized-executable blobs.

    Thread-safe (one lock around manifest state — engine prewarms run
    off the driver thread); safe across processes (atomic
    read-merge-write puts). All jax imports are lazy: constructing a
    store, or reading its manifest, never initialises a backend.
    """

    def __init__(self, directory: str):
        self.directory = os.path.abspath(os.path.expanduser(
            str(directory)))
        os.makedirs(self.directory, exist_ok=True)
        self.manifest_path = os.path.join(self.directory, MANIFEST_NAME)
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict[str, Any]] = self._read_manifest()
        self._stamp: Optional[Dict[str, str]] = None
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------- manifest ----

    def _read_manifest(self) -> Dict[str, Dict[str, Any]]:
        """Tolerant read: a missing, torn, or foreign-format manifest
        is an empty store, never an exception."""
        try:
            with open(self.manifest_path, "r") as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return {}
        if not isinstance(doc, dict) \
                or doc.get("format") != MANIFEST_FORMAT:
            return {}
        entries = doc.get("entries")
        return dict(entries) if isinstance(entries, dict) else {}

    def _write_manifest(self, entries: Dict[str, Dict[str, Any]]) -> None:
        doc = {"format": MANIFEST_FORMAT, "entries": entries}
        fd, tmp = tempfile.mkstemp(dir=self.directory,
                                   suffix=".manifest.tmp")
        try:
            with os.fdopen(fd, "w") as fh:
                json.dump(doc, fh, sort_keys=True, indent=1)
            os.replace(tmp, self.manifest_path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _merge_put(self, key: str, entry: Dict[str, Any]) -> None:
        """Read-merge-write: re-read the file under the lock, fold the
        new entry in, replace atomically — two processes writing
        different keys both survive (same-key last-writer-wins is fine:
        the blobs are content-identical by construction)."""
        with self._lock:
            on_disk = self._read_manifest()
            on_disk.update(self._entries)
            on_disk[key] = entry
            self._entries = on_disk
            self._write_manifest(on_disk)

    # ----------------------------------------------------------- keys ----

    def stamp(self) -> Dict[str, str]:
        with self._lock:
            if self._stamp is None:
                self._stamp = _env_stamp()
            return dict(self._stamp)

    def key_for(self, hlo_hash: str) -> str:
        s = self.stamp()
        kind = "".join(c if c.isalnum() else "-"
                       for c in s["device_kind"])[:32]
        return (f"{s['backend']}-{kind}-{s['jax']}-{hlo_hash}"
                .replace("/", "-"))

    def _blob_path(self, key: str) -> str:
        return os.path.join(self.directory, key + ".exec")

    # ------------------------------------------------------- get / put ----

    def get(self, label: str, hlo_hash: str) -> Optional[Any]:
        """The loaded executable for ``hlo_hash`` under the current
        environment stamp, or ``None`` (journaled ``artifact_miss``
        with the reason) — the caller then compiles, bit-identically."""
        t0 = time.perf_counter()
        key = self.key_for(hlo_hash)
        with self._lock:
            entry = self._entries.get(key)
        if entry is None:
            # another process may have written since we loaded the
            # manifest (the serving restart races its own first child)
            with self._lock:
                fresh = self._read_manifest()
                fresh.update({k: v for k, v in self._entries.items()
                              if k not in fresh})
                self._entries = fresh
                entry = self._entries.get(key)
        reason = None
        if entry is None:
            reason = "absent"
        else:
            stamp = self.stamp()
            for field in ("jax", "backend", "device_kind"):
                if entry.get(field) != stamp[field]:
                    reason = "stamp_mismatch"
                    break
        compiled = None
        if reason is None:
            compiled, reason = self._load(entry)
        if compiled is None:
            self.misses += 1
            _broadcast("artifact_miss", label=str(label),
                       hlo_hash=str(hlo_hash), reason=reason)
            return None
        self.hits += 1
        _broadcast("artifact_hit", label=str(label),
                   hlo_hash=str(hlo_hash),
                   deserialize_s=round(time.perf_counter() - t0, 6),
                   bytes=int(entry.get("bytes", 0)))
        return compiled

    def _load(self, entry: Dict[str, Any]):
        """(compiled, None) or (None, reason)."""
        path = os.path.join(self.directory,
                            os.path.basename(str(entry.get("file", ""))))
        try:
            with open(path, "rb") as fh:
                raw = fh.read()
        except OSError:
            return None, "read_error"
        if zlib.crc32(raw) != entry.get("crc"):
            return None, "crc_mismatch"
        try:
            doc = pickle.loads(raw)
            from jax.experimental import serialize_executable as se
            in_tree, out_tree = pickle.loads(doc["trees"])
            return se.deserialize_and_load(doc["blob"], in_tree,
                                           out_tree), None
        except Exception:
            return None, "deserialize_error"

    def put(self, label: str, hlo_hash: str, compiled: Any) -> bool:
        """Persist one freshly compiled executable. Best-effort: a
        program the pinned jax cannot serialize (or a full disk) is
        skipped silently — the store only ever removes future compiles,
        never adds failure modes to the run that populated it."""
        try:
            from jax.experimental import serialize_executable as se
            blob, in_tree, out_tree = se.serialize(compiled)
            payload = pickle.dumps(
                {"format": MANIFEST_FORMAT, "label": str(label),
                 "hlo_hash": str(hlo_hash), "blob": blob,
                 "trees": pickle.dumps((in_tree, out_tree))},
                protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:
            return False
        key = self.key_for(hlo_hash)
        path = self._blob_path(key)
        try:
            fd, tmp = tempfile.mkstemp(dir=self.directory,
                                       suffix=".exec.tmp")
            with os.fdopen(fd, "wb") as fh:
                fh.write(payload)
            os.replace(tmp, path)
        except OSError:
            return False
        entry = dict(self.stamp())
        entry.update(file=os.path.basename(path),
                     crc=zlib.crc32(payload), bytes=len(payload),
                     hlo_hash=str(hlo_hash), label=str(label))
        self._merge_put(key, entry)
        return True

    # ------------------------------------------------------ lifecycle ----

    def activate(self) -> "ExecutableArtifactStore":
        """Install as the process-wide active store (the instrumented
        AOT seams consult the active slot at call time)."""
        self._prev = _ACTIVE[0]
        _ACTIVE[0] = self
        return self

    def deactivate(self) -> None:
        if _ACTIVE[0] is self:
            _ACTIVE[0] = getattr(self, "_prev", None)
        self._prev = None


def enable_artifact_store(path: Optional[str] = None
                          ) -> ExecutableArtifactStore:
    """Create (or reuse) the store at ``path`` (default:
    :func:`default_dir`) and activate it. Idempotent: re-enabling the
    already-active directory returns the live store."""
    resolved = os.path.abspath(os.path.expanduser(
        str(path or default_dir())))
    cur = _ACTIVE[0]
    if cur is not None and cur.directory == resolved:
        return cur
    return ExecutableArtifactStore(resolved).activate()


def disable_artifact_store() -> None:
    """Deactivate the current store (tests, scheduler teardown)."""
    cur = _ACTIVE[0]
    if cur is not None:
        cur.deactivate()
