"""Statistics / MultiStatistics — on-device per-generation reductions.

Counterpart of /root/reference/deap/tools/support.py:154-259. The
reference's ``Statistics(key)`` extracts a value per individual and
applies registered numpy reducers; here ``key`` extracts a batched array
from the whole :class:`Population` (default: the raw fitness tensor) and
reducers are jnp functions, so ``compile`` can run *inside* a jit'd /
scanned generation step — the per-generation stats come back as stacked
arrays, one slice per generation, and feed the host-side
:class:`Logbook`.

Like the reference, statistics are meant to be compiled *after*
evaluation (algorithms do so): invalid rows are NOT masked, so compiling
mid-variation would include stale fitness values. Pass a custom ``key``
that filters by ``pop.valid`` if you need mid-variation stats.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp


def _default_key(pop):
    return pop.fitness


class Statistics:
    """``Statistics(key)`` + ``register(name, fn)`` → ``compile(pop)``.

    Reducers are applied over the population axis (axis 0), mirroring the
    reference's numpy-over-list behaviour (support.py:199-210).
    """

    def __init__(self, key: Callable = _default_key):
        self.key = key
        self.functions: Dict[str, Callable] = {}
        self.fields = []

    def register(self, name: str, function: Callable, *args, **kwargs) -> None:
        self.functions[name] = lambda x: function(x, *args, **kwargs)
        self.fields.append(name)

    def compile(self, pop) -> Dict[str, jnp.ndarray]:
        data = self.key(pop)
        return {name: fn(data) for name, fn in self.functions.items()}


class MultiStatistics(dict):
    """Named chapters of Statistics (support.py:212-259)."""

    def __init__(self, **chapters: Statistics):
        super().__init__(chapters)

    @property
    def fields(self):
        return sorted(self.keys())

    def register(self, name: str, function: Callable, *args, **kwargs) -> None:
        for stats in self.values():
            stats.register(name, function, *args, **kwargs)

    def compile(self, pop):
        return {chapter: stats.compile(pop) for chapter, stats in self.items()}


def fitness_stats(axis: int | None = 0) -> Statistics:
    """The conventional avg/std/min/max fitness statistics block used by
    every reference example (e.g. examples/ga/onemax.py)."""
    stats = Statistics(lambda pop: pop.fitness[:, 0] if pop.nobj == 1 else pop.fitness)
    stats.register("avg", jnp.mean, axis=axis)
    stats.register("std", jnp.std, axis=axis)
    stats.register("min", jnp.min, axis=axis)
    stats.register("max", jnp.max, axis=axis)
    return stats
