"""ParetoArchive — capacity-bounded non-dominated archive on device.

Counterpart of /root/reference/deap/tools/support.py:591-640
(``ParetoFront``): keeps every individual not dominated by any other seen
so far, dropping newly-dominated members. The reference archive is
unbounded (a Python list); a device archive needs static shapes, so this
one has a fixed capacity — overflow drops lexicographically-worst
members, and the unbounded variant lives in the host/compat backend.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from jax import lax

from deap_tpu.core.fitness import FitnessSpec, dominates, lex_sort_desc
from deap_tpu.core.population import Population
from deap_tpu.support.hof import duplicate_mask


@struct.dataclass
class ParetoArchive:
    genomes: Any
    fitness: jnp.ndarray
    filled: jnp.ndarray
    spec: FitnessSpec = struct.field(pytree_node=False, default=FitnessSpec((1.0,)))

    @property
    def capacity(self) -> int:
        return self.filled.shape[0]


def pareto_init(capacity: int, pop: Population) -> ParetoArchive:
    take0 = lambda a: jnp.zeros((capacity,) + a.shape[1:], a.dtype)
    return ParetoArchive(
        genomes=jax.tree_util.tree_map(take0, pop.genomes),
        fitness=jnp.zeros((capacity, pop.nobj), pop.fitness.dtype),
        filled=jnp.zeros(capacity, bool),
        spec=pop.spec,
    )


def nondominated_mask(w: jnp.ndarray, valid: jnp.ndarray | None = None,
                      chunk: int = 512) -> jnp.ndarray:
    """bool[n]: rows not Pareto-dominated by any other row.

    The O(n²) dominance work is one fused batched comparison — the
    TPU-friendly replacement for the reference's per-pair loop
    (support.py:612-633) — computed in row chunks so peak memory is
    O(chunk · n · nobj) instead of O(n²): usable at 100k populations
    inside a scanned step.
    """
    n = w.shape[0]
    if valid is None:
        valid = jnp.ones(n, bool)
    pad = (-n) % chunk
    wp = jnp.pad(w, ((0, pad), (0, 0)))
    vp = jnp.pad(valid, (0, pad))

    def block(args):
        wi, vi = args  # [chunk, nobj], [chunk]
        dom = dominates(w[None, :, :], wi[:, None, :]) & valid[None, :]
        return vi & ~jnp.any(dom, axis=1)

    out = lax.map(block, (wp.reshape(-1, chunk, w.shape[1]),
                          vp.reshape(-1, chunk)))
    return out.reshape(-1)[:n]


def pareto_update(archive: ParetoArchive, pop: Population,
                  dedup: bool = True) -> ParetoArchive:
    """Merge a population into the archive.

    Pool = archive ∪ population, keep the pool's non-dominated subset
    (deduplicated on genome equality), lex-sorted, truncated at capacity.
    """
    cap = archive.capacity
    # Dominance is not aligned with lex order in multi-objective spaces,
    # so the full population must be merged; the dominance pass is
    # chunked and the dedup is sort-based, so the cost is O(n²/chunk)
    # compute with O(chunk·n) memory — fine at 100k inside a scan.
    cat = lambda a, b: jnp.concatenate([a, b], axis=0)
    all_g = jax.tree_util.tree_map(cat, archive.genomes, pop.genomes)
    all_f = cat(archive.fitness, pop.fitness)
    all_valid = cat(archive.filled, pop.valid)

    w = all_f * archive.spec.warray
    w = jnp.where(all_valid[:, None], w, -jnp.inf)
    nd = nondominated_mask(w, all_valid)

    if dedup:
        nd &= ~duplicate_mask(all_g, w, all_valid)

    order = lex_sort_desc(jnp.where(nd[:, None], w, -jnp.inf))[:cap]
    take = lambda a: jnp.take(a, order, axis=0)
    return ParetoArchive(
        genomes=jax.tree_util.tree_map(take, all_g),
        fitness=take(all_f),
        filled=take(nd),
        spec=archive.spec,
    )
