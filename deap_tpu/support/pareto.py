"""ParetoArchive — capacity-bounded non-dominated archive on device.

Counterpart of /root/reference/deap/tools/support.py:591-640
(``ParetoFront``): keeps every individual not dominated by any other seen
so far, dropping newly-dominated members. The reference archive is
unbounded (a Python list); a device archive needs static shapes, so this
one has a fixed capacity — overflow drops lexicographically-worst
members, and the unbounded variant lives in the host/compat backend.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from deap_tpu.core.fitness import FitnessSpec, dominates, lex_sort_desc
from deap_tpu.core.population import Population
from deap_tpu.support.hof import _genome_eq_matrix


@struct.dataclass
class ParetoArchive:
    genomes: Any
    fitness: jnp.ndarray
    filled: jnp.ndarray
    spec: FitnessSpec = struct.field(pytree_node=False, default=FitnessSpec((1.0,)))

    @property
    def capacity(self) -> int:
        return self.filled.shape[0]


def pareto_init(capacity: int, pop: Population) -> ParetoArchive:
    take0 = lambda a: jnp.zeros((capacity,) + a.shape[1:], a.dtype)
    return ParetoArchive(
        genomes=jax.tree_util.tree_map(take0, pop.genomes),
        fitness=jnp.zeros((capacity, pop.nobj), pop.fitness.dtype),
        filled=jnp.zeros(capacity, bool),
        spec=pop.spec,
    )


def nondominated_mask(w: jnp.ndarray, valid: jnp.ndarray | None = None) -> jnp.ndarray:
    """bool[n]: rows not Pareto-dominated by any other row.

    The O(n²) pairwise dominance matrix is one fused batched comparison —
    the TPU-friendly replacement for the reference's per-pair loop
    (support.py:612-633). Intended for selection-sized fronts.
    """
    dom = dominates(w[None, :, :], w[:, None, :])  # dom[i, j]: j dominates i
    if valid is not None:
        dom &= valid[None, :]
        return valid & ~jnp.any(dom, axis=1)
    return ~jnp.any(dom, axis=1)


def pareto_update(archive: ParetoArchive, pop: Population,
                  dedup: bool = True) -> ParetoArchive:
    """Merge a population into the archive.

    Pool = archive ∪ population, keep the pool's non-dominated subset
    (deduplicated on genome equality), lex-sorted, truncated at capacity.
    """
    cap = archive.capacity
    # Reduce the population to its lex-best min(n, 4*cap) rows first when
    # it is much larger than the archive? No — dominance is not aligned
    # with lex order in multi-objective spaces; merge the full population.
    cat = lambda a, b: jnp.concatenate([a, b], axis=0)
    all_g = jax.tree_util.tree_map(cat, archive.genomes, pop.genomes)
    all_f = cat(archive.fitness, pop.fitness)
    all_valid = cat(archive.filled, pop.valid)

    w = all_f * archive.spec.warray
    w = jnp.where(all_valid[:, None], w, -jnp.inf)
    nd = nondominated_mask(w, all_valid)

    if dedup:
        eq = _genome_eq_matrix(all_g)
        earlier = jnp.tril(jnp.ones_like(eq), k=-1)
        is_dup = jnp.any(eq & earlier & all_valid[None, :], axis=1)
        nd &= ~is_dup

    order = lex_sort_desc(jnp.where(nd[:, None], w, -jnp.inf))[:cap]
    take = lambda a: jnp.take(a, order, axis=0)
    return ParetoArchive(
        genomes=jax.tree_util.tree_map(take, all_g),
        fitness=take(all_f),
        filled=take(nd),
        spec=archive.spec,
    )
