"""Checkpoint / resume of full evolution state.

The reference leaves checkpointing to the user: pickle a dict of
{population, generation, halloffame, logbook, random.getstate()} every
FREQ generations and restore it, RNG state included
(/root/reference/doc/tutorials/advanced/checkpoint.rst:22-70). Here the
entire evolution state — population pytree, strategy state, hall of
fame, PRNG key — is one pytree, so a checkpoint is a faithful snapshot
by construction and resuming is bit-exact (explicit `jax.random` keys
make RNG restoration trivial, SURVEY.md §5.4).

Implementation: a self-contained portable format — flattened pytree →
numpy arrays + pickled treedef, written atomically. Typed PRNG key
arrays are converted through ``jax.random.key_data``/``wrap_key_data``
so they survive serialization. (Evolution state is tiny next to NN
checkpoints; for multi-host sharded runs, swap :func:`save_state` for an
orbax checkpointer behind the same :class:`Checkpointer` interface.)
"""

from __future__ import annotations

import os
import pickle
import re
import shutil
from typing import Any, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

_PRNG_TAG = "__prng_key__"


def _pack_leaf(leaf: Any) -> Any:
    if isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
        impl = str(jax.random.key_impl(leaf))
        return {_PRNG_TAG: impl, "data": np.asarray(jax.random.key_data(leaf))}
    if isinstance(leaf, jax.Array):
        return np.asarray(leaf)
    return leaf


def _unpack_leaf(leaf: Any) -> Any:
    if isinstance(leaf, dict) and _PRNG_TAG in leaf:
        m = re.search(r"'(\w+)'", leaf[_PRNG_TAG])
        impl = m.group(1) if m else leaf[_PRNG_TAG]
        return jax.random.wrap_key_data(jnp.asarray(leaf["data"]), impl=impl)
    if isinstance(leaf, np.ndarray):
        return jnp.asarray(leaf)
    return leaf


def save_state(path: str, state: Any) -> None:
    """Serialize an arbitrary state pytree to ``path`` (atomic write)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    payload = {"leaves": [_pack_leaf(l) for l in leaves], "treedef": treedef}
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(payload, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)
    # surface the write in any open run journal (no-op otherwise)
    from deap_tpu.telemetry.journal import broadcast
    broadcast("checkpoint", path=path, bytes=os.path.getsize(path))


def restore_state(path: str) -> Any:
    """Load a state pytree written by :func:`save_state`."""
    with open(path, "rb") as f:
        payload = pickle.load(f)
    leaves = [_unpack_leaf(l) for l in payload["leaves"]]
    return jax.tree_util.tree_unflatten(payload["treedef"], leaves)


class Checkpointer:
    """Step-indexed checkpoint directory with rotation.

    The tensor analog of the reference's every-FREQ-generations pickle
    recipe (checkpoint.rst:22-70):

    >>> ckpt = Checkpointer(dir, keep=3)
    >>> if ckpt.latest_step() is not None:
    ...     state = ckpt.restore()          # resume, RNG key included
    >>> ckpt.save(gen, state)               # inside the outer loop
    """

    def __init__(self, directory: str, keep: int = 3, prefix: str = "ckpt"):
        self.directory = directory
        self.keep = keep
        self.prefix = prefix
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.prefix}_{step:08d}.pkl")

    def steps(self) -> List[int]:
        pat = re.compile(rf"{re.escape(self.prefix)}_(\d+)\.pkl$")
        out = []
        for name in os.listdir(self.directory):
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any) -> str:
        path = self._path(step)
        save_state(path, state)
        if self.keep is not None:
            for old in self.steps()[: -self.keep]:
                os.remove(self._path(old))
        return path

    def restore(self, step: Optional[int] = None) -> Any:
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return restore_state(self._path(step))

    def clear(self) -> None:
        shutil.rmtree(self.directory, ignore_errors=True)
        os.makedirs(self.directory, exist_ok=True)
