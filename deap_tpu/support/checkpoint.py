"""Checkpoint / resume of full evolution state.

The reference leaves checkpointing to the user: pickle a dict of
{population, generation, halloffame, logbook, random.getstate()} every
FREQ generations and restore it, RNG state included
(/root/reference/doc/tutorials/advanced/checkpoint.rst:22-70). Here the
entire evolution state — population pytree, strategy state, hall of
fame, PRNG key — is one pytree, so a checkpoint is a faithful snapshot
by construction and resuming is bit-exact (explicit `jax.random` keys
make RNG restoration trivial, SURVEY.md §5.4).

Implementation: a self-contained **crash-consistent** portable format
(version 2) — each packed leaf is pickled to its own blob with a CRC32,
plus a CRC'd treedef blob and a format-version tag, written
fsync-before-rename so a power cut or SIGKILL can never leave a torn
file under the final name. Typed PRNG key arrays are converted through
``jax.random.key_data``/``wrap_key_data`` with the canonical impl name
stored explicitly at pack time. :func:`restore_state` verifies every
CRC and raises :class:`CheckpointCorruptError` on any mismatch or
unreadable payload; :class:`Checkpointer` turns that into automatic
fallback to the newest *valid* older step, and its rotation never
deletes the last verified-good snapshot. Version-1 files (the pre-CRC
format) still restore. (Evolution state is tiny next to NN checkpoints;
for multi-host sharded runs, swap :func:`save_state` for an orbax
checkpointer behind the same :class:`Checkpointer` interface.)
"""

from __future__ import annotations

import contextlib
import os
import pickle
import re
import shutil
import threading
import time
import zlib
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: below this many leaves the thread-pool handoff costs more than the
#: serial loop it replaces — small states stay on the caller's thread
_PIPELINE_MIN_LEAVES = 8

#: restore worker count: zlib.crc32 and the device_put inside
#: ``_unpack_leaf`` both release the GIL, so a handful of workers
#: overlaps CRC, unpickle, and host→device transfer across leaves
_RESTORE_WORKERS = max(2, min(8, os.cpu_count() or 2))

#: cumulative wall seconds this process has spent materialising
#: checkpoint state (see :func:`restore_seconds_total`)
_RESTORE_SECONDS = [0.0]


def restore_seconds_total() -> float:
    """Cumulative wall-clock seconds this process has spent inside
    :func:`restore_state` / :meth:`Checkpointer.restore_latest`
    payload verification + materialisation. The serving layer reads
    this as a delta around its WAL replay to attribute the ``restore``
    slice of its startup-phase ledger (docs/advanced/coldstart.md)."""
    return _RESTORE_SECONDS[0]

_PRNG_TAG = "__prng_key__"
_SHARD_TAG = "__sharded_leaf__"

#: payload format written by :func:`save_state`; bump when the layout
#: changes (restore keeps reading every older version).
#: v3: mesh-partitioned array leaves are stored in a **per-shard
#: layout** — one (index, bytes) entry per distinct shard instead of a
#: gathered monolith — so a checkpoint written on an n=8 mesh carries
#: its own partitioning and restores onto ANY mesh size (the elastic
#: resume of deap_tpu.parallel.plan: reassemble + one reshard step).
FORMAT_VERSION = 3


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file exists but cannot be trusted: unreadable
    pickle, CRC mismatch, or a payload that is not a checkpoint."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"corrupt checkpoint {path}: {detail}")
        self.path = path
        self.detail = detail


class CheckpointFormatError(CheckpointCorruptError):
    """The checkpoint's bytes are intact but this code must not
    restore them: the file carries a NEWER format than this build
    understands, or it was written by a different ``deap_tpu`` version
    and the compat gate (:func:`allow_compat_restore`) is closed. The
    named refusal the rolling-upgrade path relies on — before it, a
    foreign-format file died as an arbitrary unpickle error."""


#: process-wide checkpoint compat gate: closed (default) → restoring a
#: file stamped by a different ``deap_tpu`` version raises
#: :class:`CheckpointFormatError`; open → the restore proceeds and
#: journals a ``compat_restore`` row. The rolling-upgrade drill opens
#: it on the NEW-version process so it can adopt the old version's
#: tenants — an explicit operator decision, never a silent default.
_COMPAT_ALLOW = [False]


def _code_version() -> str:
    """The running code's version stamp (``deap_tpu.__version__``,
    overridable via ``DEAP_TPU_VERSION_OVERRIDE`` — the chaos drill's
    hook for running two "versions" from one checkout)."""
    env = os.environ.get("DEAP_TPU_VERSION_OVERRIDE")
    if env:
        return env
    from deap_tpu import __version__
    return __version__


def set_compat_restore(allow: bool) -> bool:
    """Open/close the process-wide compat gate; returns the previous
    state. A service doing a rolling upgrade sets this once at startup
    (``EvolutionService(compat_restore=True)``)."""
    prev = _COMPAT_ALLOW[0]
    _COMPAT_ALLOW[0] = bool(allow)
    return prev


@contextlib.contextmanager
def allow_compat_restore():
    """Scoped form of :func:`set_compat_restore` — restores made
    inside the ``with`` block may cross ``deap_tpu`` versions (each
    journals ``compat_restore``); the gate snaps back on exit."""
    prev = set_compat_restore(True)
    try:
        yield
    finally:
        set_compat_restore(prev)


def _key_impl_name(key: jax.Array) -> str:
    """Canonical PRNG impl name for a typed key array. jax's
    ``key_impl`` has returned a plain string (0.4.x) and a PRNGSpec
    object (newer) — normalise to the registry name that
    ``wrap_key_data(..., impl=name)`` accepts, with no repr parsing."""
    spec = jax.random.key_impl(key)
    if isinstance(spec, str):
        return spec
    name = getattr(spec, "name", None) or getattr(
        getattr(spec, "_impl", None), "name", None)
    return name if isinstance(name, str) else str(spec)


def _is_partitioned(leaf: jax.Array) -> bool:
    """True when the array is actually split over devices (not merely
    multi-device replicated) and every shard is addressable from this
    process — the case the per-shard v3 layout captures."""
    sharding = getattr(leaf, "sharding", None)
    if sharding is None:
        return False
    try:
        if sharding.is_fully_replicated:
            return False
        return bool(leaf.is_fully_addressable)
    except Exception:
        return False


def _shard_index_bounds(index, shape) -> tuple:
    """Normalise a shard's index (a tuple of slices) to
    ``((start, stop), ...)`` ints — stable to pickle, trivially
    re-applied on restore."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"non-unit shard stride {step}")
        out.append((int(start), int(stop)))
    return tuple(out)


def _pack_leaf(leaf: Any) -> Any:
    if isinstance(leaf, dict) and (_PRNG_TAG in leaf or _SHARD_TAG in leaf):
        return leaf  # already packed (AsyncCheckpointWriter materialize)
    if isinstance(leaf, jax.Array) and jnp.issubdtype(leaf.dtype, jax.dtypes.prng_key):
        return {_PRNG_TAG: _key_impl_name(leaf),
                "data": np.asarray(jax.random.key_data(leaf))}
    if isinstance(leaf, jax.Array):
        if _is_partitioned(leaf):
            # per-shard leaf layout (format v3): one entry per distinct
            # shard index, replicas deduplicated — the checkpoint
            # records the partitioning instead of gathering it away,
            # and restore reassembles on ANY mesh size
            shards, seen = [], set()
            for s in leaf.addressable_shards:
                idx = _shard_index_bounds(s.index, leaf.shape)
                if idx in seen:
                    continue
                seen.add(idx)
                shards.append((idx, np.asarray(s.data)))
            return {_SHARD_TAG: True, "shape": tuple(leaf.shape),
                    "dtype": np.dtype(leaf.dtype).str, "shards": shards}
        return np.asarray(leaf)
    return leaf


def _unpack_leaf(leaf: Any) -> Any:
    if isinstance(leaf, dict) and _SHARD_TAG in leaf:
        shape = tuple(leaf["shape"])
        arr = np.empty(shape, np.dtype(leaf["dtype"]))
        covered = 0
        for idx, data in leaf["shards"]:
            arr[tuple(slice(a, b) for a, b in idx)] = data
            extent = 1
            for a, b in idx:
                extent *= max(b - a, 0)
            covered += extent
        if covered != arr.size:
            raise ValueError(
                f"sharded leaf covers {covered} of {arr.size} elements "
                "— shard set incomplete")
        # uncommitted single-device on return: the caller's reshard
        # step (ShardingPlan.place / ResilientRun plan=) re-commits it
        # to whatever mesh the resumed process runs on
        return jnp.asarray(arr)
    if isinstance(leaf, dict) and _PRNG_TAG in leaf:
        impl = leaf[_PRNG_TAG]
        # version-1 files written under jax versions whose key_impl
        # stringified to a repr (e.g. "PRNGSpec('rbg')") — extract the
        # quoted name; version-2 files store the canonical name as-is
        m = re.search(r"'(\w+)'", impl)
        if m:
            impl = m.group(1)
        return jax.random.wrap_key_data(jnp.asarray(leaf["data"]), impl=impl)
    if isinstance(leaf, np.ndarray):
        return jnp.asarray(leaf)
    return leaf


def _fsync_dir(path: str) -> None:
    """fsync the directory entry so the rename itself is durable (an
    atomic replace only guarantees old-or-new content; the *name* can
    still vanish in a crash without this). Best-effort — not every
    filesystem hands out directory fds."""
    d = os.path.dirname(os.path.abspath(path)) or "."
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def save_state(path: str, state: Any, meta: Optional[Dict[str, Any]] = None,
               fsync: bool = True) -> int:
    """Serialize an arbitrary state pytree to ``path``.

    Crash-consistent: the payload (per-leaf blobs + CRC32s + format
    version + optional ``meta`` dict) is written to a temp file,
    fsync'd, atomically renamed over ``path``, and the directory entry
    fsync'd — at no point can a reader observe a torn file under the
    final name. ``meta`` round-trips via :func:`checkpoint_meta`
    without deserializing the state (run-id chaining reads it).

    ``fsync=False`` keeps the atomic temp-file + rename (readers still
    never see a torn file) but skips both fsyncs. Process death —
    SIGKILL included — leaves the OS page cache intact, so this only
    trades durability against a *host* power cut, where the newest
    checkpoint may be lost and restore falls back one step. The
    high-frequency serving path (every resident tenant, every
    boundary) takes this mode: the fsync pair is per-save storage
    latency on the boundary critical path.

    Returns the CRC32 of the exact container bytes written — a
    read-back compare against it proves the bytes landed intact
    without re-unpickling the file (the high-frequency serving
    checkpoint path saves every resident tenant every boundary)."""
    leaves, treedef = jax.tree_util.tree_flatten(state)
    blobs = [pickle.dumps(_pack_leaf(l), protocol=pickle.HIGHEST_PROTOCOL)
             for l in leaves]
    treedef_blob = pickle.dumps(treedef, protocol=pickle.HIGHEST_PROTOCOL)
    # provenance stamp (rolling-upgrade compat gate): which code wrote
    # this file, in which layout — setdefault, so a caller migrating
    # a foreign checkpoint may preserve the original stamps
    stamped = dict(meta or {})
    stamped.setdefault("deap_tpu_version", _code_version())
    stamped.setdefault("checkpoint_format", FORMAT_VERSION)
    payload = {
        "format_version": FORMAT_VERSION,
        "treedef": treedef_blob,
        "treedef_crc": zlib.crc32(treedef_blob),
        "leaves": blobs,
        "crcs": [zlib.crc32(b) for b in blobs],
        "meta": stamped,
    }
    buf = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf)
        f.flush()
        if fsync:
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        _fsync_dir(path)
    container_crc = zlib.crc32(buf)
    # surface the write in any open run journal (no-op otherwise);
    # tenant-stamped metas also stamp the row with tenant/request id
    # so one grep over the id finds the request's checkpoint writes
    from deap_tpu.telemetry.journal import broadcast
    ids = {k: payload["meta"][k]
           for k in ("tenant_id", "request_id")
           if payload["meta"].get(k)}
    broadcast("checkpoint", path=path, bytes=len(buf), **ids)
    return container_crc


def _load_payload(path: str) -> Any:
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except FileNotFoundError:
        raise
    except Exception as e:  # torn/garbage pickle, EOF, bad opcode ...
        raise CheckpointCorruptError(path, f"unreadable payload ({e!r})")


def _verify_payload(path: str, payload: Any) -> None:
    """CRC-check a version>=2 payload; raise on the first mismatch.

    Leaf CRCs are computed on a thread pool when the state is large
    (``zlib.crc32`` releases the GIL) — mismatch reporting stays
    deterministic: always the lowest-index bad leaf, exactly as the
    serial loop reported it."""
    if not isinstance(payload, dict):
        raise CheckpointCorruptError(path, "payload is not a dict")
    version = payload.get("format_version")
    if version is None:
        # version-1 format: {"leaves": [...], "treedef": treedef} — no
        # checksums to verify, structural presence is the only check
        if "leaves" not in payload or "treedef" not in payload:
            raise CheckpointCorruptError(path, "not a checkpoint payload")
        return
    if int(version) > FORMAT_VERSION:
        # a FUTURE layout: this build cannot know what the fields mean,
        # so refuse by name instead of failing on an arbitrary unpickle
        # (the old-code-meets-new-file half of a rolling upgrade)
        raise CheckpointFormatError(
            path, f"format_version {version} is newer than this "
                  f"build's {FORMAT_VERSION}; upgrade deap_tpu to "
                  "restore it")
    for k in ("treedef", "treedef_crc", "leaves", "crcs"):
        if k not in payload:
            raise CheckpointCorruptError(path, f"missing field {k!r}")
    if zlib.crc32(payload["treedef"]) != payload["treedef_crc"]:
        raise CheckpointCorruptError(path, "treedef CRC mismatch")
    if len(payload["leaves"]) != len(payload["crcs"]):
        raise CheckpointCorruptError(path, "leaf/CRC count mismatch")
    blobs = payload["leaves"]
    if len(blobs) >= _PIPELINE_MIN_LEAVES:
        with ThreadPoolExecutor(max_workers=_RESTORE_WORKERS) as pool:
            computed = list(pool.map(zlib.crc32, blobs))
    else:
        computed = [zlib.crc32(b) for b in blobs]
    for i, (got, want) in enumerate(zip(computed, payload["crcs"])):
        if got != want:
            raise CheckpointCorruptError(path, f"leaf {i} CRC mismatch")


def verify_checkpoint(path: str) -> Dict[str, Any]:
    """Validate ``path`` without materialising the state: checks the
    pickle container and every CRC. Returns the ``meta`` dict. Raises
    :class:`CheckpointCorruptError` (or ``FileNotFoundError``)."""
    payload = _load_payload(path)
    _verify_payload(path, payload)
    meta = payload.get("meta", {}) if isinstance(payload, dict) else {}
    return meta if isinstance(meta, dict) else {}


def checkpoint_meta(path: str,
                    tenant_id: Optional[str] = None) -> Dict[str, Any]:
    """The ``meta`` dict stored by :func:`save_state` (empty for
    version-1 files). Verifies CRCs on the way.

    ``tenant_id`` asserts ownership: the serving layer stamps every
    per-tenant checkpoint with its tenant id, and a reader that knows
    whose state it expects passes it here — a mismatch (including a
    file with no tenant stamp at all) raises ``ValueError`` instead of
    handing one tenant another tenant's state."""
    meta = verify_checkpoint(path)
    if tenant_id is not None and meta.get("tenant_id") != tenant_id:
        raise ValueError(
            f"checkpoint {path} belongs to tenant "
            f"{meta.get('tenant_id')!r}, not {tenant_id!r}")
    return meta


def _materialize(path: str, payload: Any) -> Any:
    """Decode an already-verified payload into the state pytree.

    Large states decode on a thread pool: each worker unpickles its
    blob and runs :func:`_unpack_leaf`, whose ``jnp.asarray`` is a
    host→device transfer that releases the GIL — so leaf *i*'s
    device_put overlaps leaf *i+1*'s deserialize instead of
    serialising behind it (the pipelined-restore half of ISSUE 18).
    Leaf order is preserved (``pool.map``), so the reassembled pytree
    — and therefore the resumed run — is bit-identical to the serial
    path."""
    # code-version gate (single choke point: restore_state AND
    # Checkpointer.restore_latest both materialise through here;
    # verify_checkpoint/checkpoint_meta stay exempt so discovery can
    # read foreign metas freely). Unstamped files — every pre-gate
    # checkpoint — restore unconditionally.
    meta = payload.get("meta")
    meta = meta if isinstance(meta, dict) else {}
    written_by = meta.get("deap_tpu_version")
    if written_by and written_by != _code_version():
        if not _COMPAT_ALLOW[0]:
            raise CheckpointFormatError(
                path, f"written by deap_tpu {written_by}, running "
                      f"{_code_version()}; cross-version restore needs "
                      "the explicit compat gate (allow_compat_restore"
                      "() / set_compat_restore(True))")
        from deap_tpu.telemetry.journal import broadcast
        broadcast("compat_restore", path=path,
                  written_by=str(written_by), running=_code_version(),
                  **{k: meta[k] for k in ("tenant_id", "request_id")
                     if meta.get(k)})
    if payload.get("format_version") is None:
        leaves = [_unpack_leaf(l) for l in payload["leaves"]]
        return jax.tree_util.tree_unflatten(payload["treedef"], leaves)

    def decode(blob: bytes) -> Any:
        return _unpack_leaf(pickle.loads(blob))

    try:
        treedef = pickle.loads(payload["treedef"])
        blobs = payload["leaves"]
        if len(blobs) >= _PIPELINE_MIN_LEAVES:
            with ThreadPoolExecutor(
                    max_workers=_RESTORE_WORKERS) as pool:
                leaves = list(pool.map(decode, blobs))
        else:
            leaves = [decode(b) for b in blobs]
    except Exception as e:  # CRC passed but unpickling failed anyway
        raise CheckpointCorruptError(path, f"undecodable leaf ({e!r})")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def restore_state(path: str) -> Any:
    """Load a state pytree written by :func:`save_state`.

    Verifies the format version and every CRC first; raises
    :class:`CheckpointCorruptError` naming the failure rather than
    returning silently-wrong state. Reads both the current and the
    version-1 (pre-CRC) payload layout."""
    t0 = time.perf_counter()
    try:
        payload = _load_payload(path)
        _verify_payload(path, payload)
        return _materialize(path, payload)
    finally:
        _RESTORE_SECONDS[0] += time.perf_counter() - t0


class Checkpointer:
    """Step-indexed checkpoint directory with corruption-safe rotation.

    The tensor analog of the reference's every-FREQ-generations pickle
    recipe (checkpoint.rst:22-70):

    >>> ckpt = Checkpointer(dir, keep=3)
    >>> if ckpt.latest_step() is not None:
    ...     state = ckpt.restore()          # resume, RNG key included
    >>> ckpt.save(gen, state)               # inside the outer loop

    Robustness contract (tests/test_checkpoint_hardening.py):

    - :meth:`restore` with no explicit step walks steps newest-first
      and silently falls back past corrupt files to the newest *valid*
      one (each skip journaled as a ``checkpoint_corrupt`` event).
    - rotation never deletes the newest checkpoint known to be valid:
      a save whose own verification fails rotates nothing.
    - :meth:`steps`/:meth:`latest_step` return ``[]``/``None`` when the
      directory was removed out from under a live run; only an actual
      :meth:`restore` raises (a clear error naming the missing path).
    """

    def __init__(self, directory: str, keep: int = 3, prefix: str = "ckpt",
                 fsync: bool = True):
        self.directory = directory
        self.keep = keep
        self.prefix = prefix
        self.fsync = fsync  # False: page-cache durability (see save_state)
        self._verified: set = set()   # steps whose file passed CRC
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.directory, f"{self.prefix}_{step:08d}.pkl")

    def path_for(self, step: int) -> str:
        """The file path a :meth:`save` of ``step`` lands at — exposed
        so asynchronous writers can report the destination before the
        write completes."""
        return self._path(step)

    def steps(self) -> List[int]:
        pat = re.compile(rf"{re.escape(self.prefix)}_(\d+)\.pkl$")
        try:
            names = os.listdir(self.directory)
        except (FileNotFoundError, NotADirectoryError):
            return []  # directory removed under a live run: no steps
        out = []
        for name in names:
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        steps = self.steps()
        return steps[-1] if steps else None

    def save(self, step: int, state: Any,
             meta: Optional[Dict[str, Any]] = None) -> str:
        path = self._path(step)
        os.makedirs(self.directory, exist_ok=True)
        want_crc = save_state(path, state, meta=meta, fsync=self.fsync)
        try:
            # post-save verify by raw read-back: the file's bytes must
            # CRC-match the container we just serialized. Equivalent
            # bad-write detection to re-running verify_checkpoint()
            # (any flipped/torn byte changes the container CRC) at a
            # fraction of the cost — no unpickle, no per-leaf CRC walk
            # (the serving layer saves every resident every boundary)
            with open(path, "rb") as f:
                got_crc = zlib.crc32(f.read())
            if got_crc != want_crc:
                raise CheckpointCorruptError(
                    path, "post-save read-back CRC mismatch")
            self._verified.add(step)
        except (CheckpointCorruptError, FileNotFoundError, OSError):
            # the write itself went bad (disk fault): keep every older
            # file — rotating here could delete the only good snapshot
            from deap_tpu.telemetry.journal import broadcast
            broadcast("checkpoint_corrupt", path=path,
                      phase="post_save_verify")
            return path
        if self.keep is not None:
            steps = self.steps()
            last_good = max((s for s in self._verified if s in steps),
                            default=None)
            for old in steps[: -self.keep]:
                if old == last_good:
                    continue  # never delete the last verified-good one
                os.remove(self._path(old))
        return path

    def restore(self, step: Optional[int] = None) -> Any:
        """Restore a checkpoint. With ``step=None``: the newest valid
        one — corrupt files are skipped (journaled) and the next older
        step is tried; raises only when nothing valid remains. With an
        explicit ``step``: exactly that file, raising
        ``FileNotFoundError``/:class:`CheckpointCorruptError`."""
        if step is not None:
            path = self._path(step)
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"no checkpoint for step {step}: {path} is missing")
            state = restore_state(path)
            self._verified.add(step)
            return state
        got = self.restore_latest()
        if got is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        return got[1]

    def restore_latest(self, tenant_id: Optional[str] = None
                       ) -> Optional[Tuple[int, Any]]:
        """``(step, state)`` of the newest valid checkpoint, or ``None``
        when the directory holds no checkpoints at all. Corrupt files
        are skipped newest-first, each journaled as a
        ``checkpoint_corrupt`` event; if every file is corrupt, raises
        :class:`CheckpointCorruptError`.

        ``tenant_id`` (the serving layer's per-tenant swap unit) makes
        the walk *ownership-filtered*: files whose v2 ``meta`` carries
        a different ``tenant_id`` — or none at all — are skipped (each
        journaled as ``checkpoint_tenant_mismatch``), so co-located or
        misconfigured tenant directories can never cross-restore."""
        from deap_tpu.telemetry.journal import broadcast

        steps = self.steps()
        if not steps:
            return None
        last_error: Optional[CheckpointCorruptError] = None
        for s in reversed(steps):
            path = self._path(s)
            meta: Dict[str, Any] = {}
            t0 = time.perf_counter()
            try:
                # load + verify each file exactly ONCE per walk: the
                # tenant-filtered path used to run checkpoint_meta()
                # (full payload read + CRC sweep) and then
                # restore_state() (the same read + sweep again) —
                # materialise from the payload already in hand instead
                payload = _load_payload(path)
                _verify_payload(path, payload)
                raw_meta = payload.get("meta", {}) \
                    if isinstance(payload, dict) else {}
                meta = raw_meta if isinstance(raw_meta, dict) else {}
                if tenant_id is not None \
                        and meta.get("tenant_id") != tenant_id:
                    broadcast("checkpoint_tenant_mismatch",
                              path=path, expected=tenant_id,
                              found=meta.get("tenant_id"))
                    continue
                state = _materialize(path, payload)
            except FileNotFoundError:
                continue  # rotated away between listdir and read
            except CheckpointCorruptError as e:
                last_error = e
                broadcast("checkpoint_corrupt", path=path,
                          detail=e.detail, fallback=True)
                continue
            finally:
                _RESTORE_SECONDS[0] += time.perf_counter() - t0
            self._verified.add(s)
            if s != steps[-1]:
                broadcast("checkpoint_fallback", path=path, step=s,
                          skipped=[x for x in steps if x > s])
            # the restore row, tenant/request-stamped when the file's
            # meta carries the ids (read for free on the tenant-
            # filtered path) — the read-side mirror of the
            # ``checkpoint`` save row, so one grep over a request id
            # shows both halves of every swap/resume
            broadcast("checkpoint_restore", path=path, step=s,
                      **{k: meta[k]
                         for k in ("tenant_id", "request_id")
                         if meta.get(k)})
            return s, state
        if tenant_id is not None and last_error is None:
            return None  # only foreign-tenant files present
        raise last_error if last_error is not None else FileNotFoundError(
            f"no checkpoints in {self.directory}")

    def meta(self, step: Optional[int] = None) -> Dict[str, Any]:
        """The ``meta`` dict of a checkpoint (default: latest step) —
        run-id chaining reads this without materialising the state."""
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoints in {self.directory}")
        return checkpoint_meta(self._path(step))

    def clear(self) -> None:
        shutil.rmtree(self.directory, ignore_errors=True)
        self._verified.clear()
        os.makedirs(self.directory, exist_ok=True)


class AsyncCheckpointWriter:
    """Double-buffered checkpoint writes: snapshot on the caller's
    thread, serialize + fsync on a background one.

    :meth:`submit` (1) flattens the state pytree — the leaves are
    immutable device arrays / scalars, so later in-place mutation of
    the live state dicts cannot leak into the file — (2) starts the
    device→host copy of every array leaf (``copy_to_host_async``, a
    non-blocking DMA enqueue), and (3) hands the snapshot to a worker
    thread that materialises the host buffers and runs the ordinary
    crash-consistent :meth:`Checkpointer.save` (fsync-before-rename,
    CRC, rotation). The caller is free to dispatch the next segment's
    compute immediately — the D2H copy and the pickle/fsync overlap
    with it, which is what drives the segmented-run tax toward zero
    (``bench.py --resilience``, gate tightened to 1.5%).

    At most one write is in flight: :meth:`submit` waits for the
    previous one first (bounded memory — classic double buffering), and
    any worker exception is re-raised on the caller's thread at the
    next :meth:`wait`/:meth:`submit`, so a failing disk still fails the
    run rather than rotting silently. The on-disk format and its
    guarantees are unchanged — a kill mid-write leaves the previous
    checkpoint intact, exactly as with synchronous saves.
    """

    def __init__(self, materialize: bool = False):
        """``materialize=True`` packs every leaf to host memory ON the
        caller's thread before the worker starts (the per-shard v3
        layout is preserved — :func:`_pack_leaf` is idempotent in
        :func:`save_state`). Required when the next segment's compile
        DONATES the state buffers (``ShardingPlan`` runs): a donated
        buffer is reused in place by the next computation, so an
        asynchronous read of it would race — the synchronous pack costs
        one D2H copy per segment, amortised over the segment."""
        self.materialize = bool(materialize)
        self._thread: Optional[threading.Thread] = None
        self._exc: Optional[BaseException] = None
        self.last_path: Optional[str] = None

    def submit(self, ckpt: Checkpointer, step: int, state: Any,
               meta: Optional[Dict[str, Any]] = None) -> str:
        """Queue ``ckpt.save(step, state, meta)``; returns the path the
        checkpoint will land at. Blocks only until the *previous*
        submit finished."""
        self.wait()
        leaves, treedef = jax.tree_util.tree_flatten(state)
        if self.materialize:
            leaves = [_pack_leaf(l) for l in leaves]
        else:
            for leaf in leaves:
                if isinstance(leaf, jax.Array):
                    try:
                        leaf.copy_to_host_async()
                    except Exception:
                        pass  # a prefetch hint only; np.asarray works

        # capture the caller's trace context NOW — contextvars do not
        # cross into the worker thread, and the flush span belongs to
        # the request whose segment scheduled it
        from deap_tpu.telemetry import tracing
        trace_ctx = tracing.current()

        def work():
            t0 = time.perf_counter()
            try:
                snap = jax.tree_util.tree_unflatten(treedef, leaves)
                self.last_path = ckpt.save(step, snap, meta=meta)
            except BaseException as e:  # surfaced at the next wait()
                self._exc = e
                return
            if trace_ctx is not None:
                from deap_tpu.telemetry.journal import broadcast
                row = dict(name="checkpoint.flush", phase="checkpoint",
                           dur_s=round(time.perf_counter() - t0, 6),
                           trace_id=trace_ctx.trace_id,
                           span_id=tracing.new_span_id(),
                           parent_id=trace_ctx.span_id, step=int(step))
                if trace_ctx.request_id is not None:
                    row["request_id"] = trace_ctx.request_id
                if meta and meta.get("tenant_id"):
                    row["tenant_id"] = meta["tenant_id"]
                broadcast("trace_span", **row)

        self._thread = threading.Thread(
            target=work, name="deap-tpu-ckpt-writer", daemon=True)
        self._thread.start()
        return ckpt.path_for(step)

    @property
    def in_flight(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def wait(self) -> None:
        """Block until the in-flight write (if any) is durable; re-raise
        its exception on this thread."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._exc is not None:
            exc, self._exc = self._exc, None
            raise exc
