"""Co-evolution — multiple interacting populations in one jit step.

Counterpart of the reference's co-evolution examples (SURVEY.md §2.3 P7):

- **Competitive** (host-parasite, /root/reference/examples/coev/hillis.py:
  72-145): two populations with *opposite* fitness weights evaluated on
  index-paired encounters — ``fit = evaluate(host_i, parasite_i)`` is
  written to both, hosts minimising and parasites maximising
  (hillis.py:131-134, both assigned the same values).
- **Cooperative** (Potter & De Jong 2001, examples/coev/coop_base.py and
  the coop_niche/gen/adapt/evol ladder): each species evolves one *part*
  of a solution; an individual's fitness is computed by assembling it
  with the current *representatives* (best member) of every other
  species (coop_base.py:57-66 matchSetStrength over the assembled set).

Both are expressed as pure functions over tuples of
:class:`~deap_tpu.core.population.Population`; the species count is
static so a whole co-evolution step jit-compiles into one XLA program —
the tensor form of "multiple population tensors in one jit step,
cross-eval as batched pairing" (SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Callable, List, Sequence, Tuple

import jax
import jax.numpy as jnp

from deap_tpu.algorithms import var_and
from deap_tpu.core.population import Population, gather


def _as2d(values: jnp.ndarray) -> jnp.ndarray:
    return values[:, None] if values.ndim == 1 else values


def _rep(pop: Population):
    """Current representative = best member's genome
    (``toolbox.get_best`` = selBest k=1, coop_base.py:104)."""
    i = pop.best_index()
    return jax.tree_util.tree_map(lambda a: a[i], pop.genomes)


# ---------------------------------------------------------------- competitive ----

def competitive_eval(hosts: Population, parasites: Population,
                     eval_pair: Callable) -> Tuple[Population, Population]:
    """Index-paired encounter evaluation (hillis.py:131-134): row i of
    each population meet; the raw outcome is written to both sides, whose
    opposite ``FitnessSpec`` weights make one minimise and the other
    maximise. Every pair re-fights — the reference re-evaluates all
    pairs each generation (hillis.py:147-149), since selection reshuffles
    who faces whom."""
    values = _as2d(jax.vmap(eval_pair)(hosts.genomes, parasites.genomes))
    return hosts.with_fitness(values), parasites.with_fitness(values)


def competitive_step(key: jax.Array, hosts: Population,
                     parasites: Population, htoolbox, ptoolbox,
                     eval_pair: Callable, h_cxpb: float = 0.5,
                     h_mutpb: float = 0.3, p_cxpb: float = 0.5,
                     p_mutpb: float = 0.3,
                     ) -> Tuple[Population, Population]:
    """One Hillis generation (hillis.py:139-152): select + varAnd each
    side independently, then paired re-evaluation."""
    k_hs, k_hv, k_ps, k_pv = jax.random.split(key, 4)
    h_idx = htoolbox.select(k_hs, hosts.wvalues, hosts.size)
    p_idx = ptoolbox.select(k_ps, parasites.wvalues, parasites.size)
    hosts = var_and(k_hv, gather(hosts, h_idx), htoolbox, h_cxpb, h_mutpb)
    parasites = var_and(k_pv, gather(parasites, p_idx), ptoolbox,
                        p_cxpb, p_mutpb)
    return competitive_eval(hosts, parasites, eval_pair)


# --------------------------------------------------------------- cooperative ----

def coop_representatives(species: Sequence[Population]) -> List:
    """Representatives of every species (initially: their best members;
    the reference seeds them with random members before gen 0,
    coop_niche.py-style, then keeps the best)."""
    return [_rep(s) for s in species]


def coop_eval_species(i: int, pop: Population, reps: Sequence,
                      evaluate: Callable) -> Population:
    """Evaluate species ``i``: every member assembled with the other
    species' representatives. ``evaluate(i, genomes, reps) -> f32[n]``
    receives the *full* representative tuple; slot i is the member's own
    slot to substitute. All rows re-evaluate — representatives change
    between rounds, so cached fitness would be against stale partners
    (the reference re-evaluates whole species per round,
    coop_niche.py:80-81)."""
    values = _as2d(evaluate(i, pop.genomes, tuple(reps)))
    return pop.with_fitness(values)


def match_counts(genomes: jnp.ndarray, targets: jnp.ndarray) -> jnp.ndarray:
    """Pairwise match strengths: ``out[i, t] = #{positions where genome i
    equals target t}`` — the batched form of ``matchStrength``
    (coop_base.py:44-47), one comparison tensor instead of |set|×|targets|
    Python loops."""
    return (genomes[:, None, :] == targets[None, :, :]).sum(-1).astype(jnp.float32)


def match_set_strength(i: int, genomes: jnp.ndarray, reps: Sequence,
                       targets: jnp.ndarray) -> jnp.ndarray:
    """Cooperative match-set fitness for species ``i`` (matchSetStrength,
    coop_base.py:57-66): each member is assembled with the *other*
    species' representatives; the set's strength on a target is the best
    member's match, and fitness is the mean over targets.

    ``genomes [n, L]``, ``reps`` = per-species representative genomes,
    ``targets [T, L]`` → ``f32[n]``.
    """
    rep_m = match_counts(jnp.stack(list(reps)), targets)      # [R, T]
    mask = jnp.arange(rep_m.shape[0])[:, None] != i
    other_best = jnp.where(mask, rep_m, -jnp.inf).max(0)      # [T]
    ind_m = match_counts(genomes, targets)                    # [n, T]
    return jnp.maximum(ind_m, other_best[None, :]).mean(-1)


def match_set_contributions(reps: Sequence, targets: jnp.ndarray) -> jnp.ndarray:
    """Per-species credit (matchSetContribution, coop_base.py:84-98):
    each target is claimed by the representative matching it best
    (first index wins ties, like the reference's strict ``>`` scan);
    a species' contribution is the mean claimed match strength. Used by
    the evolving-species ladder to decide extinction
    (coop_evol.py:130-140)."""
    rep_m = match_counts(jnp.stack(list(reps)), targets)      # [R, T]
    winner = jnp.argmax(rep_m, axis=0)                        # [T]
    claimed = rep_m.max(0)
    R = rep_m.shape[0]
    per_species = jnp.where(winner[None, :] == jnp.arange(R)[:, None],
                            claimed[None, :], 0.0)
    return per_species.sum(-1) / targets.shape[0]


def coop_step(key: jax.Array, species: Sequence[Population],
              reps: Sequence, toolboxes, evaluate: Callable,
              cxpb: float = 0.6, mutpb: float = 1.0,
              ) -> Tuple[List[Population], List]:
    """One cooperative generation: every species does select + varAnd +
    fitness against the *round-start* representative set; the new
    representatives all swap in together after the full round, matching
    the reference's two-phase loop (coop_niche.py:71-95 collects
    ``next_repr`` and assigns ``representatives`` after iterating all
    species). ``toolboxes`` is one shared toolbox or a per-species
    list."""
    species = list(species)
    next_reps = []
    for i in range(len(species)):
        tb = toolboxes[i] if isinstance(toolboxes, (list, tuple)) else toolboxes
        k_sel, k_var = jax.random.split(jax.random.fold_in(key, i))
        s = species[i]
        idx = tb.select(k_sel, s.wvalues, s.size)
        off = var_and(k_var, gather(s, idx), tb, cxpb, mutpb)
        off = coop_eval_species(i, off, reps, evaluate)
        species[i] = off
        next_reps.append(_rep(off))
    return species, next_reps
