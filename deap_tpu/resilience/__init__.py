"""Resilience layer — preemption-safe segmented runs, crash-consistent
checkpoints, transient-failure handling, and the fault-injection
harness that proves the recovery paths (docs/advanced/resilience.md).

Quick start::

    from deap_tpu.resilience import ResilientRun

    res = ResilientRun("ckpts/exp42", segment_len=100)
    pop, logbook, hof = res.ea_simple(key, pop, toolbox, 0.5, 0.2,
                                      ngen=10_000)

The run checkpoints every 100 generations; SIGTERM/SIGINT finish the
in-flight segment, save and raise :class:`Preempted`; re-invoking the
same call resumes from the newest valid checkpoint with bit-identical
results to an uninterrupted run.
"""

from deap_tpu.resilience.drain import DrainSignal
from deap_tpu.resilience.engine import (
    QUARANTINE_PENALTY,
    Preempted,
    ResilientRun,
    RetryPolicy,
    classify_error,
    quarantine_non_finite,
)
from deap_tpu.resilience.faultinject import (
    CorruptCheckpoint,
    DelaySegment,
    DropResponse,
    FailSegments,
    Fault,
    FaultPlan,
    InjectedCrash,
    InjectedDrop,
    InjectedTransient,
    KillAt,
    KillServiceAt,
    PreemptAt,
    TornWAL,
    corrupt_file,
    nan_inject_evaluate,
)

__all__ = [
    "DrainSignal",
    "QUARANTINE_PENALTY",
    "Preempted",
    "ResilientRun",
    "RetryPolicy",
    "classify_error",
    "quarantine_non_finite",
    "CorruptCheckpoint",
    "DelaySegment",
    "DropResponse",
    "FailSegments",
    "Fault",
    "FaultPlan",
    "InjectedCrash",
    "InjectedDrop",
    "InjectedTransient",
    "KillAt",
    "KillServiceAt",
    "PreemptAt",
    "TornWAL",
    "corrupt_file",
    "nan_inject_evaluate",
]
