"""ResilientRun — preemption-safe segmented execution of any loop family.

The compiled loops in :mod:`deap_tpu.algorithms` run their full
generation count inside one uninterruptible ``lax.scan``; on a
preemptible fleet a SIGTERM, an OOM, or a torn checkpoint kills the run
with no recovery path. This driver chunks a run into **segments** of k
generations: each segment is a ``lax.scan`` over a *slice* of the same
pre-split key array the monolithic loop would have scanned, the full
state pytree (population, strategy state, hall of fame, PRNG base key,
Meter carry including probe internals, stacked records) is checkpointed
between segments through the hardened
:class:`~deap_tpu.support.checkpoint.Checkpointer`, and resume from the
newest valid checkpoint is **bit-exact** against an uninterrupted run —
pinned for every loop family by ``tests/test_resilience.py``. (Peer JAX
EC frameworks — evosax, Kozax, PAPERS.md — offer no preemption-safe
resume at all; the scan-slice construction is what makes ours exact
rather than approximate.)

Three planes:

- **Segmented execution** — :class:`ResilientRun` methods mirror the
  loop signatures (:meth:`~ResilientRun.ea_simple`,
  :meth:`~ResilientRun.ea_mu_plus_lambda`,
  :meth:`~ResilientRun.ea_mu_comma_lambda`,
  :meth:`~ResilientRun.ea_generate_update`, the host-dispatch
  :meth:`~ResilientRun.gp_loop`, the epoch-driven
  :meth:`~ResilientRun.island_run`, and the batched
  :meth:`~ResilientRun.multirun` that checkpoints a whole packed
  run-axis batch as one state). SIGTERM/SIGINT set a flag; the
  in-flight segment finishes, the state is saved, a ``preempted`` event
  is journaled and :class:`Preempted` raised — the caller exits cleanly
  and the next invocation resumes where it stopped.
- **Crash-consistent checkpoints** — every segment boundary goes
  through ``Checkpointer.save`` (fsync-before-rename, per-leaf CRC32);
  resume goes through ``restore_latest`` (corrupt files skipped,
  journaled, fallback to the newest valid step). Checkpoint ``meta``
  carries the run id, so a resumed run journals ``resumed`` with
  ``resumed_from`` and ``telemetry/report.py`` stitches the segments
  into one timeline. Saves are **double-buffered** by default
  (:class:`~deap_tpu.support.checkpoint.AsyncCheckpointWriter`): the
  boundary state is snapshotted synchronously (immutable leaves +
  async device→host copy) and serialized/fsync'd by a background
  thread while the next segment computes — drained before the next
  boundary's write, before any ``Preempted`` raise, and before the
  drive returns, so durability and bit-exactness are unchanged while
  the segmented-run overhead drops under the tightened 1.5% gate
  (``bench.py --resilience``).
- **Failure handling** — segment execution is wrapped in transient
  -error classification (:func:`classify_error`) with bounded
  retry/backoff (:class:`RetryPolicy`); each retry is journaled as a
  ``degraded`` event, and a ``degrade_cb`` hook lets the caller shed
  load (e.g. halve an eval batch on ``RESOURCE_EXHAUSTED``) before the
  retry. Retries re-run the segment from its in-memory pre-segment
  state — a pure function of (state, keys), so a retried run stays
  bit-exact. :func:`quarantine_non_finite` guards the evaluation
  itself (see its docstring).

Deterministic fault plans for proving all of this live in
:mod:`deap_tpu.resilience.faultinject`.
"""

from __future__ import annotations

import os
import signal
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import algorithms as algos
# RetryPolicy lives in the stdlib-only deap_tpu.resilience.retry so the
# no-jax service client can reuse the policy; re-exported here unchanged.
from deap_tpu.resilience.retry import RetryPolicy
from deap_tpu.support.checkpoint import AsyncCheckpointWriter, Checkpointer
from deap_tpu.telemetry import tracing

__all__ = ["Preempted", "RetryPolicy", "ResilientRun", "classify_error",
           "quarantine_non_finite", "QUARANTINE_PENALTY"]


class Preempted(RuntimeError):
    """Raised after a SIGTERM/SIGINT was honoured: the in-flight
    segment finished, its checkpoint is on disk, the journal holds a
    ``preempted`` event. ``step`` is the checkpointed generation —
    re-invoking the same :class:`ResilientRun` call resumes there."""

    def __init__(self, step: int, path: str, signum: int):
        super().__init__(
            f"run preempted by signal {signum}; state for generation "
            f"{step} checkpointed at {path} — re-invoke to resume")
        self.step = step
        self.path = path
        self.signum = signum


#: substrings of error messages classified as retry-worthy transients
#: (XLA runtime + RPC vocabulary; a fleet preemption or a wedged relay
#: surfaces as these, a shape error never does)
_TRANSIENT_MARKERS = ("DEADLINE_EXCEEDED", "UNAVAILABLE", "ABORTED",
                      "CANCELLED", "connection reset", "socket closed",
                      "failed to connect")
_RESOURCE_MARKERS = ("RESOURCE_EXHAUSTED", "out of memory", "OOM")


def classify_error(exc: BaseException) -> Optional[str]:
    """``"resource_exhausted"`` / ``"transient"`` for errors a retry
    (possibly after shedding load) can plausibly clear; ``None`` for
    deterministic failures that must propagate (a retry would just
    recompute the same exception)."""
    msg = f"{type(exc).__name__}: {exc}"
    if any(m.lower() in msg.lower() for m in _RESOURCE_MARKERS):
        return "resource_exhausted"
    if any(m.lower() in msg.lower() for m in _TRANSIENT_MARKERS):
        return "transient"
    return None




# --------------------------------------------------- non-finite guard ----

#: the sentinel a quarantined evaluation receives: worst-case but
#: finite, so max/argmax selection and running means stay well-defined
#: while the row can never win a selection tournament
QUARANTINE_PENALTY = -3.0e38


def quarantine_non_finite(evaluate: Callable,
                          penalty: float = QUARANTINE_PENALTY,
                          journal: bool = True) -> Callable:
    """Wrap a batched ``evaluate`` so NaN/Inf fitness rows are replaced
    by a worst-case ``penalty`` instead of silently poisoning max/argmax
    selection. jit/scan-safe. With ``journal=True`` a host callback
    broadcasts a ``quarantine`` event (row count) into any open run
    journal whenever a call quarantined anything. Pair it with
    :class:`~deap_tpu.telemetry.probes.QuarantineProbe` to Meter-count
    quarantined rows per generation and feed the HealthMonitor's
    ``non_finite`` alarm."""

    def _emit(n) -> None:
        n = int(n)
        if n:
            from deap_tpu.telemetry.journal import broadcast
            broadcast("quarantine", n=n)

    def wrapped(genomes):
        values = evaluate(genomes)
        bad = ~jnp.isfinite(values)
        out = jnp.where(bad, jnp.asarray(penalty, values.dtype), values)
        if journal:
            jax.debug.callback(_emit, jnp.sum(bad))
        return out

    wrapped.penalty = penalty
    wrapped.__wrapped__ = evaluate
    return wrapped


# ----------------------------------------------------- serving metrics ----

def _resolve_metrics(metrics):
    from deap_tpu.telemetry.metrics import resolve_registry
    return resolve_registry(metrics)


class _ResilienceInstruments:
    """The engine's Prometheus instruments, declared once per
    registry (create-or-get semantics make re-declaration safe)."""

    def __init__(self, registry):
        self.segment_s = registry.histogram(
            "deap_resilience_segment_seconds",
            "wall seconds per executed segment", labels=("algorithm",))
        self.checkpoint_s = registry.histogram(
            "deap_resilience_checkpoint_seconds",
            "wall seconds submitting/writing a boundary checkpoint",
            labels=("algorithm",))
        self.retries = registry.counter(
            "deap_resilience_retries_total",
            "transient segment retries", labels=("algorithm", "kind"))
        self.preemptions = registry.counter(
            "deap_resilience_preemptions_total",
            "honoured SIGTERM/SIGINT preemptions",
            labels=("algorithm",))


# ------------------------------------------------------------- driver ----

def _concat_stacked(parts):
    """Concatenate per-segment stacked scan outputs along generation
    axis 0 — the segmented twin of one scan's single stacked output."""
    if len(parts) == 1:
        return parts[0]
    return jax.tree_util.tree_map(
        lambda *xs: jnp.concatenate(xs, axis=0), *parts)


class _LoopSpec:
    """What a loop family gives the driver: build the gen-0 state, run
    generations [lo, hi) given that state, produce the final result.
    The state must be one checkpointable pytree that fully determines
    the rest of the run (together with the base key it contains)."""

    algorithm = "?"

    def init(self) -> Dict[str, Any]:
        raise NotImplementedError

    def on_resume(self, state: Dict[str, Any]) -> None:
        """Re-attach process-local context (telemetry declarations)
        after a cross-process resume."""

    def segment(self, state: Dict[str, Any], lo: int, hi: int
                ) -> Dict[str, Any]:
        raise NotImplementedError

    def finalize(self, state: Dict[str, Any]):
        raise NotImplementedError

    def stop_requested(self, state: Dict[str, Any]) -> bool:
        return False


class _ScanLoopSpec(_LoopSpec):
    """The three population loops + the ask-tell loop: one scanned step
    (built by the same ``algorithms.make_*_step`` factory the
    monolithic loop uses) scanned over slices of the pre-split keys."""

    def __init__(self, algorithm: str, step, key, carry, ngen: int,
                 telemetry, stats, record0=None, mstate0=None,
                 gen_offset: int = 1, build_result=None, plan=None):
        self.algorithm = algorithm
        self.step = step
        self.key = key
        self.carry0 = carry
        self.ngen = int(ngen)
        self.tel = telemetry
        self.stats = stats
        self.record0 = record0
        self.mstate0 = mstate0
        self.gen_offset = gen_offset  # pop loops journal gens 1..ngen,
        self.build_result = build_result  # ask-tell 0..ngen-1
        self.plan = plan
        # one jitted scan shared by every segment: an eager lax.scan
        # would re-trace per segment call (measured ~300 ms/segment at
        # pop=100k); under jit the executable is cached per xs shape —
        # two shapes total (full segment + short tail), bit-identical
        # output either way. With a plan, the scan goes through the
        # pjit-preferred compile wrapper and the carry is DONATED —
        # the per-segment population copy disappears (bench.py --mesh).
        # Both paths pass the costs.instrument AOT seam: an active
        # ProgramObservatory profiles every segment program
        # (`program_profile` journal events, hlo_drift alarms)
        scan_fn = lambda carry, xs: lax.scan(self.step, carry, xs)
        if plan is not None:
            self._scan = plan.compile(scan_fn, donate_argnums=(0,),
                                      label=f"resilient_{algorithm}")
        else:
            from deap_tpu.telemetry import costs
            self._scan = costs.instrument(
                jax.jit(scan_fn), label=f"resilient_{algorithm}")

    def init(self) -> Dict[str, Any]:
        # the gen-0 meter state doubles as the first element of the
        # donated carry: keep a safe copy for the post-run journal
        mstate0 = algos._retain(self.plan, self.mstate0)
        return {"gen": 0, "key": self.key, "carry": self.carry0,
                "records": [], "mrows": [], "record0": self.record0,
                "mstate0": mstate0}

    def on_resume(self, state) -> None:
        """Adapt the restored carry to THIS driver's telemetry
        configuration: a telemetry-on checkpoint resumed without
        telemetry drops the meter carry (and its stacked rows); a
        telemetry-off checkpoint resumed with telemetry grafts a fresh
        meter state on (metric history starts at the resume point —
        the evolutionary carry is untouched either way)."""
        carry = state["carry"]
        if self.tel is None and len(carry) == 3:
            state["carry"] = carry[:2]
            state["mrows"] = []
            state["mstate0"] = None
        elif self.tel is not None and len(carry) == 2:
            fresh = self.tel.meter.init()
            state["carry"] = carry + (fresh,)
            state["mrows"] = []
            state["mstate0"] = self.mstate0 if self.mstate0 is not None \
                else fresh
        if self.plan is not None:
            # the elastic reshard step: a checkpoint written on any
            # mesh re-commits to THIS process's plan (possibly a
            # different device count) — values are untouched, the
            # global program computes the same bits on the new layout
            state["carry"] = self.plan.place(state["carry"])

    def segment(self, state, lo, hi):
        if self.ngen:
            keys = jax.random.split(state["key"], self.ngen)
        else:  # ngen=0: an empty key array with the right key dtype
            keys = jax.random.split(state["key"], 1)[:0]
        if self.tel is None:
            carry, recs = self._scan(state["carry"], keys[lo:hi])
        else:
            xs = (keys[lo:hi],
                  jnp.arange(lo + self.gen_offset, hi + self.gen_offset))
            carry, (recs, mrows) = self._scan(state["carry"], xs)
            state["mrows"] = state["mrows"] + [mrows]
        state["carry"] = carry
        state["records"] = state["records"] + [recs]
        state["gen"] = hi
        return state

    def finalize(self, state):
        if not state["records"]:
            # ngen=0 (or a fully pre-completed resume of it): run a
            # zero-length scan so the empty stacked records/mrows exist
            # with the structure the logbook builder expects — exactly
            # what the monolithic loop's zero-length scan produces
            state = self.segment(dict(state), 0, 0)
        records = _concat_stacked(state["records"])
        if self.tel is not None:
            # pop loops (gen_offset 1) journal the pre-scan state as
            # the gen-0 row; the ask-tell loop starts at gen 0 with no
            # founder row — mirror the monolithic loops exactly
            initial = state["mstate0"] if self.gen_offset else None
            self.tel.end_run(
                self.algorithm,
                stacked_meter=_concat_stacked(state["mrows"]),
                initial=initial,
                gen0=self.gen_offset, ngen=self.ngen, segmented=True)
        return self.build_result(state, records)


class _GPLoopSpec(_LoopSpec):
    """The host-dispatch GP engine: per-generation keys are
    ``fold_in(key, gen)`` (stateless), so segmenting is just driving
    ``run.advance`` with checkpoints at segment boundaries."""

    algorithm = "gp_loop"

    def __init__(self, loop_run, key, genomes, ngen: int, plan=None):
        if getattr(loop_run, "init_state", None) is None:
            raise TypeError("gp_loop needs a run built by make_gp_loop")
        self.run = loop_run
        self.key = key
        self.genomes = genomes
        self.ngen = int(ngen)
        self.plan = plan

    def init(self):
        gp = self.run.init_state(self.key, self.genomes, self.ngen)
        return {"gen": gp["gen"], "key": self.key, "gp": gp}

    def on_resume(self, state):
        if self.plan is not None:
            for k in ("genomes", "depths", "fit"):
                state["gp"][k] = self.plan.place(state["gp"][k],
                                                fresh=False)
        if self.run.begin_telemetry is not None:
            n = int(jnp.asarray(state["gp"]["fit"]).shape[0])
            self.run.begin_telemetry(self.ngen, n)
            tel = self.run.telemetry
            if state["gp"].get("mstate") is None and tel is not None:
                # telemetry-off checkpoint resumed with telemetry:
                # graft a fresh meter carry (declared by
                # begin_telemetry above; metric history starts here)
                state["gp"]["mstate"] = tel.meter.init()

    def segment(self, state, lo, hi):
        gp = state["gp"]
        for _ in range(lo, hi):
            if gp["stopped_at"] is not None:
                break
            self.run.advance(state["key"], gp)
        state["gen"] = hi
        return state

    def finalize(self, state):
        return self.run.finalize(state["gp"], self.ngen)

    def stop_requested(self, state):
        return state["gp"]["stopped_at"] is not None


class _IslandSpec(_LoopSpec):
    """Epoch-driven island evolution: ``step`` from
    :func:`deap_tpu.parallel.make_island_step`; epoch keys are
    ``fold_in(key, epoch)``. ``reshard`` (e.g. a ``shard_population``
    partial) re-applies device placement to the restored pops."""

    algorithm = "island"

    def __init__(self, step, key, pops, n_epochs: int, telemetry=None,
                 reshard: Optional[Callable] = None,
                 record_rows: bool = True):
        self.step = step
        self.key = key
        self.pops = pops
        self.ngen = int(n_epochs)
        self.tel = telemetry
        self.reshard = reshard
        self.record_rows = record_rows

    def init(self):
        mstate = self.tel.meter.init() if self.tel is not None else None
        return {"gen": 0, "key": self.key, "pops": self.pops,
                "mstate": mstate}

    def on_resume(self, state):
        if self.reshard is not None:
            state["pops"] = self.reshard(state["pops"])

    def segment(self, state, lo, hi):
        pops, mstate = state["pops"], state["mstate"]
        for epoch in range(lo, hi):
            k = jax.random.fold_in(state["key"], epoch)
            if self.tel is None:
                pops = self.step(k, pops)
            else:
                pops, mstate = self.step(k, pops, mstate)
                if self.record_rows:
                    self.tel.record_row(mstate, epoch)
        state.update(pops=pops, mstate=mstate, gen=hi)
        return state

    def finalize(self, state):
        if self.tel is None:
            return state["pops"]
        return state["pops"], state["mstate"]


class _EngineBatchSpec(_LoopSpec):
    """A packed :class:`deap_tpu.serving.multirun.MultiRunEngine`
    batch (any family, including the GP and island run-axis engines)
    driven in checkpointed segments: the whole batch — every lane's
    carry, shadow, keys and per-lane record chunks — is ONE state
    pytree, so a preempted N-lane sweep resumes all N lanes from one
    boundary, bit-exactly."""

    def __init__(self, engine, keys, inits, ngens, hypers):
        self.algorithm = f"multirun_{engine.family}"
        self.engine = engine
        self.keys = list(keys)
        self.inits = list(inits)
        self.ngens = ngens
        self.hypers = hypers
        self.n = len(self.keys)
        self.horizon = max(ngens) if ngens else 0

    def init(self):
        eng = self.engine
        lanes = [eng.lane_init(k, p, g, h)
                 for k, p, g, h in zip(self.keys, self.inits,
                                       self.ngens, self.hypers)]
        batch = eng.pack(lanes, n_lanes=self.n, horizon=self.horizon)
        return {"gen": 0, "batch": batch,
                "records": [[] for _ in range(self.n)]}

    def on_resume(self, state):
        # re-pack the restored lanes through the engine so engine-side
        # pack hooks run on the concrete state (the GP engine grows its
        # union mask from the restored genomes; scan-family engines
        # round-trip unchanged) — the same unpack→pack path the
        # scheduler's evict/resume uses, pinned bit-exact by
        # tests/test_serving.py
        eng = self.engine
        n_real = int(state["batch"].get("n_real", self.n))
        lanes = [eng.unpack(state["batch"], i) for i in range(n_real)]
        state["batch"] = eng.pack(lanes, n_lanes=self.n,
                                  horizon=self.horizon)

    def segment(self, state, lo, hi):
        batch, seg = self.engine.advance(state["batch"], hi - lo)
        for i in range(self.n):
            chunk = self.engine.lane_records((seg,), i)
            if chunk is not None:
                state["records"][i] = state["records"][i] + [chunk]
        state["batch"] = batch
        state["gen"] = hi
        return state

    def finalize(self, state):
        eng = self.engine
        return [eng.lane_result(eng.unpack(state["batch"], i),
                                eng.concat_records(state["records"][i]))
                for i in range(self.n)]

    def stop_requested(self, state):
        return bool(self.engine.done(state["batch"]).all())


class ResilientRun:
    """Segmented, checkpointed, signal-aware driver for every loop
    family (see the module docstring). One instance drives one logical
    run; re-constructing it over the same checkpoint directory resumes
    that run::

        res = ResilientRun("ckpts/run7", segment_len=50, telemetry=tel)
        pop, logbook, hof = res.ea_simple(key, pop, tb, 0.5, 0.2,
                                          ngen=1000)
        # SIGTERM mid-run → Preempted raised after the in-flight
        # segment's checkpoint lands; the same call in the next
        # process continues at that segment, bit-exactly.

    :param checkpoints: a directory path or a pre-built
        :class:`~deap_tpu.support.checkpoint.Checkpointer`.
    :param segment_len: generations (epochs for islands) per segment —
        the preemption/checkpoint granularity.
    :param telemetry: optional RunTelemetry; segment/resume/degraded
        events land in its journal (otherwise they broadcast to any
        open journal).
    :param retry: a :class:`RetryPolicy` (default: 2 retries, 50 ms
        doubling backoff) for transient segment failures.
    :param degrade_cb: ``degrade_cb(kind, exc) -> description`` called
        before each retry of a ``resource_exhausted``/``transient``
        failure — the hook that halves an eval batch or shrinks a
        shard; its return value is journaled in the ``degraded`` event.
    :param handle_signals: install SIGTERM/SIGINT handlers for the
        duration of the drive (main thread only; off-thread drives
        skip installation silently).
    :param double_buffer: overlap each boundary checkpoint's
        serialize+fsync with the NEXT segment's compute: the state is
        snapshotted synchronously (tree-flattened immutable leaves +
        async device→host copy), written by a background thread, and
        the write is always drained before the next boundary's write,
        before a ``Preempted`` raise, and before the drive returns —
        so every durability and bit-exactness guarantee of the
        synchronous path is preserved while the resilience tax drops
        toward zero (``bench.py --resilience``, gate 1.5%). Forced off
        when a ``fault_plan`` is present: the chaos harness's event
        schedule (corrupt-after-save etc.) assumes the file exists the
        moment ``saved`` fires.
    :param fault_plan: a deterministic
        :class:`~deap_tpu.resilience.faultinject.FaultPlan` — test
        harness hook, inert in production.
    :param tenant_id: multi-tenant serving stamp: written into every
        checkpoint's v2 ``meta`` and required of any checkpoint this
        run resumes from (``restore_latest(tenant_id=...)``), so
        co-located or mis-pointed tenant directories can never
        cross-restore (see ``docs/advanced/serving.md``).
    :param plan: a :class:`deap_tpu.parallel.ShardingPlan` — the run
        executes mesh-natively (population sharded, segment scans
        donated) and checkpoints become **elastic**: per-shard v3
        leaves stamped with the writer's mesh, re-placed on THIS plan
        at resume, bit-exactly, even when the device counts differ
        (``docs/advanced/sharding.md``).
    :param trace_every: the **flight recorder** cadence: every k-th
        segment executes inside a real ``jax.profiler.trace`` capture
        written under ``trace_dir`` (one xplane trace per captured
        segment, journaled as ``flight_trace``), and every segment
        boundary journals a ``device_memory`` event — live device
        bytes by platform plus a ``jax.profiler
        .device_memory_profile`` pprof snapshot on the traced
        boundaries — so the HBM trajectory and a device timeline
        exist for any long run *after the fact*. ``None`` (default)
        disables both; tracing changes no computed result
        (``tests/test_costs.py`` pins it).
    :param trace_dir: flight-recorder artifact directory (default
        ``<checkpoint dir>/flight``).
    :param metrics: a :class:`~deap_tpu.telemetry.metrics
        .MetricsRegistry` (or ``True`` for the process default):
        segment/checkpoint wall seconds, retry and preemption counts
        are recorded as Prometheus instruments
        (``deap_resilience_*``) for the ``/metrics`` endpoint.
        ``None`` (default) records nothing.
    """

    def __init__(self, checkpoints, *, segment_len: int = 10,
                 keep: int = 3, telemetry=None,
                 retry: Optional[RetryPolicy] = None,
                 degrade_cb: Optional[Callable] = None,
                 handle_signals: bool = True,
                 double_buffer: bool = True, fault_plan=None,
                 run_id: Optional[str] = None,
                 tenant_id: Optional[str] = None,
                 plan=None, trace_every: Optional[int] = None,
                 trace_dir: Optional[str] = None, metrics=None):
        if isinstance(checkpoints, Checkpointer):
            self.ckpt = checkpoints
        else:
            self.ckpt = Checkpointer(str(checkpoints), keep=keep)
        if segment_len == "auto":
            # dispatch-tuner ladder (env DEAP_TPU_TUNE_SEGMENT_LEN →
            # cached winner → 10); the winner itself is probed and
            # persisted out of band by ``bench.py --tuning``'s
            # segment-length sweep, since an inline probe would need a
            # whole segmented run in hand
            from deap_tpu import tuning
            segment_len = tuning.resolve_int("segment_len", default=10,
                                             program="resilient_scan")
        if segment_len < 1:
            raise ValueError("segment_len must be >= 1")
        self.segment_len = int(segment_len)
        self.telemetry = telemetry
        self.retry = retry if retry is not None else RetryPolicy()
        self.degrade_cb = degrade_cb
        self.handle_signals = bool(handle_signals)
        self.fault_plan = fault_plan
        # chaos plans fire on 'saved' with the path in hand — only the
        # synchronous save satisfies that contract
        self.double_buffer = bool(double_buffer) and fault_plan is None
        if run_id is None and telemetry is not None:
            run_id = telemetry.journal.run_id
        self.run_id = run_id or hex(int(time.time() * 1e6))[2:]
        # multi-tenant serving: stamp every checkpoint with the owning
        # tenant and restore only checkpoints carrying that stamp
        # (Checkpointer.restore_latest(tenant_id=...)) — a mis-pointed
        # checkpoint directory resumes nothing instead of resuming
        # someone else's run
        self.tenant_id = tenant_id
        # mesh-native sharding plan (deap_tpu.parallel.ShardingPlan):
        # populations are placed on the plan's mesh, segment scans
        # compile through the plan's donating wrapper, checkpoints
        # store per-shard leaves (format v3) stamped with the mesh, and
        # resume re-places the restored state on THIS plan — which may
        # have a different device count than the writer's (elastic
        # resume; journaled as ``elastic_resume``)
        self.plan = plan
        # flight recorder: every k-th segment runs under a real
        # profiler trace; every boundary journals a device-memory
        # sample — artifacts land under trace_dir, the journal carries
        # their paths (see the trace_every docstring above)
        if trace_every is not None and int(trace_every) < 1:
            raise ValueError("trace_every must be >= 1")
        self.trace_every = int(trace_every) if trace_every else None
        self.trace_dir = (str(trace_dir) if trace_dir is not None
                          else os.path.join(self.ckpt.directory,
                                            "flight"))
        self._metrics = _resolve_metrics(metrics)
        self._minst = (_ResilienceInstruments(self._metrics)
                       if self._metrics is not None else None)
        self.preempt_requested = False
        self._preempt_signum: Optional[int] = None
        self.resumed_from: Optional[str] = None
        self.last_step: Optional[int] = None

    # ------------------------------------------------------ loop entries ----

    def ea_simple(self, key, pop, toolbox, cxpb, mutpb, ngen, *,
                  stats=None, halloffame_size=0, probes=()):
        tel = self._begin_pop("ea_simple", probes, ngen=ngen,
                              n=pop.size, cxpb=cxpb, mutpb=mutpb)
        step = algos.make_ea_simple_step(toolbox, cxpb, mutpb, stats,
                                         tel, plan=self.plan)
        return self._drive_pop("ea_simple", step, key, pop, toolbox,
                               ngen, stats, halloffame_size, tel)

    def ea_mu_plus_lambda(self, key, pop, toolbox, mu, lambda_, cxpb,
                          mutpb, ngen, *, stats=None, halloffame_size=0,
                          probes=()):
        assert cxpb + mutpb <= 1.0
        tel = self._begin_pop("ea_mu_plus_lambda", probes, ngen=ngen,
                              mu=mu, lambda_=lambda_, cxpb=cxpb,
                              mutpb=mutpb)
        step = algos.make_ea_mu_plus_lambda_step(
            toolbox, mu, lambda_, cxpb, mutpb, stats, tel,
            plan=self.plan)
        return self._drive_pop("ea_mu_plus_lambda", step, key, pop,
                               toolbox, ngen, stats, halloffame_size,
                               tel)

    def ea_mu_comma_lambda(self, key, pop, toolbox, mu, lambda_, cxpb,
                           mutpb, ngen, *, stats=None,
                           halloffame_size=0, probes=()):
        assert lambda_ >= mu and cxpb + mutpb <= 1.0
        tel = self._begin_pop("ea_mu_comma_lambda", probes, ngen=ngen,
                              mu=mu, lambda_=lambda_, cxpb=cxpb,
                              mutpb=mutpb)
        step = algos.make_ea_mu_comma_lambda_step(
            toolbox, mu, lambda_, cxpb, mutpb, stats, tel,
            plan=self.plan)
        return self._drive_pop("ea_mu_comma_lambda", step, key, pop,
                               toolbox, ngen, stats, halloffame_size,
                               tel)

    def ea_generate_update(self, key, state, toolbox, ngen, spec, *,
                           stats=None, halloffame_size=0, probes=()):
        if self.plan is not None:
            state = self.plan.place(state)
        lam, hof = algos._generate_update_init(toolbox, state, spec,
                                               halloffame_size)
        tel = self.telemetry
        algos._check_probes(probes, tel)
        mstate0 = None
        if tel is not None:
            tel.begin_run("ea_generate_update", toolbox,
                          declare=algos._tel_declare, probes=probes,
                          ngen=ngen, lambda_=lam, resilient=True)
            mstate0 = tel.meter.init()
        step = algos.make_ea_generate_update_step(toolbox, spec, lam,
                                                  stats, tel,
                                                  plan=self.plan)
        carry0 = ((state, hof) if tel is None
                  else (state, hof, mstate0))

        def build_result(st, records):
            logbook = algos._build_gu_logbook(records, stats)
            carry = st["carry"]
            return carry[0], logbook, carry[1]

        loop = _ScanLoopSpec("ea_generate_update", step, key, carry0,
                             ngen, tel, stats, mstate0=mstate0,
                             gen_offset=0, build_result=build_result,
                             plan=self.plan)
        return self._drive(loop, ngen)

    def gp_loop(self, loop_run, key, genomes, ngen):
        """Drive a :func:`deap_tpu.gp.loop.make_gp_loop` engine in
        segments; returns its usual result dict."""
        return self._drive(_GPLoopSpec(loop_run, key, genomes, ngen,
                                       plan=self.plan),
                           ngen)

    def island_run(self, step, key, pops, n_epochs, *,
                   reshard: Optional[Callable] = None,
                   record_rows: bool = True):
        """Drive a :func:`deap_tpu.parallel.make_island_step` epoch
        step for ``n_epochs`` (epoch keys ``fold_in(key, epoch)``).
        Returns final pops — ``(pops, mstate)`` when the step was built
        with telemetry. ``reshard`` re-applies device placement to a
        restored population (mesh runs); with a ``plan`` it defaults to
        the plan's own placement, which is what makes the restore
        *elastic* — the step must then be built with the same plan."""
        if reshard is None and self.plan is not None:
            reshard = self.plan.place
        if self.plan is not None:
            pops = self.plan.place(pops)
        return self._drive(
            _IslandSpec(step, key, pops, n_epochs,
                        telemetry=self.telemetry, reshard=reshard,
                        record_rows=record_rows),
            n_epochs)

    def multirun(self, engine, keys, inits, ngen, hyper=None):
        """Drive a packed :class:`deap_tpu.serving.multirun
        .MultiRunEngine` batch — any family, including the GP
        (:class:`~deap_tpu.serving.gp_multirun.GpMultiRunEngine`) and
        island run-axis engines — in checkpointed segments. ``ngen``
        and ``hyper`` broadcast like
        :func:`deap_tpu.serving.multirun.multirun`'s; returns the same
        per-lane solo-format result list, bit-identically, with the
        whole batch checkpointed as one state at every boundary."""
        n = len(keys)
        if len(inits) != n:
            raise ValueError("len(inits) != len(keys)")
        ngens = [int(g) for g in
                 (ngen if isinstance(ngen, (list, tuple))
                  else [ngen] * n)]
        hypers = (list(hyper) if isinstance(hyper, (list, tuple))
                  else [hyper] * n)
        if len(ngens) != n or len(hypers) != n:
            raise ValueError("ngen/hyper lists must match len(keys)")
        spec = _EngineBatchSpec(engine, keys, inits, ngens, hypers)
        return self._drive(spec, max(ngens) if ngens else 0)

    # -------------------------------------------------------- pop plumbing ----

    def _begin_pop(self, algorithm, probes, **params):
        tel = self.telemetry
        algos._check_probes(probes, tel)
        if tel is not None:
            tel.begin_run(algorithm, None, declare=algos._tel_declare,
                          probes=probes, resilient=True, **params)
        return tel

    def _drive_pop(self, algorithm, step, key, pop, toolbox, ngen,
                   stats, halloffame_size, tel):
        if self.plan is not None:
            pop = self.plan.place(pop)
        pop, hof, record0 = algos._pop_loop_init(pop, toolbox,
                                                 halloffame_size, stats)
        mstate0 = None
        if tel is not None:
            mstate0 = algos._tel_measure(tel, tel.meter.init(),
                                         record0["nevals"], pop,
                                         jnp.int32(0))
        carry0 = (pop, hof) if tel is None else (pop, hof, mstate0)

        def build_result(st, records):
            logbook = algos._build_logbook(st["record0"], records,
                                           stats)
            carry = st["carry"]
            return carry[0], logbook, carry[1]

        loop = _ScanLoopSpec(algorithm, step, key, carry0, ngen, tel,
                             stats, record0=record0, mstate0=mstate0,
                             gen_offset=1, build_result=build_result,
                             plan=self.plan)
        return self._drive(loop, ngen)

    # ----------------------------------------------------------- the drive ----

    def _journal_event(self, kind: str, **payload) -> None:
        payload.setdefault("run_id", self.run_id)
        if self.telemetry is not None:
            self.telemetry.journal.event(kind, **payload)
        else:
            from deap_tpu.telemetry.journal import broadcast
            broadcast(kind, **payload)

    def _fault(self, event: str, **ctx) -> None:
        if self.fault_plan is not None:
            self.fault_plan.fire(event, ckpt=self.ckpt, run=self, **ctx)

    def _drive(self, spec: _LoopSpec, total: int):
        total = int(total)
        resumed = self.ckpt.restore_latest(tenant_id=self.tenant_id)
        cur_mesh = (self.plan.describe() if self.plan is not None
                    else None)
        if resumed is not None:
            step0, state = resumed
            meta = state.get("_resilience", {})
            if meta.get("algorithm") not in (None, spec.algorithm):
                raise ValueError(
                    f"checkpoint dir {self.ckpt.directory} holds a "
                    f"{meta.get('algorithm')!r} run; refusing to resume "
                    f"it as {spec.algorithm!r}")
            self.resumed_from = meta.get("run_id")
            saved_mesh = meta.get("mesh")
            spec.on_resume(state)
            self._journal_event("resumed", algorithm=spec.algorithm,
                                step=step0,
                                resumed_from=self.resumed_from)
            if saved_mesh != cur_mesh and (saved_mesh or cur_mesh):
                # the checkpoint was written on a different mesh than
                # this process runs: the reshard in on_resume makes the
                # resume ELASTIC — journal it so the timeline shows
                # where the device count changed
                self._journal_event(
                    "elastic_resume", algorithm=spec.algorithm,
                    step=step0, from_mesh=saved_mesh, to_mesh=cur_mesh)
        else:
            state = spec.init()
            state["_resilience"] = {"algorithm": spec.algorithm,
                                    "run_id": self.run_id,
                                    "ngen": total}
            self._journal_event("segments_begin",
                                algorithm=spec.algorithm, ngen=total,
                                segment_len=self.segment_len)
        state["_resilience"]["run_id"] = self.run_id
        state["_resilience"]["mesh"] = cur_mesh

        # donated carries are rewritten in place by the NEXT segment's
        # compute: the snapshot must be materialised on the driver
        # thread before that dispatch, not read asynchronously under it
        writer = (AsyncCheckpointWriter(
            materialize=self.plan is not None and self.plan.donate)
            if self.double_buffer else None)
        try:
            with self._signals():
                gen = int(state["gen"])
                seg_i = 0  # segments executed by THIS drive — the
                #            flight-recorder cadence counter
                while gen < total and not spec.stop_requested(state):
                    hi = min(gen + self.segment_len, total)
                    self._fault("segment_start", lo=gen, hi=hi)
                    t_seg = time.perf_counter()
                    self._last_trace_dir = None
                    state = self._flight_segment(spec, state, gen, hi,
                                                 seg_i)
                    seg_s = time.perf_counter() - t_seg
                    if self._minst is not None:
                        self._minst.segment_s.observe(
                            seg_s, algorithm=spec.algorithm)
                    # trace-plane segment span (no-op outside a traced
                    # request); a flight-recorded segment links its
                    # xplane dir so the waterfall points straight at
                    # the device timeline
                    tracing.emit_current(
                        "segment.run", seg_s, phase="device",
                        lo=gen, hi=hi,
                        algorithm=spec.algorithm,
                        links=([{"xplane_dir": self._last_trace_dir}]
                               if self._last_trace_dir else None))
                    self._fault("segment_end", lo=gen, hi=hi)
                    meta = dict(state["_resilience"], step=hi)
                    if self.tenant_id is not None:
                        meta["tenant_id"] = self.tenant_id
                    t_ck = time.perf_counter()
                    if writer is not None:
                        # double-buffered: snapshot now, write in the
                        # background; submit() first drains the PREVIOUS
                        # boundary's write, which by then has overlapped
                        # with this whole segment's compute
                        path = writer.submit(self.ckpt, hi, state,
                                             meta=meta)
                    else:
                        path = self.ckpt.save(hi, state, meta=meta)
                    ck_s = time.perf_counter() - t_ck
                    if self._minst is not None:
                        self._minst.checkpoint_s.observe(
                            ck_s, algorithm=spec.algorithm)
                    # for async saves this is the snapshot+drain cost
                    # on the driver; the background write lands as its
                    # own checkpoint.flush span from the writer thread
                    tracing.emit_current("checkpoint", ck_s,
                                         phase="checkpoint",
                                         step=hi,
                                         async_save=writer is not None)
                    self.last_step = hi
                    self._journal_event("segment",
                                        algorithm=spec.algorithm,
                                        lo=gen, hi=hi, path=path,
                                        async_save=writer is not None)
                    self._record_memory(hi, seg_i)
                    self._fault("saved", lo=gen, hi=hi, path=path)
                    gen = hi
                    seg_i += 1
                    if self.preempt_requested:
                        if writer is not None:
                            writer.wait()  # durable before we claim so
                        if self._minst is not None:
                            self._minst.preemptions.inc(
                                algorithm=spec.algorithm)
                        self._journal_event(
                            "preempted", algorithm=spec.algorithm,
                            step=gen, signum=self._preempt_signum)
                        raise Preempted(gen, path,
                                        self._preempt_signum or 0)
            if writer is not None:
                writer.wait()  # surface any background write error
        except BaseException:
            if writer is not None:
                try:  # the final good write should still land
                    writer.wait()
                except Exception as e:
                    self._journal_event(
                        "checkpoint_write_failed", error=repr(e)[:300])
            raise
        return spec.finalize(state)

    # ---------------------------------------------------- flight recorder ----

    def _flight_segment(self, spec, state, lo, hi, seg_i: int):
        """Run one segment, inside a real ``jax.profiler.trace``
        capture when the flight-recorder cadence says so. The traced
        segment is synced before the capture closes (dispatch is
        async — an unsynced exit would truncate the device timeline);
        syncing forces completion but changes no computed value."""
        if self.trace_every is None or seg_i % self.trace_every:
            return self._run_segment(spec, state, lo, hi)
        from deap_tpu.support.profiling import sync

        tdir = os.path.join(self.trace_dir, f"seg_{lo:06d}")
        try:
            os.makedirs(tdir, exist_ok=True)
            tracer = jax.profiler.trace(tdir)
            tracer.__enter__()
        except Exception as e:
            # a wedged profiler must never take down the run it
            # observes: journal, run the segment untraced
            self._journal_event("flight_trace_error",
                                error=repr(e)[:200])
            return self._run_segment(spec, state, lo, hi)
        try:
            state = self._run_segment(spec, state, lo, hi)
            sync([leaf for leaf in jax.tree_util.tree_leaves(state)
                  if isinstance(leaf, jax.Array)
                  and not leaf.is_deleted()])
        finally:
            try:
                tracer.__exit__(None, None, None)
            except Exception:
                pass
        self._journal_event("flight_trace", algorithm=spec.algorithm,
                            lo=lo, hi=hi, dir=tdir)
        self._last_trace_dir = tdir  # span-link target for the drive
        return state

    def _record_memory(self, step: int, seg_i: int) -> None:
        """Boundary device-memory sample (flight recorder only): live
        bytes by platform every boundary, plus the full
        ``device_memory_profile`` pprof blob on traced boundaries."""
        if self.trace_every is None:
            return
        from deap_tpu.support.profiling import device_memory_snapshot

        path = None
        if seg_i % self.trace_every == 0:
            os.makedirs(self.trace_dir, exist_ok=True)
            path = os.path.join(self.trace_dir,
                                f"mem_{step:06d}.pprof.gz")
        snap = device_memory_snapshot(path)
        self._journal_event("device_memory", step=step, **snap)

    def _run_segment(self, spec, state, lo, hi):
        attempt = 0
        while True:
            try:
                self._fault("segment_attempt", lo=lo, hi=hi,
                            attempt=attempt)
                return spec.segment(state, lo, hi)
            except Exception as exc:
                kind = classify_error(exc)
                if kind is not None and self._state_buffers_lost(state):
                    # a donating plan dispatched the segment before it
                    # failed: the pre-segment carry buffers are gone,
                    # so an in-memory retry would read deleted arrays —
                    # fail fatally (a re-invocation resumes from the
                    # last checkpoint instead)
                    kind = None
                if kind is None or attempt >= self.retry.max_retries:
                    self._journal_event(
                        "segment_failed", algorithm=spec.algorithm,
                        lo=lo, hi=hi, attempt=attempt,
                        error=repr(exc)[:300],
                        error_kind=kind or "fatal")
                    raise
                action = None
                if self.degrade_cb is not None:
                    action = self.degrade_cb(kind, exc)
                if self._minst is not None:
                    self._minst.retries.inc(algorithm=spec.algorithm,
                                            kind=kind)
                delay = self.retry.delay(attempt)
                self._journal_event(
                    "degraded", algorithm=spec.algorithm, lo=lo, hi=hi,
                    error_kind=kind, attempt=attempt,
                    backoff_s=round(delay, 4),
                    error=repr(exc)[:300],
                    **({"action": action} if action else {}))
                self.retry.sleep(delay)
                attempt += 1

    def _state_buffers_lost(self, state) -> bool:
        """True when a donating plan already consumed (deleted) any of
        the in-memory state's device buffers — retrying from that state
        is impossible; the run must fail to its checkpoint instead."""
        if self.plan is None or not self.plan.donate:
            return False
        for leaf in jax.tree_util.tree_leaves(state):
            if isinstance(leaf, jax.Array):
                try:
                    if leaf.is_deleted():
                        return True
                except Exception:
                    pass
        return False

    # ------------------------------------------------------------- signals ----

    def _signals(self):
        run = self

        class _Guard:
            def __enter__(self):
                self.prev = {}
                if (not run.handle_signals
                        or threading.current_thread()
                        is not threading.main_thread()):
                    return self

                def handler(signum, frame):
                    run.preempt_requested = True
                    run._preempt_signum = signum

                for sig in (signal.SIGTERM, signal.SIGINT):
                    self.prev[sig] = signal.signal(sig, handler)
                return self

            def __exit__(self, *exc):
                for sig, h in self.prev.items():
                    signal.signal(sig, h)

        return _Guard()
