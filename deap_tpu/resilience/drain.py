"""DrainSignal — SIGTERM/SIGINT → one graceful-drain callback.

The service-plane sibling of :class:`~deap_tpu.resilience.engine.
ResilientRun`'s signal guard: where the resilient runner converts a
signal into "finish the in-flight segment, checkpoint, raise
:class:`Preempted`", a *server* converts it into "stop admitting,
finish the in-flight segment, checkpoint every resident tenant, exit"
— the :meth:`deap_tpu.serving.service.EvolutionService.drain` path.
This helper owns only the signal plumbing, with the same rules the
engine learned:

- install from the **main thread only** (CPython delivers signals
  there; installing elsewhere raises ``ValueError`` — surfaced, not
  swallowed, unless ``strict=False``);
- the handler body is minimal and reentrancy-safe: it sets a flag and
  invokes the callback **once** (a second SIGTERM during a slow drain
  doesn't re-enter it) — so callbacks must themselves be
  non-blocking (``service.drain(wait=False)`` is);
- previous handlers are saved and restored by :meth:`uninstall` /
  context-manager exit, so a test harness's (or pytest's) own
  handlers survive.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Dict, Iterable, Optional

__all__ = ["DrainSignal"]


class DrainSignal:
    """Route ``signals`` (default SIGTERM + SIGINT) to ``callback``
    exactly once::

        ds = DrainSignal(lambda signum: service.drain(wait=False))
        with ds:                  # or ds.install() / ds.uninstall()
            serve_forever()
    """

    def __init__(self, callback: Callable[[int], None],
                 signals: Iterable[int] = (signal.SIGTERM,
                                           signal.SIGINT),
                 strict: bool = True):
        self.callback = callback
        self.signals = tuple(signals)
        self.strict = bool(strict)
        self.fired: Optional[int] = None  # signum that triggered
        self._prev: Dict[int, object] = {}
        self._installed = False

    def _handler(self, signum, frame):
        if self.fired is not None:
            return  # drain already in flight; stay quiet
        self.fired = signum
        self.callback(signum)

    def install(self) -> "DrainSignal":
        if self._installed:
            return self
        if threading.current_thread() is not threading.main_thread():
            if self.strict:
                raise RuntimeError(
                    "DrainSignal.install() must run on the main "
                    "thread (CPython delivers signals there)")
            return self
        for sig in self.signals:
            self._prev[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev.clear()
        self._installed = False

    def __enter__(self) -> "DrainSignal":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()
