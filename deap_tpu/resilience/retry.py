"""RetryPolicy — bounded, optionally jittered exponential backoff.

Factored out of :mod:`deap_tpu.resilience.engine` (which re-exports it
unchanged) into a **stdlib-only** module so the no-jax halves of the
service plane can reuse the exact same policy object: the
:class:`~deap_tpu.serving.client.ServiceClient` honours the server's
``Retry-After`` on 429/503 and backs off on connection errors with
this policy, and a submit box must never initialise an XLA backend
just to compute a backoff schedule (the same constraint that keeps
``serving/wire.py`` and ``telemetry/metrics.py`` import-light).

Jitter: retries synchronised across hundreds of clients re-collide on
every attempt (the thundering-herd failure mode of a service restart);
``jitter=0.5`` spreads each delay uniformly over ``[delay*(1-j),
delay*(1+j)]`` using the policy's own seeded ``random.Random`` — the
schedule stays deterministic per (seed, attempt sequence), which is
what lets chaos tests replay exact retry timelines.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional

__all__ = ["RetryPolicy"]


class RetryPolicy:
    """Bounded exponential backoff for transient failures.

    ``delay(attempt)`` is ``backoff_s * backoff_factor**attempt``
    clamped to ``max_backoff_s``, spread by ``jitter`` (fraction, 0 =
    deterministic). ``sleep`` is injectable so tests never wait."""

    def __init__(self, max_retries: int = 2, backoff_s: float = 0.05,
                 backoff_factor: float = 2.0, max_backoff_s: float = 5.0,
                 sleep: Callable[[float], None] = time.sleep,
                 jitter: float = 0.0, seed: Optional[int] = 0):
        self.max_retries = int(max_retries)
        self.backoff_s = float(backoff_s)
        self.backoff_factor = float(backoff_factor)
        self.max_backoff_s = float(max_backoff_s)
        self.sleep = sleep
        self.jitter = float(jitter)
        self._rng = random.Random(seed)

    def delay(self, attempt: int) -> float:
        base = min(self.backoff_s * self.backoff_factor ** attempt,
                   self.max_backoff_s)
        if not self.jitter:
            return base
        lo = base * (1.0 - self.jitter)
        hi = base * (1.0 + self.jitter)
        return min(lo + (hi - lo) * self._rng.random(),
                   self.max_backoff_s)
