"""Deterministic fault injection — the proof harness for the
resilience layer.

A :class:`FaultPlan` is a list of :class:`Fault` objects fired by
:class:`~deap_tpu.resilience.engine.ResilientRun` at well-defined
points of the drive (``segment_start`` / ``segment_attempt`` /
``segment_end`` / ``saved``), each carrying the segment bounds and the
live :class:`~deap_tpu.support.checkpoint.Checkpointer`. Every fault is
a pure function of (event, bounds, its own fire counter) — no clocks,
no RNG — so a chaos test replays the exact same failure schedule every
run, which is what lets ``tests/test_chaos.py`` pin *bit-exact*
recovery rather than "it eventually finished".

Catalogue:

- :class:`KillAt` — simulate a hard kill (OOM-killer, node loss) by
  raising :class:`InjectedCrash` at a generation boundary, before or
  after the segment's checkpoint lands. The test then resumes with a
  fresh driver, exactly like a rescheduled pod would.
- :class:`PreemptAt` — deliver a real ``SIGTERM`` to this process at a
  segment boundary; the driver's handler finishes the in-flight
  segment, saves, journals ``preempted`` and raises ``Preempted``.
- :class:`CorruptCheckpoint` — flip (or truncate to) bytes of the
  checkpoint file just written, emulating a torn/rotted snapshot; the
  CRC layer must detect it and fall back.
- :class:`FailSegments` — raise a classifiable transient error
  (``RESOURCE_EXHAUSTED`` by default) on the first ``times`` attempts
  of a segment, exercising retry/backoff/degrade.
- :func:`nan_inject_evaluate` — wrap an evaluator so chosen rows come
  back NaN, exercising the quarantine wrapper and the ``non_finite``
  alarm.

Service-shaped faults (ISSUE 12) — fired by the
:class:`~deap_tpu.serving.service.EvolutionService` ``fault_plan``
event stream (``step`` after every driver iteration, ``boundary``
inside the segment drain, ``http_response`` before a response is
written, ``wal_append`` after an admission-WAL record lands):

- :class:`DropResponse` — the network loses a response: the handler
  raises :class:`InjectedDrop`, the service closes the connection
  without replying — the client must retry, and only an idempotency
  key keeps the retry from admitting a twin job.
- :class:`DelaySegment` — wedge the driver thread for ``delay_s`` at a
  chosen step, the deterministic stand-in for a hung segment; the
  watchdog must notice (``driver_stall``), flip ``/healthz`` to 503
  and re-arm when the driver recovers.
- :class:`KillServiceAt` — ``SIGKILL`` this process at a chosen driver
  step or boundary: the real crash the admission WAL + checkpoint
  recovery path exists for. Only meaningful in a child process (the
  chaos harness, :mod:`deap_tpu.serving.chaos`).
- :class:`TornWAL` — tear the tail off the admission WAL right after a
  record lands (then optionally ``SIGKILL``), emulating a power cut
  mid-append; replay must drop exactly the torn (never-ACKed) record.
- :class:`CorruptResult` — silently corrupt a finishing tenant's raw
  result (one flipped byte in the first array leaf) at the service's
  ``result`` seam, BEFORE the wire encode. Every layer still reports
  success — journal, status, HTTP 200 — which is exactly the silent
  wrong-answer failure only the known-answer canary tenants
  (:mod:`deap_tpu.serving.canary`) can catch: the corrupted result's
  wire digest no longer matches the canary's precomputed reference.
- :class:`KillDuringHandoff` — ``SIGKILL`` the source driver at a
  chosen seam of the live-migration handshake
  (:mod:`deap_tpu.serving.migration` fires ``migration`` events at
  ``after_offer`` / ``before_adopted`` / ``before_transferred``):
  between offer-fsync and adoption-ACK is the exactly-once protocol's
  worst window, and the chaos tests pin that the tenant survives on
  exactly one driver with bit-identical digests no matter which seam
  the kill lands on.
"""

from __future__ import annotations

import os
import signal
import time
from typing import Any, List, Optional

import jax.numpy as jnp

__all__ = ["InjectedCrash", "InjectedTransient", "InjectedDrop",
           "InjectedReject", "InjectedCorruption", "Fault",
           "FaultPlan", "KillAt", "PreemptAt", "CorruptCheckpoint",
           "FailSegments", "DropResponse", "Reject429",
           "DelaySegment", "KillServiceAt", "KillDuringHandoff",
           "TornWAL", "CorruptResult", "nan_inject_evaluate",
           "corrupt_file", "corrupt_pytree"]


class InjectedCrash(RuntimeError):
    """A simulated hard kill — deliberately *not* classified transient,
    so the driver must not retry it (a real SIGKILL retries nothing)."""


class InjectedTransient(RuntimeError):
    """A simulated infrastructure error whose message carries a
    transient marker (``RESOURCE_EXHAUSTED`` etc.) so
    :func:`~deap_tpu.resilience.engine.classify_error` retries it."""


class InjectedDrop(RuntimeError):
    """A simulated lost response: the service's HTTP handler catches
    this and closes the connection without writing a reply — the
    client-visible shape of a network partition mid-response."""


class InjectedReject(RuntimeError):
    """A simulated overload rejection: the service's HTTP handler
    catches this and answers 429 + ``Retry-After`` *instead of* the
    real response — the deterministic 429 source behind the load
    generator's thundering-herd retry-storm model (every rejected
    client backs off the same ``Retry-After`` and returns at once)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)


class InjectedCorruption(RuntimeError):
    """A simulated silent wrong answer: the service's boundary handler
    catches this around the result handoff and perturbs the finishing
    tenant's raw result (:func:`corrupt_pytree`) *before* the wire
    encode — so every success signal still fires and only a
    known-answer digest compare can tell."""


class Fault:
    """One scheduled failure. Subclasses implement :meth:`fire`;
    ``fired`` counts activations so plans stay single-shot by
    default."""

    def __init__(self):
        self.fired = 0

    def fire(self, event: str, **ctx) -> None:  # pragma: no cover
        raise NotImplementedError


class FaultPlan:
    """An ordered set of faults sharing the driver's event stream."""

    def __init__(self, faults: Optional[List[Fault]] = None):
        self.faults = list(faults or [])
        self.log: List[dict] = []

    def add(self, fault: Fault) -> "FaultPlan":
        self.faults.append(fault)
        return self

    def fire(self, event: str, **ctx) -> None:
        self.log.append({"event": event,
                         **{k: v for k, v in ctx.items()
                            if isinstance(v, (int, str, float))}})
        for f in self.faults:
            f.fire(event, **ctx)


class KillAt(Fault):
    """Raise :class:`InjectedCrash` when the drive crosses generation
    ``gen`` — ``when='before_save'`` kills after the segment computed
    but before its checkpoint landed (the worst crash window: that
    segment's work is lost and resume replays it), ``'after_save'``
    kills right after the checkpoint landed."""

    def __init__(self, gen: int, when: str = "before_save"):
        super().__init__()
        if when not in ("before_save", "after_save"):
            raise ValueError(f"unknown when={when!r}")
        self.gen = int(gen)
        self.when = when

    def fire(self, event: str, **ctx) -> None:
        want = "segment_end" if self.when == "before_save" else "saved"
        if event == want and not self.fired and ctx["hi"] >= self.gen:
            self.fired += 1
            raise InjectedCrash(
                f"injected hard kill at gen {ctx['hi']} ({self.when})")


class PreemptAt(Fault):
    """Deliver a real ``SIGTERM`` to this process when the drive
    crosses generation ``gen`` — exercises the actual signal-handler
    path: the driver finishes the segment, saves, raises
    ``Preempted``."""

    def __init__(self, gen: int, signum: int = signal.SIGTERM):
        super().__init__()
        self.gen = int(gen)
        self.signum = signum

    def fire(self, event: str, **ctx) -> None:
        if event == "segment_end" and not self.fired \
                and ctx["hi"] >= self.gen:
            self.fired += 1
            signal.raise_signal(self.signum)


def corrupt_file(path: str, mode: str = "flip", nbytes: int = 16,
                 offset: int = -256) -> None:
    """Deterministically damage a file in place. ``flip`` XORs
    ``nbytes`` bytes starting at ``offset`` (negative = from the end);
    ``truncate`` cuts the file to ``offset`` bytes."""
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(0, size + offset if offset < 0 else offset))
        return
    if mode != "flip":
        raise ValueError(f"unknown mode={mode!r}")
    start = size + offset if offset < 0 else offset
    start = max(0, min(start, max(0, size - nbytes)))
    with open(path, "r+b") as f:
        f.seek(start)
        chunk = f.read(nbytes)
        f.seek(start)
        f.write(bytes(b ^ 0xA5 for b in chunk))


class CorruptCheckpoint(Fault):
    """After the checkpoint for generation ``gen`` lands, damage its
    bytes (``mode`` as in :func:`corrupt_file`) — the restore path must
    detect the CRC mismatch and fall back to the newest valid older
    step. ``then_crash=True`` also raises :class:`InjectedCrash` so the
    test resumes from the damaged directory."""

    def __init__(self, gen: int, mode: str = "flip",
                 then_crash: bool = True):
        super().__init__()
        self.gen = int(gen)
        self.mode = mode
        self.then_crash = then_crash

    def fire(self, event: str, **ctx) -> None:
        if event == "saved" and not self.fired and ctx["hi"] >= self.gen:
            self.fired += 1
            corrupt_file(ctx["path"], mode=self.mode)
            if self.then_crash:
                raise InjectedCrash(
                    f"injected crash after corrupting {ctx['path']}")


class FailSegments(Fault):
    """Fail the first ``times`` attempts of the segment starting at
    ``lo`` with a transient error (``marker`` lands in the message so
    the classifier sees it) — retry/backoff must absorb the failures
    and the result must stay bit-exact."""

    def __init__(self, lo: int, times: int = 2,
                 marker: str = "RESOURCE_EXHAUSTED"):
        super().__init__()
        self.lo = int(lo)
        self.times = int(times)
        self.marker = marker

    def fire(self, event: str, **ctx) -> None:
        if event == "segment_attempt" and ctx["lo"] == self.lo \
                and self.fired < self.times:
            self.fired += 1
            raise InjectedTransient(
                f"{self.marker}: injected transient failure "
                f"(attempt {ctx['attempt']})")


# ---------------------------------------------- service-shaped faults ----


class DropResponse(Fault):
    """Drop the response of the next ``times`` requests whose route
    contains ``route_substr`` — fired on the service's
    ``http_response`` event *after* the request was processed, so the
    server-side effect (an accepted job, a durable WAL record) stands
    while the client never learns of it. The retry that follows is
    exactly the duplicate-submit case idempotency keys exist for."""

    def __init__(self, route_substr: str, times: int = 1):
        super().__init__()
        self.route_substr = str(route_substr)
        self.times = int(times)

    def fire(self, event: str, **ctx) -> None:
        if event == "http_response" and self.fired < self.times \
                and self.route_substr in str(ctx.get("route", "")):
            self.fired += 1
            raise InjectedDrop(
                f"injected response drop on {ctx.get('route')} "
                f"(#{self.fired}/{self.times})")


class Reject429(Fault):
    """Answer the next ``times`` requests whose route contains
    ``route_substr`` with 429 + ``Retry-After: retry_after_s`` —
    fired on the service's ``http_response`` event. Like
    :class:`DropResponse` it fires *after* processing (the request's
    server-side effects stand), so pair it with submit idempotency
    keys; its value is determinism — the retry storm hits exactly
    when scheduled, independent of real load."""

    def __init__(self, route_substr: str, times: int = 1,
                 retry_after_s: float = 1.0):
        super().__init__()
        self.route_substr = str(route_substr)
        self.times = int(times)
        self.retry_after_s = float(retry_after_s)

    def fire(self, event: str, **ctx) -> None:
        if event == "http_response" and self.fired < self.times \
                and self.route_substr in str(ctx.get("route", "")):
            self.fired += 1
            raise InjectedReject(
                f"injected 429 on {ctx.get('route')} "
                f"(#{self.fired}/{self.times})",
                retry_after_s=self.retry_after_s)


class DelaySegment(Fault):
    """Wedge the driver thread for ``delay_s`` seconds at driver step
    ``step`` (event ``step``, ``boundary`` with ``event='boundary'``,
    or — the regression-attribution seam — ``segment``, which the
    service fires INSIDE the scheduler's segment-latency window so
    the injected stall lands in the segment spans and histogram) —
    the deterministic hung-segment stand-in the watchdog must
    detect and, once the sleep returns, recover from."""

    def __init__(self, step: int, delay_s: float, event: str = "step"):
        super().__init__()
        self.step = int(step)
        self.delay_s = float(delay_s)
        self.event = str(event)

    def fire(self, event: str, **ctx) -> None:
        if event == self.event and not self.fired \
                and int(ctx.get("step", -1)) >= self.step:
            self.fired += 1
            time.sleep(self.delay_s)


class KillServiceAt(Fault):
    """``SIGKILL`` this process at driver step ``step`` (or at a
    segment ``boundary`` with ``event='boundary'`` — mid-drain, after
    compute but amid bookkeeping: the worst window). No handler runs,
    no drain happens, nothing flushes — recovery is entirely the
    admission WAL + checkpoint replay path. Use inside a chaos-harness
    child process only (:mod:`deap_tpu.serving.chaos`)."""

    def __init__(self, step: int, event: str = "step",
                 signum: int = signal.SIGKILL):
        super().__init__()
        self.step = int(step)
        self.event = str(event)
        self.signum = signum

    def fire(self, event: str, **ctx) -> None:
        if event == self.event and not self.fired \
                and int(ctx.get("step", -1)) >= self.step:
            self.fired += 1
            os.kill(os.getpid(), self.signum)


class KillDuringHandoff(Fault):
    """``SIGKILL`` the source process at a chosen **seam of the
    live-migration handshake** — fired on the ``migration`` event
    :mod:`deap_tpu.serving.migration` emits with ``seam=`` context:

    - ``after_offer`` — the offer record is fsync'd but the target has
      heard nothing: the tenant must replay on the SOURCE.
    - ``before_adopted`` — the target received the checkpoint but its
      ``adopted`` record is not yet durable: still the source's.
    - ``before_transferred`` — the target ACKed (its adoption is
      durable) but the source died before writing ``transferred``: the
      tenant must resume on the TARGET, and the restarted source must
      discover that from the target's WAL and retroactively close its
      open offer.

    Optionally filtered to one tenant (``tenant_substr``). Only
    meaningful in a chaos-harness child process."""

    def __init__(self, seam: str, tenant_substr: str = "",
                 signum: int = signal.SIGKILL):
        super().__init__()
        if seam not in ("after_offer", "before_adopted",
                        "before_transferred"):
            raise ValueError(f"unknown migration seam {seam!r}")
        self.seam = seam
        self.tenant_substr = str(tenant_substr)
        self.signum = signum

    def fire(self, event: str, **ctx) -> None:
        if event == "migration" and not self.fired \
                and str(ctx.get("seam")) == self.seam \
                and self.tenant_substr in str(ctx.get("tenant_id", "")):
            self.fired += 1
            os.kill(os.getpid(), self.signum)


class TornWAL(Fault):
    """After the ``seq``-th admission-WAL append, tear ``nbytes`` off
    the log's tail (a power cut mid-append) and — default — raise
    :class:`InjectedCrash` so the submit that wrote the record never
    ACKs. The restarted WAL must self-heal the tear and replay
    everything *except* the torn record."""

    def __init__(self, seq: int, nbytes: int = 7,
                 then_crash: bool = True):
        super().__init__()
        self.seq = int(seq)
        self.nbytes = int(nbytes)
        self.then_crash = then_crash

    def fire(self, event: str, **ctx) -> None:
        if event == "wal_append" and not self.fired \
                and int(ctx.get("seq", -1)) >= self.seq:
            self.fired += 1
            corrupt_file(ctx["path"], mode="truncate",
                         offset=-self.nbytes)
            if self.then_crash:
                raise InjectedCrash(
                    f"injected crash after tearing {ctx['path']}")


class CorruptResult(Fault):
    """Silently corrupt the raw result of the next ``times`` finishing
    tenants whose id contains ``tenant_substr`` — fired on the
    service's ``result`` event at the segment boundary where the
    tenant completes. The service catches the raised
    :class:`InjectedCorruption` and swaps in
    ``corrupt_pytree(result)`` before the result view is published, so
    the corruption is upstream of the wire digest: journal, tenant
    status and HTTP all report success, and only the known-answer
    canary's digest compare (:mod:`deap_tpu.serving.canary`) can
    detect it. The default ``tenant_substr='canary'`` aims the fault
    straight at the canary tenants — the end-to-end detection proof
    ``bench.py --canary`` measures the latency of."""

    def __init__(self, tenant_substr: str = "canary", times: int = 1):
        super().__init__()
        self.tenant_substr = str(tenant_substr)
        self.times = int(times)

    def fire(self, event: str, **ctx) -> None:
        if event == "result" and self.fired < self.times \
                and self.tenant_substr in str(ctx.get("tenant_id", "")):
            self.fired += 1
            raise InjectedCorruption(
                f"injected result corruption for "
                f"{ctx.get('tenant_id')} (#{self.fired}/{self.times})")


def corrupt_pytree(tree: Any) -> Any:
    """Return ``tree`` with the first byte of its first numeric array
    leaf XOR-flipped — the smallest corruption that is *guaranteed* to
    change the wire digest (which hashes raw leaf bytes), independent
    of dtype and of special values like NaN/inf that arithmetic
    perturbations can leave fixed. Structure, shapes and dtypes are
    untouched; non-array leaves pass through."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    for i, leaf in enumerate(leaves):
        try:
            arr = np.asarray(leaf)
        except Exception:
            continue
        if arr.size == 0 or arr.dtype.kind not in "biufc":
            continue
        damaged = np.array(arr)  # contiguous owned copy
        raw = damaged.reshape(-1).view(np.uint8)
        raw[0] ^= 0xA5
        leaves[i] = damaged
        return jax.tree_util.tree_unflatten(treedef, leaves)
    return tree


def nan_inject_evaluate(evaluate, rows: Any):
    """Wrap a batched evaluator so fitness rows ``rows`` (indices)
    come back NaN every call — deterministic input for the
    quarantine wrapper and the ``non_finite`` alarm path."""
    rows = jnp.asarray(rows)

    def wrapped(genomes):
        values = evaluate(genomes)
        flat_bad = jnp.zeros(values.shape[0], bool).at[rows].set(True)
        bad = flat_bad.reshape((-1,) + (1,) * (values.ndim - 1))
        return jnp.where(bad, jnp.nan, values)

    return wrapped
