"""COCO/BBOB black-box benchmark harness glue.

Counterpart of /root/reference/examples/bbob.py, which glues DEAP onto
the (externally installed) BBOB campaign runner via ``fgeneric``. The
modern COCO package is ``cocoex``; it is not part of this environment,
so the harness gates on its availability and otherwise demonstrates the
same loop shape on the built-in benchmark suite.
"""

import jax
import jax.numpy as jnp

from deap_tpu import benchmarks, strategies


def run_campaign(problems, dim: int, budget_mult: int = 100):
    """Run CMA-ES restarts over a problem list (the tuneup/restart shape
    of the reference's main loop)."""
    results = {}
    for name, fn in problems:
        strat = strategies.Strategy(centroid=[0.0] * dim, sigma=2.0,
                                    lambda_=10)
        state = strat.initial_state()

        @jax.jit
        def gen_step(k, st):
            g = strat.generate(k, st)
            v = jax.vmap(fn)(g)[:, 0]
            return strat.update(st, g, v), v.min()

        key = jax.random.key(hash(name) % (2 ** 31))
        best = jnp.inf
        for t in range(budget_mult):
            key, kg = jax.random.split(key)
            state, gen_best = gen_step(kg, state)
            best = jnp.minimum(best, gen_best)
        results[name] = float(best)
    return results


def main(smoke: bool = False):
    try:
        import cocoex  # noqa: F401
        print("cocoex available — wire run_campaign into a COCO suite "
              "observer here")
    except ImportError:
        pass
    dim = 5
    problems = [
        ("sphere", benchmarks.sphere),
        ("rosenbrock", benchmarks.rosenbrock),
        ("rastrigin", benchmarks.rastrigin),
    ]
    results = run_campaign(problems, dim,
                           budget_mult=100 if not smoke else 15)
    for name, best in results.items():
        print(f"{name:12s} best {best:.4e}")
    return results


if __name__ == "__main__":
    main()
