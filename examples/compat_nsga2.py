"""Drop-in multi-objective GA on :mod:`deap_tpu.compat`: NSGA-II on ZDT3.

Original demo code for the multi-objective half of docs/porting.md's
drop-in route, exercising the surfaces a ported NSGA-II program touches:
``creator``/``Toolbox``, ``compat.benchmarks.zdt3`` as a plain
``evaluate``, bounded SBX + polynomial mutation, ``selTournamentDCD``
over crowding distances, ``selNSGA2`` environmental selection, and the
``compat.benchmarks.tools.hypervolume`` quality metric. Program shape
covered (not the text): ``/root/reference/examples/ga/nsga2.py`` —
with ZDT3's disconnected front instead of the reference demo's ZDT1.
"""

import random

from deap_tpu.compat import base, benchmarks, creator, tools

N_DIM = 12
LOW, UP = 0.0, 1.0


def build_toolbox():
    creator.create("Zdt3Fit", base.Fitness, weights=(-1.0, -1.0))
    creator.create("Vector", list, fitness=creator.Zdt3Fit)

    tb = base.Toolbox()
    tb.register("gene", random.uniform, LOW, UP)
    tb.register("individual", tools.initRepeat, creator.Vector,
                tb.gene, N_DIM)
    tb.register("population", tools.initRepeat, list, tb.individual)

    tb.register("evaluate", benchmarks.zdt3)
    tb.register("mate", tools.cxSimulatedBinaryBounded,
                eta=20.0, low=LOW, up=UP)
    tb.register("mutate", tools.mutPolynomialBounded,
                eta=20.0, low=LOW, up=UP, indpb=1.0 / N_DIM)
    tb.register("select", tools.selNSGA2)
    return tb


def main(smoke: bool = False, seed: int = 9173):
    random.seed(seed)
    tb = build_toolbox()

    mu = 40 if smoke else 100
    ngen = 8 if smoke else 80
    cxpb = 0.9

    pop = tb.population(n=mu)
    for ind in pop:
        ind.fitness.values = tb.evaluate(ind)
    # rank + crowding must exist before the first DCD tournament
    pop = tb.select(pop, mu)

    for _ in range(ngen):
        parents = tools.selTournamentDCD(pop, mu)
        offspring = [tb.clone(ind) for ind in parents]
        for a, b in zip(offspring[::2], offspring[1::2]):
            if random.random() <= cxpb:
                tb.mate(a, b)
            tb.mutate(a)
            tb.mutate(b)
            del a.fitness.values, b.fitness.values
        for ind in offspring:
            if not ind.fitness.valid:
                ind.fitness.values = tb.evaluate(ind)
        pop = tb.select(pop + offspring, mu)

    hv = benchmarks.tools.hypervolume(pop, ref=[11.0, 11.0])
    print(f"ZDT3 front hypervolume (ref [11, 11]): {hv:.3f}")
    return hv


if __name__ == "__main__":
    main()
