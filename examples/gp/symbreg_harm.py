"""Symbolic regression under HARM-GP bloat control.

Counterpart of /root/reference/examples/gp/symbreg_harm.py: the same
quartic target as symbreg.py but evolved with ``gp.harm``
(gp.py:938-1135), which shapes the offspring size distribution to stop
tree bloat.
"""

import jax
import jax.numpy as jnp

from deap_tpu import gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.support.stats import Statistics

MAX_LEN = 64


def main(smoke: bool = False):
    n, ngen = (300, 25) if not smoke else (60, 5)
    nbrinds = 600 if not smoke else 200

    pset = gp.math_set(n_args=1)
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 2)
    expr_mut = gp.make_generator(pset, 32, 0, 2, "full")
    interp = gp.make_batch_interpreter(pset, MAX_LEN)

    X = jnp.linspace(-1.0, 1.0, 20, endpoint=False)[:, None]
    y = X[:, 0] ** 4 + X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda gs: -jnp.mean((interp(gs, X) - y) ** 2, -1))
    toolbox.register("mate", gp.make_cx_one_point(pset))
    toolbox.register("mutate", gp.make_mut_uniform(pset, expr_mut))
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    size_stats = Statistics(lambda pop: pop.genomes["length"])
    size_stats.register("avg", jnp.mean)
    size_stats.register("max", jnp.max)

    pop = init_population(jax.random.key(33), n, gen, FitnessSpec((1.0,)))
    pop, logbook, _ = gp.harm(
        jax.random.key(34), pop, toolbox, cxpb=0.5, mutpb=0.1, ngen=ngen,
        alpha=0.05, beta=10, gamma=0.25, rho=0.9, nbrindsmodel=nbrinds,
        stats=size_stats, verbose=not smoke)
    mean_size = float(jnp.mean(pop.genomes["length"]))
    mse = float(-pop.wvalues.max())
    print(f"Best MSE {mse:.6f} with mean tree size {mean_size:.1f}")
    return mean_size


if __name__ == "__main__":
    main()
