"""The Koza artificial ant on the Santa Fe trail.

Counterpart of /root/reference/examples/gp/ant.py (+ the C++ fast
simulator AntSimulatorFast.cpp): evolve an if_food_ahead/prog2/prog3
program eating the 89 food pieces within 543 moves. Evaluation runs
either as the vmapped JAX rollout (device path) or the native C++
simulator (host path) — both bit-identical.
"""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.gp import ant

MAX_LEN = 80


def main(smoke: bool = False, native: bool = False):
    n, ngen = (300, 40) if not smoke else (60, 6)
    pset = ant.ant_pset()
    trail, start = ant.parse_trail()
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 4)
    expr_mut = gp.make_generator(pset, 24, 0, 2, "full")

    if native:
        from deap_tpu.native.ant_binding import ant_eval

        def evaluate(genomes):
            out = ant_eval(np.asarray(genomes["nodes"]),
                           np.asarray(genomes["length"]), trail, start,
                           max_moves=543)
            return jnp.asarray(out, jnp.float32)
    else:
        eval_one = ant.make_ant_evaluator(pset, MAX_LEN, trail, start,
                                          max_moves=543)
        evaluate = jax.vmap(eval_one)

    limit = gp.static_limit(lambda g: gp.tree_height(g, pset), 17)
    toolbox = Toolbox()
    toolbox.register("evaluate", evaluate)
    toolbox.register("mate", limit(gp.make_cx_one_point(pset)))
    toolbox.register("mutate", limit(gp.make_mut_uniform(pset, expr_mut)))
    toolbox.register("select", ops.sel_tournament, tournsize=7)

    pop = init_population(jax.random.key(46), n, gen, FitnessSpec((1.0,)))
    if native:
        # host evaluation can't live inside the scanned/jitted loop —
        # run the generational loop on host around jitted variation
        # (the reference's toolbox.map seam, SURVEY.md §3.1)
        from deap_tpu.core.population import gather

        pop = pop.with_fitness(evaluate(pop.genomes))

        @jax.jit
        def vary(key, pop):
            k_sel, k_var = jax.random.split(key)
            idx = toolbox.select(k_sel, pop.wvalues, pop.size)
            return algorithms.var_and(k_var, gather(pop, idx), toolbox,
                                      0.5, 0.2)

        key = jax.random.key(47)
        for g in range(ngen):
            key, kg = jax.random.split(key)
            off = vary(kg, pop)
            values = evaluate(off.genomes)
            pop = off.with_fitness(values, mask=~off.valid)
    else:
        pop, logbook, _ = algorithms.ea_simple(
            jax.random.key(47), pop, toolbox, cxpb=0.5, mutpb=0.2,
            ngen=ngen)
    best = float(pop.wvalues.max())
    print(f"Most food eaten: {best} / 89")
    return best


if __name__ == "__main__":
    main()
