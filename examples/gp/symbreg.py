"""Symbolic regression of the quartic polynomial — the canonical GP.

Counterpart of /root/reference/examples/gp/symbreg.py (92 LoC, seed 318
at symbreg.py:73): evolve ``x⁴ + x³ + x² + x`` from 20 sample points in
[-1, 1) with the add/sub/mul/protectedDiv/neg/cos/sin + ERC vocabulary.
Evaluation of the whole population on all points is one batched stack
-interpreter program instead of per-individual codegen + eval
(SURVEY.md §3.3).
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 64


def main(smoke: bool = False, seed: int = 318):
    n, ngen = (300, 40) if not smoke else (60, 8)

    pset = gp.math_set(n_args=1)
    pset.rename_arguments(ARG0="x")
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 2)
    expr_mut = gp.make_generator(pset, 32, 0, 2, "full")
    interp = gp.make_batch_interpreter(pset, MAX_LEN)

    X = jnp.linspace(-1.0, 1.0, 20, endpoint=False)[:, None]
    y = X[:, 0] ** 4 + X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]

    limit = gp.static_limit(lambda g: gp.tree_height(g, pset), 17)

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda gs: -jnp.mean((interp(gs, X) - y) ** 2, -1))
    toolbox.register("mate", limit(gp.make_cx_one_point(pset)))
    toolbox.register("mutate", limit(gp.make_mut_uniform(pset, expr_mut)))
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(seed), n, gen,
                          FitnessSpec((1.0,)))
    pop, logbook, hof = algorithms.ea_simple(
        jax.random.key(seed + 1), pop, toolbox, cxpb=0.5, mutpb=0.1,
        ngen=ngen, halloffame_size=1)
    best_i = int(pop.best_index())
    best = jax.tree_util.tree_map(lambda a: a[best_i], pop.genomes)
    mse = float(-pop.wvalues.max())
    print(f"Best MSE: {mse:.6f}")
    print("Best expr:", gp.to_string(best, pset))
    return mse


if __name__ == "__main__":
    main()
