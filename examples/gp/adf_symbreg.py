"""Symbolic regression with automatically defined functions.

Counterpart of /root/reference/examples/gp/adf_symbreg.py: a MAIN tree
plus three ADF branches, each with its own primitive set; MAIN may call
ADF0/ADF1/ADF2, ADF0 may call ADF1/ADF2, ADF1 may call ADF2 (the
progressive compile order of compileADF, gp.py:490-513). Variation is
branch-wise, as in the reference's per-subtree mate/mutate loops.
"""

import jax
import jax.numpy as jnp

from deap_tpu import gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu import algorithms

MAIN_LEN, ADF_LEN = 48, 24


def build_branches():
    adf2 = gp.math_set(n_args=2, trig=False, erc=False, name="ADF2")
    adf1 = gp.math_set(n_args=2, trig=False, erc=False, name="ADF1")
    adf1.add_adf("ADF2", 2, branch=3)
    adf0 = gp.math_set(n_args=2, trig=False, erc=False, name="ADF0")
    adf0.add_adf("ADF1", 2, branch=2)
    adf0.add_adf("ADF2", 2, branch=3)
    main = gp.math_set(n_args=1, trig=True, erc=True, name="MAIN")
    main.add_adf("ADF0", 2, branch=1)
    main.add_adf("ADF1", 2, branch=2)
    main.add_adf("ADF2", 2, branch=3)
    return [(main, MAIN_LEN), (adf0, ADF_LEN), (adf1, ADF_LEN),
            (adf2, ADF_LEN)]


def main(smoke: bool = False):
    n, ngen = (200, 25) if not smoke else (50, 5)
    branches = build_branches()
    gen = gp.make_adf_generator(branches, 1, 2)
    interp = gp.make_adf_batch_interpreter(branches)
    cx = gp.branch_wise_cx([gp.make_cx_one_point(ps) for ps, _ in branches])
    mut = gp.branch_wise_mut([
        gp.make_mut_uniform(ps, gp.make_generator(ps, 16, 0, 2, "full"))
        for ps, _ in branches])

    X = jnp.linspace(-1.0, 1.0, 20, endpoint=False)[:, None]
    y = X[:, 0] ** 4 + X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda gs: -jnp.mean((interp(gs, X) - y) ** 2, -1))
    toolbox.register("mate", cx)
    toolbox.register("mutate", mut)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(37), n, gen, FitnessSpec((1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(38), pop, toolbox, cxpb=0.5, mutpb=0.2, ngen=ngen)
    mse = float(-pop.wvalues.max())
    print(f"Best MSE with ADFs: {mse:.6f}")
    return mse


if __name__ == "__main__":
    main()
