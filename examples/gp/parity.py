"""Even-parity — boolean GP over all input combinations.

Counterpart of /root/reference/examples/gp/parity.py (even-parity-6
over and/or/xor/not with True/False terminals; PARITY_FANIN_M at
parity.py:40-44). The full truth table is evaluated for the whole
population in one batched interpreter call. Fan-in is reduced to 4 by
default to keep the smoke run fast — pass ``fanin=6`` for the
reference's size.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 96


def truth_table(fanin: int):
    n = 1 << fanin
    X = ((jnp.arange(n)[:, None] >> jnp.arange(fanin)[None, :]) & 1
         ).astype(jnp.float32)
    y = (X.sum(-1) % 2 == 0).astype(jnp.float32)   # even parity
    return X, y


def main(smoke: bool = False, fanin: int = 4):
    n, ngen = (300, 40) if not smoke else (60, 8)
    pset = gp.bool_set(fanin)
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 3)
    expr_mut = gp.make_generator(pset, 32, 0, 2, "grow")
    interp = gp.make_batch_interpreter(pset, MAX_LEN)
    X, y = truth_table(fanin)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda gs: (
        interp(gs, X) == y).sum(-1).astype(jnp.float32))
    toolbox.register("mate", gp.make_cx_one_point(pset))
    toolbox.register("mutate", gp.make_mut_uniform(pset, expr_mut))
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(39), n, gen, FitnessSpec((1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(40), pop, toolbox, cxpb=0.5, mutpb=0.2, ngen=ngen)
    best = float(pop.wvalues.max())
    print(f"Best truth-table matches: {best} / {1 << fanin}")
    return best


if __name__ == "__main__":
    main()
