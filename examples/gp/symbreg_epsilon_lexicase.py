"""Symbolic regression with ε-lexicase parent selection.

Counterpart of /root/reference/examples/gp/symbreg_epsilon_lexicase.py:
selection pressure comes from per-case errors (automatic-ε lexicase,
selection.py:283-330) instead of an aggregated MSE.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 64


def main(smoke: bool = False):
    n, ngen = (200, 25) if not smoke else (50, 6)
    n_cases = 20

    pset = gp.math_set(n_args=1)
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 2)
    expr_mut = gp.make_generator(pset, 32, 0, 2, "full")
    interp = gp.make_batch_interpreter(pset, MAX_LEN)

    X = jnp.linspace(-1.0, 1.0, n_cases, endpoint=False)[:, None]
    y = X[:, 0] ** 4 + X[:, 0] ** 3 + X[:, 0] ** 2 + X[:, 0]
    case_weights = (-1.0,) * n_cases       # minimise every case error

    def case_errors(gs):
        preds = interp(gs, X)
        return jnp.abs(preds - y)          # [pop, cases]

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda gs: -case_errors(gs).mean(-1))
    toolbox.register("mate", gp.make_cx_one_point(pset))
    toolbox.register("mutate", gp.make_mut_uniform(pset, expr_mut))

    pop = init_population(jax.random.key(35), n, gen, FitnessSpec((1.0,)))
    pop = algorithms.evaluate_invalid(pop, toolbox.evaluate)

    @jax.jit
    def generation(key, pop):
        k_sel, k_var = jax.random.split(key)
        errors = case_errors(pop.genomes)
        idx = ops.sel_automatic_epsilon_lexicase(k_sel, errors,
                                                 case_weights, pop.size)
        off = algorithms.var_and(k_var, gather(pop, idx), toolbox,
                                 cxpb=0.5, mutpb=0.1)
        return algorithms.evaluate_invalid(off, toolbox.evaluate)

    key = jax.random.key(36)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        pop = generation(kg, pop)
    mse = float(-pop.wvalues.max())
    print(f"Best mean abs error: {mse:.6f}")
    return mse


if __name__ == "__main__":
    main()
