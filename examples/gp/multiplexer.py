"""Boolean 6-multiplexer (2 address + 4 data lines).

Counterpart of /root/reference/examples/gp/multiplexer.py (MUX_SELECT_LINES
= 3 → 11-mux in the reference; 2 → 6-mux here for speed, same
machinery): find a boolean program computing
``data[address]`` over the full truth table.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 96


def truth_table(select: int = 2):
    data = 1 << select
    fanin = select + data
    n = 1 << fanin
    X = ((jnp.arange(n)[:, None] >> jnp.arange(fanin)[None, :]) & 1
         ).astype(jnp.float32)
    addr = (X[:, :select] * (2 ** jnp.arange(select))).sum(-1).astype(jnp.int32)
    y = X[jnp.arange(n), select + addr]
    return X, y, fanin


def main(smoke: bool = False):
    n, ngen = (300, 40) if not smoke else (60, 8)
    X, y, fanin = truth_table(2)
    pset = gp.bool_set(fanin)
    gen = gp.gen_half_and_half(pset, MAX_LEN, 2, 4)
    expr_mut = gp.make_generator(pset, 32, 0, 2, "grow")
    interp = gp.make_batch_interpreter(pset, MAX_LEN)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda gs: (
        interp(gs, X) == y).sum(-1).astype(jnp.float32))
    toolbox.register("mate", gp.make_cx_one_point(pset))
    toolbox.register("mutate", gp.make_mut_uniform(pset, expr_mut))
    toolbox.register("select", ops.sel_tournament, tournsize=7)

    pop = init_population(jax.random.key(41), n, gen, FitnessSpec((1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(42), pop, toolbox, cxpb=0.8, mutpb=0.1, ngen=ngen)
    best = float(pop.wvalues.max())
    print(f"Best truth-table matches: {best} / {X.shape[0]}")
    return best


if __name__ == "__main__":
    main()
