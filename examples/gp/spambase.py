"""Spam classification with strongly-typed GP.

Counterpart of /root/reference/examples/gp/spambase.py: a typed
vocabulary where float comparisons feed boolean logic feeding an
if-then-else, evolved to classify feature vectors (the reference reads
spambase.csv; a reproducible synthetic spam-like dataset stands in).
Typed generation/variation guarantee well-typed trees by construction.

For direct comparability, ``main(csv_path=...)`` (or
``DEAP_TPU_SPAMBASE``) accepts the reference's UCI ``spambase.csv``
(57 features + 0/1 label per row); fitness is then accuracy on a
fixed 400-row subset, the reference example's per-evaluation sample
size (examples/gp/spambase.py's ``random.sample(spam, 400)``) made
deterministic for a stable quality gate.
"""

import os

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

N_FEATURES = 6
MAX_LEN = 64


def make_dataset(key, n: int = 200):
    """Spam iff freq0 > 40 or (freq1 > 60 and freq2 < 20) — a rule the
    typed vocabulary can express exactly."""
    X = jax.random.uniform(key, (n, N_FEATURES)) * 100.0
    y = ((X[:, 0] > 40.0) | ((X[:, 1] > 60.0) & (X[:, 2] < 20.0))
         ).astype(jnp.float32)
    return X, y


def load_csv(path: str, n_rows: int = 400, seed: int = 7):
    """The reference-format spambase CSV (comma-separated floats, label
    last) reduced to a fixed ``n_rows`` subset."""
    import numpy as np

    data = jnp.asarray(np.loadtxt(path, delimiter=","), jnp.float32)
    idx = jax.random.choice(jax.random.key(seed), data.shape[0],
                            (min(n_rows, data.shape[0]),), replace=False)
    rows = data[idx]
    return rows[:, :-1], rows[:, -1]


def main(smoke: bool = False, csv_path: str | None = None):
    n, ngen = (200, 30) if not smoke else (50, 6)
    csv_path = csv_path or os.environ.get("DEAP_TPU_SPAMBASE")
    if csv_path:
        X, y = load_csv(csv_path)
    else:
        X, y = make_dataset(jax.random.key(43))
    pset = gp.spam_set(n_features=X.shape[1])
    gen = gp.make_generator_typed(pset, MAX_LEN, 1, 4)
    interp = gp.make_batch_interpreter(pset, MAX_LEN)

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda gs: (interp(gs, X) == y).mean(-1))
    toolbox.register("mate", gp.make_cx_one_point_typed(pset))
    toolbox.register("mutate", gp.make_mut_node_replacement_typed(pset))
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(44), n, lambda k: gen(k),
                          FitnessSpec((1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(45), pop, toolbox, cxpb=0.5, mutpb=0.2, ngen=ngen)
    acc = float(pop.wvalues.max())
    print(f"Best classification accuracy: {acc:.3f}")
    return acc


if __name__ == "__main__":
    main()
