"""CMA-ES run with full trajectory recording (plotting optional).

Counterpart of /root/reference/examples/es/cma_plotting.py: run CMA-ES
on Rastrigin while recording per-generation best fitness, sigma, axis
ratio and centroid — the quantities the reference plots with
matplotlib. The scanned loop returns the whole trajectory as stacked
arrays; plotting is gated on matplotlib availability.
"""

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import benchmarks, strategies

N = 10


def main(smoke: bool = False, plot: bool = False):
    ngen = 150 if not smoke else 25
    strat = strategies.Strategy(centroid=[5.0] * N, sigma=5.0, lambda_=40)

    def gen_step(state, key):
        genomes = strat.generate(key, state)
        values = jax.vmap(benchmarks.rastrigin)(genomes)[:, 0]
        new_state = strat.update(state, genomes, values)
        rec = {
            "best": values.min(),
            "sigma": state.sigma,
            "axis_ratio": state.diagD[-1] / state.diagD[0],
            "centroid_norm": jnp.linalg.norm(state.centroid),
        }
        return new_state, rec

    state, traj = lax.scan(gen_step, strat.initial_state(),
                           jax.random.split(jax.random.key(53), ngen))
    print(f"final best {float(traj['best'][-1]):.4f}, "
          f"sigma {float(traj['sigma'][-1]):.2e}, "
          f"axis ratio {float(traj['axis_ratio'][-1]):.1f}")
    if plot:
        try:
            import matplotlib.pyplot as plt
        except ImportError:
            print("matplotlib unavailable; skipping plot")
        else:
            fig, axes = plt.subplots(2, 2)
            for ax, (name, series) in zip(axes.flat, traj.items()):
                ax.plot(series)
                ax.set_title(name)
                ax.set_yscale("log")
            fig.savefig("cma_plotting.png")
            print("wrote cma_plotting.png")
    return {k: float(v[-1]) for k, v in traj.items()}


if __name__ == "__main__":
    main(plot=True)
