"""MO-CMA-ES on a bi-objective problem.

Counterpart of /root/reference/examples/es/cma_mo.py:
``cma.StrategyMultiObjective`` with per-parent success-rate adaptation
and indicator-based selection, run on ZDT1.
"""

import jax
import jax.numpy as jnp

from deap_tpu import benchmarks, strategies
from deap_tpu.benchmarks.tools import hypervolume
from deap_tpu.core.fitness import FitnessSpec


def main(smoke: bool = False):
    mu, lam, ndim = 10, 10, 30
    ngen = 250 if not smoke else 25

    pop0 = jax.random.uniform(jax.random.key(54), (mu, ndim))
    fit0 = jax.vmap(benchmarks.zdt1)(pop0)
    strat = strategies.StrategyMultiObjective(
        population=pop0, fitnesses=fit0, sigma=0.1, mu=mu, lambda_=lam,
        spec=FitnessSpec((-1.0, -1.0)))
    state = strat.initial_state()

    @jax.jit
    def gen_step(key, state):
        genomes = strat.generate(key, state)
        clipped = jnp.clip(genomes["x"], 0.0, 1.0)
        values = jax.vmap(benchmarks.zdt1)(clipped)
        return strat.update(state, genomes, values), values

    key = jax.random.key(55)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        state, values = gen_step(kg, state)

    final = jax.vmap(benchmarks.zdt1)(jnp.clip(state.x, 0, 1))
    hv = float(hypervolume(final, ref=jnp.asarray([11.0, 11.0]),
                           weights=(-1.0, -1.0)))
    print(f"MO-CMA-ES final hypervolume: {hv:.3f}")
    return hv


if __name__ == "__main__":
    main()
