"""Evolution strategy with per-gene mutation strategies.

Counterpart of /root/reference/examples/es/fctmin.py: individuals carry
a ``strategy`` vector (self-adaptive step sizes), varied by
``cxESBlend`` + ``mutESLogNormal`` under (μ, λ) selection. The strategy
vector travels in the genome pytree so all machinery applies unchanged.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, benchmarks, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

IND_SIZE = 30
MIN_STRATEGY = 0.5


def main(smoke: bool = False):
    mu, lam = 10, 100
    ngen = 100 if not smoke else 10

    def init_es(key):
        kx, ks = jax.random.split(key)
        return {
            "x": jax.random.uniform(kx, (IND_SIZE,), minval=-3.0,
                                    maxval=3.0),
            "strategy": jax.random.uniform(ks, (IND_SIZE,), minval=0.5,
                                           maxval=3.0),
        }

    def mate(key, a, b):
        (c1x, c1s), (c2x, c2s) = ops.cx_es_blend(
            key, a["x"], a["strategy"], b["x"], b["strategy"], alpha=0.1)
        return ({"x": c1x, "strategy": c1s},
                {"x": c2x, "strategy": c2s})

    def mutate(key, a):
        x, s = ops.mut_es_log_normal(key, a["x"], a["strategy"],
                                     c=1.0, indpb=0.03)
        # the reference's checkStrategy decorator clamps the step sizes
        # from below (fctmin.py:42-53)
        return {"x": x, "strategy": jnp.maximum(s, MIN_STRATEGY)}

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: jax.vmap(benchmarks.sphere)(
        g["x"])[:, 0])
    toolbox.register("mate", mate)
    toolbox.register("mutate", mutate)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(48), mu, init_es,
                          FitnessSpec((-1.0,)))
    pop, logbook, _ = algorithms.ea_mu_comma_lambda(
        jax.random.key(49), pop, toolbox, mu=mu, lambda_=lam,
        cxpb=0.6, mutpb=0.3, ngen=ngen)
    best = float(-pop.wvalues.max())
    print(f"Best sphere value: {best:.6f}")
    return best


if __name__ == "__main__":
    main()
