"""BIPOP-CMA-ES restart regime on Rastrigin.

Counterpart of /root/reference/examples/es/cma_bipop.py (199 LoC):
alternating large- and small-population restarts with the full Hansen
stopping-criteria set; promoted to the first-class
:func:`deap_tpu.strategies.bipop_cmaes`.
"""

import jax

from deap_tpu import benchmarks, strategies


def main(smoke: bool = False):
    dim = 30 if not smoke else 5
    nrestarts = 10 if not smoke else 2

    best_x, best_f, logbooks = strategies.bipop_cmaes(
        jax.random.key(56),
        lambda g: jax.vmap(benchmarks.rastrigin)(g)[:, 0],
        dim=dim, sigma0=2.0, nrestarts=nrestarts, verbose=False)
    total_gens = sum(len(lb) for lb in logbooks)
    print(f"best rastrigin {best_f:.4f} after {len(logbooks)} restarts, "
          f"{total_gens} total generations")
    return best_f


if __name__ == "__main__":
    main()
