"""(1+λ)-CMA-ES.

Counterpart of /root/reference/examples/es/cma_1+l_minfct.py:
``cma.StrategyOnePlusLambda`` — Cholesky-based covariance adaptation
with success-rate-driven step size — minimising a shifted sphere.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, benchmarks, strategies
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox

N = 5


def main(smoke: bool = False):
    ngen = 200 if not smoke else 40
    parent = jnp.full((N,), 5.0)
    strat = strategies.StrategyOnePlusLambda(
        parent=parent, parent_fitness=benchmarks.sphere(parent),
        sigma=5.0, lambda_=10)
    toolbox = Toolbox()
    toolbox.register("generate", strat.generate)
    toolbox.register("update", strat.update)
    toolbox.register("evaluate",
                     lambda g: jax.vmap(benchmarks.sphere)(g)[:, 0])

    state, logbook, _ = algorithms.ea_generate_update(
        jax.random.key(52), strat.initial_state(), toolbox, ngen,
        spec=FitnessSpec((-1.0,)))
    best = float(benchmarks.sphere(state.parent)[0])
    print(f"Parent sphere value: {best:.3e}")
    return best


if __name__ == "__main__":
    main()
