"""CMA-ES minimisation via the ask-tell loop.

Counterpart of /root/reference/examples/es/cma_minfct.py: ``cma.Strategy``
driven by ``eaGenerateUpdate`` on Rastrigin. The whole
generate → evaluate → update cycle is one scanned, compiled step.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, benchmarks, strategies
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.support.stats import fitness_stats

N = 20


def main(smoke: bool = False):
    ngen = 250 if not smoke else 30
    strat = strategies.Strategy(centroid=[5.0] * N, sigma=5.0,
                                lambda_=20 * N if not smoke else 40)
    toolbox = Toolbox()
    toolbox.register("generate", strat.generate)
    toolbox.register("update", strat.update)
    toolbox.register("evaluate", lambda g: jax.vmap(benchmarks.rastrigin)(
        g)[:, 0])

    state, logbook, hof = algorithms.ea_generate_update(
        jax.random.key(51), strat.initial_state(), toolbox, ngen,
        spec=FitnessSpec((-1.0,)), stats=fitness_stats(),
        halloffame_size=1, verbose=not smoke)
    from deap_tpu.support.hof import hof_best

    _, values = hof_best(hof)          # raw objective values
    best = float(values[0])
    print(f"Best rastrigin value: {best:.6f}")
    return best


if __name__ == "__main__":
    main()
