"""(1+1)-ES with the one-fifth success rule.

Counterpart of /root/reference/examples/es/onefifth.py: a single parent,
one Gaussian offspring per iteration, sigma scaled up on success and
down on failure to hold the 1/5 success rate. The whole run is a
``lax.scan``.
"""

import jax
import jax.numpy as jnp
from jax import lax

from deap_tpu import benchmarks

IND_SIZE = 10


def main(smoke: bool = False):
    ngen = 1500 if not smoke else 200
    c = 0.817        # the reference's decrease factor (onefifth.py)

    def f(x):
        return benchmarks.sphere(x)[0]

    def step(carry, key):
        x, sigma, fx = carry
        child = x + sigma * jax.random.normal(key, x.shape)
        fc = f(child)
        success = fc < fx
        x = jnp.where(success, child, x)
        fx = jnp.where(success, fc, fx)
        sigma = jnp.where(success, sigma / c, sigma * c ** 0.25)
        return (x, sigma, fx), fx

    x0 = jnp.full((IND_SIZE,), 5.0)
    (x, sigma, fx), hist = lax.scan(
        step, (x0, jnp.float32(1.0), f(x0)),
        jax.random.split(jax.random.key(50), ngen))
    print(f"Best after {ngen} iters: {float(fx):.3e} (sigma {float(sigma):.2e})")
    return float(fx)


if __name__ == "__main__":
    main()
