"""Multiswarm PSO tracking MovingPeaks.

Counterpart of /root/reference/examples/pso/multiswarm.py (Blackwell,
Branke & Li 2008): constricted swarms with anti-convergence, exclusion
and quantum re-diversification on a changing landscape. The swarm set
lives on a static-capacity axis with an active mask so every dynamic
rule compiles.
"""

import jax

from deap_tpu import strategies
from deap_tpu.benchmarks import movingpeaks as mp


def main(smoke: bool = False):
    ndim = 5
    epochs = 4 if not smoke else 2
    gens_per_epoch = 30 if not smoke else 8

    cfg = mp.MovingPeaksConfig(dim=ndim, **{
        k: v for k, v in mp.SCENARIO_2.items()
        if k not in ("pfunc", "bfunc")})
    state = mp.mp_init(jax.random.key(68), cfg)

    ms = strategies.MultiSwarmPSO(
        lambda x: mp.mp_evaluate(cfg, state, x)[1][:, 0],
        pmin=cfg.min_coord, pmax=cfg.max_coord,
        rcloud=0.5 * cfg.move_severity)
    s = ms.init(jax.random.key(69), nswarms=4, nparticles=5, dim=ndim,
                capacity=12)
    key = jax.random.key(70)
    for epoch in range(epochs):
        for g in range(gens_per_epoch):
            key, kg = jax.random.split(key)
            s = ms.step(kg, s)
        _, best = ms.best(s)
        print(f"epoch {epoch}: best {float(best):.2f} "
              f"(optimum {float(mp.global_maximum(cfg, state)):.2f}), "
              f"{int(s.active.sum())} swarms")
        state = mp.change_peaks(cfg, state)
        ms.evaluate = lambda x: mp.mp_evaluate(cfg, state, x)[1][:, 0]
    return float(best)


if __name__ == "__main__":
    main()
