"""Particle swarm optimisation, canonical form.

Counterpart of /root/reference/examples/pso/basic.py: velocity update
with personal/global attractors and speed clamping
(updateParticle, basic.py:38-48), maximising the inverted h1 landscape
— here minimising sphere for a crisp check, with the whole run scanned.
"""

import jax

from deap_tpu import benchmarks, strategies
from deap_tpu.core.fitness import FitnessSpec


def main(smoke: bool = False):
    ngen = 100 if not smoke else 20
    pso = strategies.PSO(
        evaluate=lambda x: -jax.vmap(benchmarks.sphere)(x)[:, 0],
        phi1=2.0, phi2=2.0, smin=-3.0, smax=3.0)
    state = pso.init(jax.random.key(66), n=100, dim=2,
                     pmin=-100.0, pmax=100.0, smin=-3.0, smax=3.0)
    state, hist = pso.run(jax.random.key(67), state, ngen)
    best = float(-state.gbest_w[0])
    print(f"Best sphere value: {best:.4f}")
    return best


if __name__ == "__main__":
    main()
