"""Speciation PSO on MovingPeaks.

Counterpart of /root/reference/examples/pso/speciation.py: species form
around best-first seeds within radius ``rs``; capped species, replaced
worst species, quantum conversion on change detection.
"""

import jax

from deap_tpu import strategies
from deap_tpu.benchmarks import movingpeaks as mp


def main(smoke: bool = False):
    ndim = 5
    steps = 60 if not smoke else 15

    cfg = mp.MovingPeaksConfig(dim=ndim, **{
        k: v for k, v in mp.SCENARIO_1.items()
        if k not in ("pfunc", "bfunc")})
    state = mp.mp_init(jax.random.key(71), cfg)
    rs = (cfg.max_coord - cfg.min_coord) / (50 ** (1.0 / ndim))

    sp = strategies.SpeciationPSO(
        lambda x: mp.mp_evaluate(cfg, state, x)[1][:, 0],
        pmin=cfg.min_coord, pmax=cfg.max_coord, rs=rs, pmax_size=10,
        rcloud=1.0)
    s = sp.init(jax.random.key(72), n=100, dim=ndim)
    key = jax.random.key(73)
    for g in range(steps):
        key, kg = jax.random.split(key)
        s = sp.step(kg, s)
    _, best = sp.best(s)
    seeds, _ = strategies.species_seeds(s.pbest_x, s.pbest_f, rs)
    print(f"best {float(best):.2f} "
          f"(optimum {float(mp.global_maximum(cfg, state)):.2f}); "
          f"{int(seeds.sum())} species")
    return float(best)


if __name__ == "__main__":
    main()
