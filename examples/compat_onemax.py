"""The reference's OneMax program, unchanged except for the imports.

This is /root/reference/examples/ga/onemax.py's main loop shape (also
README.md:74-104) running verbatim on :mod:`deap_tpu.compat` — the
drop-in route of docs/porting.md. Everything below the import block is
written exactly as a DEAP user would write it: list individuals,
``creator.create``, stdlib ``random``, in-place operators, fitness
deletion.
"""

import random

from deap_tpu.compat import base, creator, tools


def main(smoke: bool = False, seed: int = 64):
    random.seed(seed)

    creator.create("FitnessMax", base.Fitness, weights=(1.0,))
    creator.create("Individual", list, fitness=creator.FitnessMax)

    toolbox = base.Toolbox()
    toolbox.register("attr_bool", random.randint, 0, 1)
    toolbox.register("individual", tools.initRepeat, creator.Individual,
                     toolbox.attr_bool, 100)
    toolbox.register("population", tools.initRepeat, list,
                     toolbox.individual)

    def evalOneMax(individual):
        return sum(individual),

    toolbox.register("evaluate", evalOneMax)
    toolbox.register("mate", tools.cxTwoPoint)
    toolbox.register("mutate", tools.mutFlipBit, indpb=0.05)
    toolbox.register("select", tools.selTournament, tournsize=3)

    pop = toolbox.population(n=300 if not smoke else 60)
    CXPB, MUTPB, NGEN = 0.5, 0.2, 40 if not smoke else 10

    fitnesses = map(toolbox.evaluate, pop)
    for ind, fit in zip(pop, fitnesses):
        ind.fitness.values = fit

    for g in range(NGEN):
        offspring = toolbox.select(pop, len(pop))
        offspring = list(map(toolbox.clone, offspring))

        for child1, child2 in zip(offspring[::2], offspring[1::2]):
            if random.random() < CXPB:
                toolbox.mate(child1, child2)
                del child1.fitness.values
                del child2.fitness.values
        for mutant in offspring:
            if random.random() < MUTPB:
                toolbox.mutate(mutant)
                del mutant.fitness.values

        invalid_ind = [ind for ind in offspring if not ind.fitness.valid]
        fitnesses = map(toolbox.evaluate, invalid_ind)
        for ind, fit in zip(invalid_ind, fitnesses):
            ind.fitness.values = fit

        pop[:] = offspring

    best = tools.selBest(pop, 1)[0]
    print(f"Best individual has fitness {best.fitness.values[0]}")
    return best.fitness.values[0]


if __name__ == "__main__":
    main()
