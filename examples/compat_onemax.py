"""Drop-in GA on :mod:`deap_tpu.compat`: matching a striped bit mask.

Original demo code for docs/porting.md's drop-in route, written the way
a DEAP user writes a GA — ``creator.create`` type factory, stdlib
``random``, a ``Toolbox`` of aliases, ``tools`` operators,
``Statistics`` + ``HallOfFame``, ``algorithms.eaSimple`` — but on its
own problem: the target is a 96-bit striped mask (every third bit off),
so unlike OneMax the optimum is not the all-ones string and flip-bit
mutation pressure alone cannot find it. API surface covered (not the
text): the toolbox-registration and generational-loop conventions of
``/root/reference/examples/ga/onemax.py:72-157`` and
``README.md:74-104``.
"""

import random

from deap_tpu.compat import algorithms, base, creator, tools

BITS = 96
# Striped target: bit i should be 0 when i is a multiple of 3, else 1.
TARGET = [0 if i % 3 == 0 else 1 for i in range(BITS)]


def score_match(individual):
    """Number of positions agreeing with TARGET (maximise; optimum BITS)."""
    agree = sum(1 for have, want in zip(individual, TARGET) if have == want)
    return (float(agree),)


def build_toolbox():
    creator.create("StripeFit", base.Fitness, weights=(1.0,))
    creator.create("Bitstring", list, fitness=creator.StripeFit)

    tb = base.Toolbox()
    tb.register("coin", random.randint, 0, 1)
    tb.register("individual", tools.initRepeat, creator.Bitstring,
                tb.coin, BITS)
    tb.register("population", tools.initRepeat, list, tb.individual)

    tb.register("evaluate", score_match)
    tb.register("mate", tools.cxUniform, indpb=0.5)
    tb.register("mutate", tools.mutFlipBit, indpb=1.0 / BITS)
    tb.register("select", tools.selTournament, tournsize=4)
    return tb


def main(smoke: bool = False, seed: int = 2207):
    random.seed(seed)
    tb = build_toolbox()

    pop = tb.population(n=60 if smoke else 240)
    ngen = 12 if smoke else 60

    elite = tools.HallOfFame(3)
    stats = tools.Statistics(lambda ind: ind.fitness.values[0])
    stats.register("mean", lambda vals: sum(vals) / len(vals))
    stats.register("max", max)

    pop, log = algorithms.eaSimple(
        pop, tb, cxpb=0.6, mutpb=0.3, ngen=ngen,
        stats=stats, halloffame=elite, verbose=False)

    best = elite[0].fitness.values[0]
    print(f"Best stripe match: {best:.0f}/{BITS} "
          f"(gen-{len(log) - 1} mean {log[-1]['mean']:.1f})")
    return best


if __name__ == "__main__":
    main()
