"""Hillis-style host-parasite coevolution of sorting networks.

Counterpart of /root/reference/examples/coev/hillis.py: hosts are
sorting networks (minimising misses), parasites are sets of hard test
sequences (maximising the misses they induce); both populations evolve
against each other on index-paired encounters with shared outcome
values (hillis.py:131-134).
"""

import jax
import jax.numpy as jnp

from deap_tpu import coev, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

from examples.ga.sortingnetwork import apply_network

DIM = 6
MAX_PAIRS = 20
N_TESTS = 8


def main(smoke: bool = False):
    n = 100 if not smoke else 40
    ngen = 30 if not smoke else 8

    def init_host(key):
        k1, k2 = jax.random.split(key)
        a = jax.random.randint(k1, (MAX_PAIRS,), 0, DIM)
        off = jax.random.randint(k2, (MAX_PAIRS,), 1, DIM)
        b = (a + off) % DIM
        return jnp.stack([jnp.minimum(a, b), jnp.maximum(a, b)], axis=-1)

    def init_parasite(key):
        return jax.random.bernoulli(key, 0.5, (N_TESTS, DIM)).astype(
            jnp.int32)

    def eval_pair(host, parasite):
        """misses of the host network on the parasite's test set."""
        out = apply_network(host, jnp.int32(MAX_PAIRS), parasite)
        ref = jnp.sort(parasite, axis=1)
        return (out != ref).any(axis=1).sum().astype(jnp.float32)

    def mate_host(key, a, b):
        cut = jax.random.randint(key, (), 1, MAX_PAIRS)
        sel = (jnp.arange(MAX_PAIRS) < cut)[:, None]
        return jnp.where(sel, a, b), jnp.where(sel, b, a)

    def mut_host(key, a):
        k1, k2, k3 = jax.random.split(key, 3)
        i = jax.random.randint(k1, (), 0, MAX_PAIRS)
        x = jax.random.randint(k2, (), 0, DIM)
        off = jax.random.randint(k3, (), 1, DIM)
        y = (x + off) % DIM
        return a.at[i].set(jnp.stack([jnp.minimum(x, y),
                                      jnp.maximum(x, y)]))

    def mate_parasite(key, a, b):
        sel = jax.random.bernoulli(key, 0.5, (N_TESTS, 1))
        return jnp.where(sel, a, b), jnp.where(sel, b, a)

    def mut_parasite(key, a):
        flip = jax.random.bernoulli(key, 0.05, a.shape)
        return jnp.where(flip, 1 - a, a)

    htb = Toolbox()
    htb.register("mate", mate_host)
    htb.register("mutate", mut_host)
    htb.register("select", ops.sel_tournament, tournsize=3)
    ptb = Toolbox()
    ptb.register("mate", mate_parasite)
    ptb.register("mutate", mut_parasite)
    ptb.register("select", ops.sel_tournament, tournsize=3)

    hosts = init_population(jax.random.key(74), n, init_host,
                            FitnessSpec((-1.0,)))
    parasites = init_population(jax.random.key(75), n, init_parasite,
                                FitnessSpec((1.0,)))
    hosts, parasites = coev.competitive_eval(hosts, parasites, eval_pair)

    step = jax.jit(lambda k, h, p: coev.competitive_step(
        k, h, p, htb, ptb, eval_pair, 0.5, 0.3, 0.5, 0.3))
    key = jax.random.key(76)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        hosts, parasites = step(kg, hosts, parasites)

    best_misses = float(-hosts.wvalues.max())
    print(f"Best host misses on its parasite suite: {best_misses}")
    return best_misses


if __name__ == "__main__":
    main()
