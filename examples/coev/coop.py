"""Cooperative coevolution: species evolve parts of one solution.

Counterpart of /root/reference/examples/coev/coop_base.py and its
ladder (coop_niche/gen/adapt/evol — Potter & De Jong 2001): each
species evolves one segment of a target bitstring; an individual's
fitness is the match strength of the solution assembled with the other
species' representatives (matchSetStrength, coop_base.py:57-66).
"""

import jax
import jax.numpy as jnp

from deap_tpu import coev, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

N_SPECIES = 4
SEG = 16


def main(smoke: bool = False):
    species_size = 50 if not smoke else 24
    rounds = 30 if not smoke else 8
    target = jax.random.bernoulli(
        jax.random.key(77), 0.5, (N_SPECIES * SEG,)).astype(jnp.int8)

    def evaluate(i, genomes, reps):
        parts = [jnp.broadcast_to(reps[j], genomes.shape) if j != i
                 else genomes for j in range(N_SPECIES)]
        assembled = jnp.concatenate(parts, axis=-1)
        return jnp.sum(assembled == target, axis=-1).astype(jnp.float32)

    tb = Toolbox()
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=1.0 / SEG)
    tb.register("select", ops.sel_tournament, tournsize=3)

    species = [
        init_population(jax.random.key(80 + i), species_size,
                        ops.bernoulli_genome(SEG), FitnessSpec((1.0,)))
        for i in range(N_SPECIES)
    ]
    zero = [jnp.zeros((SEG,), jnp.int8)] * N_SPECIES
    species = [coev.coop_eval_species(i, s, zero, evaluate)
               for i, s in enumerate(species)]
    reps = coev.coop_representatives(species)

    step = jax.jit(lambda k, sp, r: coev.coop_step(
        k, sp, r, tb, evaluate, cxpb=0.6, mutpb=1.0))
    key = jax.random.key(78)
    for r in range(rounds):
        key, kr = jax.random.split(key)
        species, reps = step(kr, species, reps)
    best = max(float(s.wvalues.max()) for s in species)
    print(f"Best assembled match: {best} / {N_SPECIES * SEG}")
    return best


if __name__ == "__main__":
    main()
