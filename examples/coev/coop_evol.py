"""Cooperative co-evolution with an evolving number of species.

Counterpart of the reference's Potter & De Jong ladder
(/root/reference/examples/coev/coop_niche.py, coop_gen.py,
coop_adapt.py, coop_evol.py — sections 4.2.1-4.2.4 of *Cooperative
Coevolution: An Architecture for Evolving Co-adapted Subcomponents*,
2001): species of bitstrings cooperatively cover a noisy schemata-match
problem; the match-set strength of an individual assembled with the
other species' representatives is its fitness
(coop_base.py:57-66), and in the full ladder stagnation triggers adding
a fresh species while weak contributors go extinct
(coop_evol.py:120-146).

``mode`` selects the rung:

- ``"niche"`` — fixed one-species-per-schema setup (coop_niche.py):
  shows species settling into distinct niches.
- ``"gen"``  — fixed species count chosen up front (coop_gen.py's
  NUM_SPECIES study).
- ``"adapt"`` — start with one species and *add* one on a FIXED
  schedule, every ``ADAPT_LENGTH`` rounds (coop_adapt.py:18 "A species
  is added each 100 generations"; its g counts per-species generations,
  ours counts whole rounds — same ladder shape, scaled).
- ``"evol"`` — stagnation of the best collaboration triggers an
  addition, and species whose contribution falls below the extinction
  threshold are removed first (coop_evol.py:130-146).

The per-round species step is the jit'd tensor program
(`coev.coop_step`); only the add/remove decisions — data-dependent
*structure* changes — run on the host, recompiling per species count
(SURVEY.md §7.3 "data-dependent control flow ... keep on host around
the jit'd inner loop").
"""

import jax
import jax.numpy as jnp

from deap_tpu import coev, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

IND_SIZE = 64
SPECIES_SIZE = 50
TARGET_SIZE = 30
IMPROVEMENT_THRESHOLD = 0.5
IMPROVEMENT_LENGTH = 5
EXTINCTION_THRESHOLD = 5.0
ADAPT_LENGTH = 12  # rounds between scheduled additions (adapt mode)


def block_schematas(n_types: int, length: int) -> list:
    """Structured schemata in the style of nicheSchematas
    (coop_niche.py:36-42): each type fixes a contiguous '1' block over
    its own stretch of the string, '#' (noise) elsewhere."""
    rept = length // n_types
    out = []
    for i in range(n_types):
        s = "#" * (i * rept) + "1" * rept
        out.append(s + "#" * (length - len(s)))
    return out


def init_target_set(key, schemata: str, size: int) -> jnp.ndarray:
    """[size, L] noisy targets from one schema (initTargetSet,
    coop_base.py:29-42): fixed positions copy the schema, '#' positions
    are uniform random bits per target."""
    L = len(schemata)
    rand = jax.random.bernoulli(key, 0.5, (size, L)).astype(jnp.int8)
    fixed = jnp.array([c in "01" for c in schemata])
    vals = jnp.array([1 if c == "1" else 0 for c in schemata], jnp.int8)
    return jnp.where(fixed[None, :], vals[None, :], rand)


def _new_species(key):
    return init_population(key, SPECIES_SIZE,
                           ops.bernoulli_genome(IND_SIZE, dtype=jnp.int8),
                           FitnessSpec((1.0,)))


def main(smoke: bool = False, mode: str = "evol", verbose: bool = True,
         num_species: int = 1, seed: int = 0,
         return_trace: bool = False):
    if mode not in ("niche", "gen", "adapt", "evol"):
        raise ValueError(f"unknown mode {mode!r}")

    n_types = 3
    rounds = (40 if mode in ("adapt", "evol") else 30) if not smoke else 6
    # smoke must still exercise the adapt rung's addition path
    adapt_length = ADAPT_LENGTH if not smoke else max(2, rounds // 2)
    keys = iter(jax.random.split(jax.random.key(seed), 4096))

    schematas = block_schematas(n_types, IND_SIZE)
    per = TARGET_SIZE // n_types
    targets = jnp.concatenate(
        [init_target_set(next(keys), s, per) for s in schematas])

    tb = Toolbox()
    tb.register("mate", ops.cx_two_point)
    tb.register("mutate", ops.mut_flip_bit, indpb=1.0 / IND_SIZE)
    tb.register("select", ops.sel_tournament, tournsize=3)

    def evaluate(i, genomes, reps):
        return coev.match_set_strength(i, genomes, reps, targets)

    if mode == "niche":
        num_species = n_types
    elif mode in ("adapt", "evol"):
        num_species = 1
    species = [_new_species(next(keys)) for _ in range(num_species)]
    # random initial representatives (coop_evol.py:77)
    reps = [jax.tree_util.tree_map(lambda a: a[0], s.genomes)
            for s in species]
    species = [coev.coop_eval_species(i, s, reps, evaluate)
               for i, s in enumerate(species)]
    reps = coev.coop_representatives(species)

    # one jit'd program per species count; structure changes recompile
    @jax.jit
    def _round(key, sp, r):
        return coev.coop_step(key, sp, r, tb, evaluate,
                              cxpb=0.6, mutpb=1.0)

    history = []
    trace = []  # (round, n_species, best) — the rung's observable shape
    for rnd in range(rounds):
        species, reps = _round(next(keys), tuple(species), tuple(reps))
        best = float(max(float(s.wvalues.max()) for s in species))
        history.append(best)
        trace.append((rnd, len(species), best))
        if verbose:
            print(f"round {rnd:3d}  species {len(species)}  "
                  f"best collaboration {best:.3f}")

        add = False
        if mode == "adapt":
            # fixed schedule, like coop_adapt.py's add-every-100-gens
            add = (rnd + 1) % adapt_length == 0 and rnd + 1 < rounds
        elif mode == "evol" and len(history) >= IMPROVEMENT_LENGTH:
            add = (history[-1] - history[-IMPROVEMENT_LENGTH]
                   < IMPROVEMENT_THRESHOLD)
        if add:
            if mode == "evol" and len(species) > 1:
                contribs = coev.match_set_contributions(reps, targets)
                keep = [i for i in range(len(species))
                        if float(contribs[i]) >= EXTINCTION_THRESHOLD]
                if keep:  # never extinguish everything
                    species = [species[i] for i in keep]
                    reps = [reps[i] for i in keep]
            s = _new_species(next(keys))
            reps.append(jax.tree_util.tree_map(lambda a: a[0], s.genomes))
            species.append(
                coev.coop_eval_species(len(species), s, reps, evaluate))
            reps = coev.coop_representatives(species)
            history = []
            if verbose:
                print(f"  {'schedule' if mode == 'adapt' else 'stagnation'}:"
                      f" now {len(species)} species")

    final = float(max(float(s.wvalues.max()) for s in species))
    if verbose:
        print(f"final best collaboration: {final:.3f} "
              f"({len(species)} species)")
    if return_trace:
        return {"final": final, "trace": trace, "reps": reps,
                "schematas": schematas, "targets": targets}
    return final


if __name__ == "__main__":
    main()
