"""Competitive coevolution for symbolic regression.

Counterpart of /root/reference/examples/coev/symbreg.py: formulas
coevolve against training-point subsets — the point population seeks
samples that expose formula errors, the formula population minimises
error on its paired sample set.
"""

import jax
import jax.numpy as jnp

from deap_tpu import coev, gp, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

MAX_LEN = 48
N_POINTS = 10


def target(x):
    return x ** 4 + x ** 3 + x ** 2 + x


def main(smoke: bool = False):
    n = 100 if not smoke else 40
    ngen = 20 if not smoke else 6

    pset = gp.math_set(n_args=1, trig=False)
    gen = gp.gen_half_and_half(pset, MAX_LEN, 1, 3)
    interp = gp.make_interpreter(pset, MAX_LEN)

    def eval_pair(formula, points):
        X = points[:, None]
        err = jnp.mean((interp(formula, X) - target(points)) ** 2)
        return jnp.clip(err, 0.0, 1e6)

    ftb = Toolbox()
    ftb.register("mate", gp.make_cx_one_point(pset))
    ftb.register("mutate", gp.make_mut_uniform(
        pset, gp.make_generator(pset, 16, 0, 2, "grow")))
    ftb.register("select", ops.sel_tournament, tournsize=3)

    ptb = Toolbox()
    ptb.register("mate", ops.cx_blend, alpha=0.1)
    ptb.register("mutate", ops.mut_gaussian, mu=0.0, sigma=0.2, indpb=0.3)
    ptb.register("select", ops.sel_tournament, tournsize=3)

    formulas = init_population(jax.random.key(79), n, gen,
                               FitnessSpec((-1.0,)))
    points = init_population(jax.random.key(80), n,
                             ops.uniform_genome(N_POINTS, -1.0, 1.0),
                             FitnessSpec((1.0,)))
    formulas, points = coev.competitive_eval(formulas, points, eval_pair)

    step = jax.jit(lambda k, f, p: coev.competitive_step(
        k, f, p, ftb, ptb, eval_pair))
    key = jax.random.key(81)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        formulas, points = step(kg, formulas, points)
    best = float(-formulas.wvalues.max())
    print(f"Best formula error on its adversarial sample: {best:.4f}")
    return best


if __name__ == "__main__":
    main()
