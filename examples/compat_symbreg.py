"""The reference's symbolic-regression program, unchanged except imports.

/root/reference/examples/gp/symbreg.py's program shape (seed 318 at
symbreg.py:73) running verbatim on :mod:`deap_tpu.compat` — the GP half
of docs/porting.md's drop-in route: ``PrimitiveSet`` with Python
callables, an ephemeral constant, ``staticLimit`` decorators,
``MultiStatistics`` and ``eaSimple``. The only semantic upgrade is that
``compile`` interprets the tree instead of ``eval``-ing generated
source.
"""

import math
import operator
import random

from deap_tpu.compat import algorithms, base, creator, gp, tools


def protectedDiv(left, right):
    try:
        return left / right
    except ZeroDivisionError:
        return 1


def main(smoke: bool = False, seed: int = 318):
    random.seed(seed)

    pset = gp.PrimitiveSet("MAIN", 1)
    pset.addPrimitive(operator.add, 2)
    pset.addPrimitive(operator.sub, 2)
    pset.addPrimitive(operator.mul, 2)
    pset.addPrimitive(protectedDiv, 2)
    pset.addPrimitive(operator.neg, 1)
    pset.addPrimitive(math.cos, 1)
    pset.addPrimitive(math.sin, 1)
    pset.addEphemeralConstant("rand101", lambda: random.randint(-1, 1))
    pset.renameArguments(ARG0="x")

    creator.create("FitnessMin", base.Fitness, weights=(-1.0,))
    creator.create("Individual", gp.PrimitiveTree,
                   fitness=creator.FitnessMin)

    toolbox = base.Toolbox()
    toolbox.register("expr", gp.genHalfAndHalf, pset=pset, min_=1, max_=2)
    toolbox.register("individual", tools.initIterate, creator.Individual,
                     toolbox.expr)
    toolbox.register("population", tools.initRepeat, list,
                     toolbox.individual)
    toolbox.register("compile", gp.compile, pset=pset)

    def evalSymbReg(individual, points):
        func = toolbox.compile(expr=individual)
        sqerrors = ((func(x) - x ** 4 - x ** 3 - x ** 2 - x) ** 2
                    for x in points)
        return math.fsum(sqerrors) / len(points),

    toolbox.register("evaluate", evalSymbReg,
                     points=[x / 10.0 for x in range(-10, 10)])
    toolbox.register("select", tools.selTournament, tournsize=3)
    toolbox.register("mate", gp.cxOnePoint)
    toolbox.register("expr_mut", gp.genFull, min_=0, max_=2)
    toolbox.register("mutate", gp.mutUniform, expr=toolbox.expr_mut,
                     pset=pset)

    toolbox.decorate("mate", gp.staticLimit(
        key=operator.attrgetter("height"), max_value=17))
    toolbox.decorate("mutate", gp.staticLimit(
        key=operator.attrgetter("height"), max_value=17))

    pop = toolbox.population(n=300 if not smoke else 60)
    hof = tools.HallOfFame(1)

    stats_fit = tools.Statistics(lambda ind: ind.fitness.values)
    stats_size = tools.Statistics(len)
    mstats = tools.MultiStatistics(fitness=stats_fit, size=stats_size)
    import numpy

    mstats.register("avg", numpy.mean)
    mstats.register("min", numpy.min)

    pop, log = algorithms.eaSimple(
        pop, toolbox, 0.5, 0.1, 40 if not smoke else 8,
        stats=mstats, halloffame=hof, verbose=False)
    best_mse = hof[0].fitness.values[0]
    print(f"Best MSE: {best_mse:.6f}  ({hof[0]})")
    return best_mse


if __name__ == "__main__":
    main()
