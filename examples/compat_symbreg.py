"""Drop-in GP on :mod:`deap_tpu.compat`: regressing a damped sine.

Original demo code for the GP half of docs/porting.md's drop-in route.
It exercises the reference GP surface — ``PrimitiveSet`` over plain
Python callables with an ephemeral constant, ``genHalfAndHalf`` /
``genGrow`` tree generators, ``staticLimit`` bloat control,
``MultiStatistics`` and ``eaSimple`` — on its own problem: fit
``f(x) = sin(x) + x/2`` over [-3, 3] by mean absolute error, with a
parsimony-aware ``selDoubleTournament`` instead of plain tournament.
Surface covered (not the text): ``/root/reference/examples/gp/
symbreg.py:30-70`` (program shape), ``deap/gp.py:432-487``
(PrimitiveSet/compile), ``gp.py:890-931`` (staticLimit). ``compile``
here interprets the tree instead of ``eval``-ing generated source.
"""

import math
import operator
import random

from deap_tpu.compat import algorithms, base, creator, gp, tools


def safe_div(a, b):
    """Division with an epsilon guard instead of exception handling."""
    if abs(b) < 1e-9:
        return 1.0
    return a / b


def target(x):
    return math.sin(x) + 0.5 * x


def build_pset():
    pset = gp.PrimitiveSet("REGRESS", 1)
    pset.addPrimitive(operator.add, 2)
    pset.addPrimitive(operator.sub, 2)
    pset.addPrimitive(operator.mul, 2)
    pset.addPrimitive(safe_div, 2)
    pset.addPrimitive(math.sin, 1)
    pset.addEphemeralConstant(
        "coeff", lambda: round(random.uniform(-2.0, 2.0), 2))
    pset.renameArguments(ARG0="x")
    return pset


def main(smoke: bool = False, seed: int = 4411):
    random.seed(seed)
    pset = build_pset()

    creator.create("RegressFit", base.Fitness, weights=(-1.0,))
    creator.create("Program", gp.PrimitiveTree, fitness=creator.RegressFit)

    xs = [-3.0 + 6.0 * i / 29 for i in range(30)]
    ys = [target(x) for x in xs]

    tb = base.Toolbox()
    tb.register("expr_init", gp.genHalfAndHalf, pset=pset, min_=1, max_=3)
    tb.register("individual", tools.initIterate, creator.Program,
                tb.expr_init)
    tb.register("population", tools.initRepeat, list, tb.individual)
    tb.register("compile", gp.compile, pset=pset)

    def mean_abs_error(individual):
        func = tb.compile(expr=individual)
        err = sum(abs(func(x) - y) for x, y in zip(xs, ys))
        return (err / len(xs),)

    tb.register("evaluate", mean_abs_error)
    tb.register("select", tools.selDoubleTournament,
                fitness_size=4, parsimony_size=1.3, fitness_first=True)
    tb.register("mate", gp.cxOnePoint)
    tb.register("expr_mut", gp.genGrow, min_=0, max_=2)
    tb.register("mutate", gp.mutUniform, expr=tb.expr_mut, pset=pset)

    depth_cap = gp.staticLimit(
        key=operator.attrgetter("height"), max_value=12)
    tb.decorate("mate", depth_cap)
    tb.decorate("mutate", depth_cap)

    pop = tb.population(n=60 if smoke else 250)
    elite = tools.HallOfFame(1)

    err_stats = tools.Statistics(lambda ind: ind.fitness.values[0])
    size_stats = tools.Statistics(len)
    both = tools.MultiStatistics(error=err_stats, size=size_stats)
    both.register("min", min)
    both.register("mean", lambda vals: sum(vals) / len(vals))

    pop, _log = algorithms.eaSimple(
        pop, tb, cxpb=0.55, mutpb=0.25, ngen=8 if smoke else 40,
        stats=both, halloffame=elite, verbose=False)

    best_err = elite[0].fitness.values[0]
    print(f"Best mean |error|: {best_err:.4f}  ({elite[0]})")
    return best_err


if __name__ == "__main__":
    main()
