"""Time every example program — counterpart of the reference's
historical timing harness (examples/speed.txt is its program list;
SURVEY.md §4.5).

Runs each program's ``main(smoke=True)`` and prints one JSON line per
program: ``{"example": ..., "seconds": ..., "quality": ..., "ok":
...}`` — ``quality`` is whatever scalar the program's ``main``
returns (MSE, front size, best fitness...; see each program's
docstring for its meaning). Pass ``--full`` for the real (non-smoke)
configurations.

Usage::

    python examples/speed.py [--full] [--cpu] [--isolate] [--flagship]
                             [--report PATH] [pattern]

``--flagship`` restricts the sweep to one TPU-salient program per
family (the short-relay-window zoo subset, see ``FLAGSHIP``).

``--cpu`` forces the CPU backend (the environment's TPU plugin pins
``jax_platforms``, and a wedged tunnel hangs jax init — see bench.py's
probe; this flag is the manual override). ``--report PATH`` writes the
aggregate run as one JSON document — ``examples/ZOO_REPORT.json`` is
the committed artifact of the latest full-zoo validation.
"""

import datetime
import importlib
import json
import pathlib
import sys
import time


# One TPU-salient program per family — the short-relay-window zoo
# subset (``--flagship``): enough to show the examples run on the
# hardware they're named for without spending a window on all 53.
FLAGSHIP = (
    "examples.ga.onemax_fused",
    "examples.ga.nsga2_large",
    "examples.gp.symbreg",
    "examples.es.cma_minfct",
    "examples.ga.onemax_island_sharded",
    "examples.neuroevolution.cartpole",
)


def discover():
    root = pathlib.Path(__file__).resolve().parent
    out = []
    for p in sorted(root.rglob("*.py")):
        if p.name.startswith("_") or p.name == "speed.py":
            continue
        rel = p.relative_to(root.parent).with_suffix("")
        out.append(".".join(rel.parts))
    return out


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    full = "--full" in argv
    if full:
        argv.remove("--full")
    force_cpu = "--cpu" in argv
    if force_cpu:
        argv.remove("--cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    isolate = "--isolate" in argv
    if isolate:
        argv.remove("--isolate")
    flagship = "--flagship" in argv
    if flagship:
        argv.remove("--flagship")
    resume = "--resume" in argv
    if resume:
        argv.remove("--resume")
    report_path = None
    if "--report" in argv:
        i = argv.index("--report")
        if i + 1 >= len(argv):
            sys.exit("usage: speed.py [--full] [--cpu] [--isolate] "
                     "[--report PATH] [pattern] — --report needs a path")
        report_path = pathlib.Path(argv[i + 1])
        del argv[i:i + 2]
    only = None
    if "--only" in argv:                  # exact-match (subprocess mode)
        i = argv.index("--only")
        if i + 1 >= len(argv):
            sys.exit("usage: speed.py [--full] [--cpu] [--isolate] "
                     "[--report PATH] [--only NAME] [pattern] — "
                     "--only needs a module name")
        only = argv[i + 1]
        del argv[i:i + 2]
    pattern = argv[0] if argv else ""

    root = pathlib.Path(__file__).resolve().parent.parent
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))

    # the capture queue's completion predicate keeps its own copy of
    # the flagship list (it cannot import us); fail loudly on drift
    try:
        from tpu_capture import ZOO_FLAGSHIP
        if FLAGSHIP != ZOO_FLAGSHIP:
            sys.exit("FLAGSHIP drifted from tpu_capture.ZOO_FLAGSHIP")
    except ImportError:
        pass  # running from an installed copy without the harness

    def write_report(results):
        # rewritten after every program: a crash partway (one process
        # accumulating 50+ XLA programs can exhaust compile memory)
        # still leaves a valid partial artifact. The backend comes
        # from the per-program records — the driver must NOT import
        # jax in --isolate mode (initialising a backend in the parent
        # would contend with the children on a single-client TPU).
        n_ok = sum(1 for r in results if r["ok"] is True)
        backends = sorted({r["backend"] for r in results
                           if r.get("backend")})
        report = {
            "date": datetime.date.today().isoformat(),
            "mode": "full" if full else "smoke",
            "backend": backends[0] if len(backends) == 1 else backends,
            "passed": n_ok,
            "total": len(results),
            "results": results,
        }
        report_path.write_text(json.dumps(report, indent=1) + "\n")
        return n_ok

    results = []
    done = set()
    if resume and report_path is not None and report_path.exists():
        # cross-window resume (relay windows are scarce): keep prior
        # rows that resolved ON TPU and only re-run the rest — without
        # this, a window that died mid-sweep discards every earlier
        # window's on-chip evidence at the first write_report
        try:
            prior = json.loads(report_path.read_text())
        except (ValueError, OSError):
            prior = {}
        for r in prior.get("results", []):
            if (r.get("backend") == "tpu"
                    and r.get("config") == ("full" if full else "smoke")):
                results.append(r)
                done.add(r["example"])
    for name in discover():
        if only is not None and name != only:
            continue
        if flagship and name not in FLAGSHIP:
            continue
        if pattern and pattern not in name:
            continue
        if name in done:
            print(f'{{"example": "{name}", "skipped": "captured"}}',
                  flush=True)
            continue
        if isolate:
            rec = _run_isolated(name, full, force_cpu)
        else:
            t0 = time.perf_counter()
            ok = True
            quality = None
            try:
                mod = importlib.import_module(name)
                out = mod.main(smoke=not full)
                if isinstance(out, (int, float)):
                    quality = round(float(out), 6)
            except Exception as e:  # keep timing the rest
                ok = f"{type(e).__name__}: {e}"
            import jax

            rec = {
                "example": name,
                "config": "full" if full else "smoke",
                "seconds": round(time.perf_counter() - t0, 2),
                "quality": quality,
                "ok": ok,
                "backend": jax.default_backend(),
            }
        results.append(rec)
        print(json.dumps(rec), flush=True)
        if report_path is not None:
            write_report(results)

    if report_path is not None:
        n_ok = write_report(results)
        print(f"report: {report_path} ({n_ok}/{len(results)} ok)",
              flush=True)


def _run_isolated(name: str, full: bool, force_cpu: bool) -> dict:
    """Run one program in a fresh subprocess (own jax/XLA arena) and
    parse the single JSON line it prints — process isolation for long
    sweeps where one resident process would accumulate every example's
    compiled programs."""
    import subprocess

    args = [sys.executable, str(pathlib.Path(__file__).resolve())]
    if full:
        args.append("--full")
    if force_cpu:
        args.append("--cpu")
    args += ["--only", name]
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(args, capture_output=True, text=True,
                              timeout=3600)
    except subprocess.TimeoutExpired:
        # record the hang and keep sweeping — the whole point of
        # isolation is that one stuck program can't kill the report
        return {
            "example": name,
            "config": "full" if full else "smoke",
            "seconds": round(time.perf_counter() - t0, 2),
            "quality": None,
            "ok": "subprocess timeout (3600s)",
            "backend": None,
        }
    for line in proc.stdout.splitlines():
        try:
            rec = json.loads(line)
            if rec.get("example") == name:
                return rec
        except (ValueError, AttributeError):
            continue
    err_lines = proc.stderr.strip().splitlines()
    last_err = err_lines[-1] if err_lines else "no output"
    return {
        "example": name,
        "config": "full" if full else "smoke",
        "seconds": round(time.perf_counter() - t0, 2),
        "quality": None,
        "ok": f"subprocess rc={proc.returncode}: {last_err}",
        "backend": None,
    }


if __name__ == "__main__":
    main()
