"""Time every example program — counterpart of the reference's
historical timing harness (examples/speed.txt is its program list;
SURVEY.md §4.5).

Runs each program's ``main(smoke=True)`` and prints one JSON line per
program: ``{"example": ..., "seconds": ..., "ok": ...}``. Pass
``--full`` for the real (non-smoke) configurations.

Usage::

    python examples/speed.py [--full] [--cpu] [pattern]

``--cpu`` forces the CPU backend (the environment's TPU plugin pins
``jax_platforms``, and a wedged tunnel hangs jax init — see bench.py's
probe; this flag is the manual override).
"""

import importlib
import json
import pathlib
import sys
import time


def discover():
    root = pathlib.Path(__file__).resolve().parent
    out = []
    for p in sorted(root.rglob("*.py")):
        if p.name.startswith("_") or p.name == "speed.py":
            continue
        rel = p.relative_to(root.parent).with_suffix("")
        out.append(".".join(rel.parts))
    return out


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    full = "--full" in argv
    if full:
        argv.remove("--full")
    if "--cpu" in argv:
        argv.remove("--cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    pattern = argv[0] if argv else ""

    root = pathlib.Path(__file__).resolve().parent.parent
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))

    for name in discover():
        if pattern and pattern not in name:
            continue
        t0 = time.perf_counter()
        ok = True
        try:
            mod = importlib.import_module(name)
            mod.main(smoke=not full)
        except Exception as e:  # keep timing the rest
            ok = f"{type(e).__name__}: {e}"
        print(json.dumps({
            "example": name,
            "seconds": round(time.perf_counter() - t0, 2),
            "ok": ok,
        }), flush=True)


if __name__ == "__main__":
    main()
