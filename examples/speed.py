"""Time every example program — counterpart of the reference's
historical timing harness (examples/speed.txt is its program list;
SURVEY.md §4.5).

Runs each program's ``main(smoke=True)`` and prints one JSON line per
program: ``{"example": ..., "seconds": ..., "quality": ..., "ok":
...}`` — ``quality`` is whatever scalar the program's ``main``
returns (MSE, front size, best fitness...; see each program's
docstring for its meaning). Pass ``--full`` for the real (non-smoke)
configurations.

Usage::

    python examples/speed.py [--full] [--cpu] [--report PATH] [pattern]

``--cpu`` forces the CPU backend (the environment's TPU plugin pins
``jax_platforms``, and a wedged tunnel hangs jax init — see bench.py's
probe; this flag is the manual override). ``--report PATH`` writes the
aggregate run as one JSON document — ``examples/ZOO_REPORT.json`` is
the committed artifact of the latest full-zoo validation.
"""

import datetime
import importlib
import json
import pathlib
import sys
import time


def discover():
    root = pathlib.Path(__file__).resolve().parent
    out = []
    for p in sorted(root.rglob("*.py")):
        if p.name.startswith("_") or p.name == "speed.py":
            continue
        rel = p.relative_to(root.parent).with_suffix("")
        out.append(".".join(rel.parts))
    return out


def main(argv=None):
    argv = list(sys.argv[1:] if argv is None else argv)
    full = "--full" in argv
    if full:
        argv.remove("--full")
    if "--cpu" in argv:
        argv.remove("--cpu")
        import jax

        jax.config.update("jax_platforms", "cpu")
    report_path = None
    if "--report" in argv:
        i = argv.index("--report")
        if i + 1 >= len(argv):
            sys.exit("usage: speed.py [--full] [--cpu] "
                     "[--report PATH] [pattern] — --report needs a path")
        report_path = pathlib.Path(argv[i + 1])
        del argv[i:i + 2]
    pattern = argv[0] if argv else ""

    root = pathlib.Path(__file__).resolve().parent.parent
    if str(root) not in sys.path:
        sys.path.insert(0, str(root))

    results = []
    for name in discover():
        if pattern and pattern not in name:
            continue
        t0 = time.perf_counter()
        ok = True
        quality = None
        try:
            mod = importlib.import_module(name)
            out = mod.main(smoke=not full)
            if isinstance(out, (int, float)):
                quality = round(float(out), 6)
        except Exception as e:  # keep timing the rest
            ok = f"{type(e).__name__}: {e}"
        rec = {
            "example": name,
            "config": "full" if full else "smoke",
            "seconds": round(time.perf_counter() - t0, 2),
            "quality": quality,
            "ok": ok,
        }
        results.append(rec)
        print(json.dumps(rec), flush=True)

    if report_path is not None:
        import jax

        n_ok = sum(1 for r in results if r["ok"] is True)
        report = {
            "date": datetime.date.today().isoformat(),
            "mode": "full" if full else "smoke",
            "backend": jax.default_backend(),
            "passed": n_ok,
            "total": len(results),
            "results": results,
        }
        report_path.write_text(json.dumps(report, indent=1) + "\n")
        print(f"report: {report_path} ({n_ok}/{len(results)} ok)",
              flush=True)


if __name__ == "__main__":
    main()
