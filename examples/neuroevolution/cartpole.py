"""Neuroevolution: evolve MLP weights for CartPole over the device mesh.

BASELINE.json config #5: a GA over flat MLP weight vectors whose fitness
is a batched CartPole rollout; the population is sharded over the local
device mesh so rollouts run data-parallel — the TPU-native counterpart
of farming per-individual simulator processes through ``toolbox.map``.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.benchmarks.cartpole import mlp_policy, rollout_population
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import population_mesh, shard_population


def main(smoke: bool = False, pop_size: int = None):
    n = pop_size or (2048 if not smoke else 128)
    ngen = 30 if not smoke else 5
    episodes = 3       # fitness = mean over episodes (noise reduction)
    max_steps = 200 if smoke else 500

    policy, n_params = mlp_policy((4, 16, 2))

    def evaluate(genomes):
        keys = jax.random.split(jax.random.key(123), episodes)
        # compaction cascade: alive episodes are compacted into
        # halving buffers as the population dies off, so cost tracks
        # the survivor curve instead of paying max_steps per episode
        return rollout_population(policy, genomes, keys,
                                  max_steps).mean(axis=1)

    toolbox = Toolbox()
    toolbox.register("evaluate", evaluate)
    toolbox.register("mate", ops.cx_blend, alpha=0.1)
    toolbox.register("mutate", ops.mut_gaussian, mu=0.0, sigma=0.3,
                     indpb=0.1)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(90), n,
                          ops.normal_genome(n_params, sigma=0.5),
                          FitnessSpec((1.0,)))
    mesh = population_mesh()
    pop = shard_population(pop, mesh)

    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(91), pop, toolbox, cxpb=0.5, mutpb=0.5, ngen=ngen)
    best = float(pop.wvalues.max())
    print(f"Best mean episode length: {best:.1f} / {max_steps} "
          f"({n} policies x {jax.device_count()} devices)")
    return best


if __name__ == "__main__":
    main()
