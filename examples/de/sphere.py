"""Differential evolution on the sphere function.

Counterpart of /root/reference/examples/de/sphere.py (a DE variant with
per-generation best tracking on sphere).
"""

import jax

from deap_tpu import benchmarks, strategies
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.ops import uniform_genome


def main(smoke: bool = False):
    n, ndim = 300, 20
    ngen = 200 if not smoke else 25

    de = strategies.DifferentialEvolution(
        evaluate=lambda g: jax.vmap(benchmarks.sphere)(g)[:, 0],
        F=0.5, CR=0.9, spec=FitnessSpec((-1.0,)))
    pop = init_population(jax.random.key(59), n,
                          uniform_genome(ndim, -5.0, 5.0),
                          FitnessSpec((-1.0,)))
    pop, hist = de.run(jax.random.key(60), pop, ngen)
    best = float(-pop.wvalues.max())
    print(f"Best sphere value: {best:.3e}")
    return best


if __name__ == "__main__":
    main()
