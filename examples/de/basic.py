"""Differential evolution, basic rand/1/bin scheme.

Counterpart of /root/reference/examples/de/basic.py: ``y = a + F(b - c)``
with three distinct random donors per target (the reference draws them
with ``selRandom(k=3)``, basic.py:36) and binomial crossover, on
Griewank.
"""

import jax
import jax.numpy as jnp

from deap_tpu import benchmarks, strategies
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.ops import uniform_genome


def main(smoke: bool = False):
    n, ndim = 300, 10
    ngen = 200 if not smoke else 25

    de = strategies.DifferentialEvolution(
        evaluate=lambda g: jax.vmap(benchmarks.griewank)(g)[:, 0],
        F=0.25, CR=0.25, spec=FitnessSpec((-1.0,)))
    pop = init_population(jax.random.key(57), n,
                          uniform_genome(ndim, -100.0, 100.0),
                          FitnessSpec((-1.0,)))
    pop, hist = de.run(jax.random.key(58), pop, ngen)
    best = float(-pop.wvalues.max())
    print(f"Best griewank value: {best:.6f}")
    return best


if __name__ == "__main__":
    main()
