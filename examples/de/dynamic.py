"""Differential evolution on a dynamic landscape (MovingPeaks).

Counterpart of /root/reference/examples/de/dynamic.py: DE tracking the
moving-peaks benchmark, re-evaluating the population after each
landscape change.
"""

import jax
import jax.numpy as jnp

from deap_tpu import strategies
from deap_tpu.benchmarks import movingpeaks as mp
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.ops import uniform_genome


def main(smoke: bool = False):
    n, ndim = 100, 2
    epochs = 6 if not smoke else 3
    gens_per_epoch = 20 if not smoke else 6

    cfg = mp.MovingPeaksConfig(dim=ndim, **{
        k: v for k, v in mp.SCENARIO_1.items()
        if k not in ("pfunc", "bfunc")})
    state = mp.mp_init(jax.random.key(61), cfg)

    pop = init_population(
        jax.random.key(62), n,
        uniform_genome(ndim, cfg.min_coord, cfg.max_coord),
        FitnessSpec((1.0,)))

    key = jax.random.key(63)
    for epoch in range(epochs):
        de = strategies.DifferentialEvolution(
            evaluate=lambda g: mp.mp_evaluate(cfg, state, g)[1][:, 0],
            F=0.5, CR=0.9, spec=FitnessSpec((1.0,)))
        key, ke = jax.random.split(key)
        pop, _ = de.run(ke, pop, gens_per_epoch)
        best = float(pop.wvalues.max())
        gm = float(mp.global_maximum(cfg, state))
        print(f"epoch {epoch}: best {best:.2f} / optimum {gm:.2f}")
        # the landscape moves; stored fitness is stale → invalidate all
        state = mp.change_peaks(cfg, state)
        pop = pop.invalidate(jnp.ones(n, bool))
    return best


if __name__ == "__main__":
    main()
