"""Population-based incremental learning on OneMax.

Counterpart of /root/reference/examples/eda/pbil.py: a probability
vector generates bitstring samples and learns toward the best
(eaGenerateUpdate protocol, pbil.py:71-81).
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, strategies
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False):
    length = 50
    ngen = 100 if not smoke else 20

    pbil = strategies.PBIL(ndim=length, lambda_=20, learning_rate=0.3,
                           mut_prob=0.1, mut_shift=0.05)
    toolbox = Toolbox()
    toolbox.register("generate", pbil.generate)
    toolbox.register("update", pbil.update)
    toolbox.register("evaluate",
                     lambda g: g.sum(-1).astype(jnp.float32))

    state, logbook, _ = algorithms.ea_generate_update(
        jax.random.key(64), pbil.initial_state(), toolbox, ngen,
        spec=FitnessSpec((1.0,)))
    # a converged probability vector saturates toward 1.0
    conv = float(state.prob_vector.mean())
    print(f"Mean probability after {ngen} gens: {conv:.3f}")
    return conv


if __name__ == "__main__":
    main()
