"""Estimation of multivariate normal algorithm (EMNA_global).

Counterpart of /root/reference/examples/eda/emna.py: sample a Gaussian,
keep the best half, refit mean/covariance — the ask-tell protocol on a
continuous sphere problem.
"""

import jax

from deap_tpu import algorithms, benchmarks, strategies
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox

N = 5


def main(smoke: bool = False):
    ngen = 150 if not smoke else 25
    emna = strategies.EMNA(centroid=[5.0] * N, sigma=1.0, mu=30,
                           lambda_=100)
    toolbox = Toolbox()
    toolbox.register("generate", emna.generate)
    toolbox.register("update", emna.update)
    toolbox.register("evaluate",
                     lambda g: jax.vmap(benchmarks.sphere)(g)[:, 0])

    state, logbook, _ = algorithms.ea_generate_update(
        jax.random.key(65), emna.initial_state(), toolbox, ngen,
        spec=FitnessSpec((-1.0,)))
    best = float(benchmarks.sphere(state.centroid)[0])
    print(f"Centroid sphere value: {best:.3e}")
    return best


if __name__ == "__main__":
    main()
