"""NSGA-III on DTLZ2 with uniform reference points.

Counterpart of /root/reference/examples/ga/nsga3.py (132 LoC): DTLZ2
with 3 objectives, ``uniform_reference_points(nobj=3, p=12)``, SBX +
polynomial variation, NSGA-III niching selection.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, benchmarks, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import concat, gather, init_population
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False):
    nobj, p = 3, 12
    ref_points = mo.uniform_reference_points(nobj, p)
    mu = int(ref_points.shape[0] + (4 - ref_points.shape[0] % 4) % 4)
    ngen = 100 if not smoke else 10
    ndim = nobj + 4

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda g: jax.vmap(benchmarks.dtlz2, in_axes=(0, None))(
                         g, nobj))
    toolbox.register("mate", ops.cx_simulated_binary_bounded,
                     eta=30.0, low=0.0, up=1.0)
    toolbox.register("mutate", ops.mut_polynomial_bounded,
                     eta=20.0, low=0.0, up=1.0, indpb=1.0 / ndim)
    toolbox.register("select", ops.sel_tournament, tournsize=2)

    pop = init_population(jax.random.key(21), mu,
                          ops.uniform_genome(ndim, 0.0, 1.0),
                          FitnessSpec((-1.0,) * nobj))
    pop = algorithms.evaluate_invalid(pop, toolbox.evaluate)

    @jax.jit
    def generation(key, pop):
        k_sel, k_var, k_niche = jax.random.split(key, 3)
        idx = toolbox.select(k_sel, pop.wvalues, pop.size)
        off = algorithms.var_and(k_var, gather(pop, idx), toolbox,
                                 cxpb=1.0, mutpb=1.0)
        off = algorithms.evaluate_invalid(off, toolbox.evaluate)
        pool = concat([pop, off])
        keep = mo.sel_nsga3(k_niche, pool.wvalues, mu, ref_points)
        return gather(pool, keep)

    key = jax.random.key(22)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        pop = generation(kg, pop)

    spread = float(pop.fitness.max(0).min())
    print(f"Final population size {pop.size}, objective spread {spread:.3f}")
    return pop


if __name__ == "__main__":
    main()
