"""OneMax, short form — one call to ea_simple.

Counterpart of /root/reference/examples/ga/onemax_short.py (the
README's canonical example, README.md:74-104): toolbox registration +
``algorithms.eaSimple`` with stats and a hall of fame. Here the whole
40-generation run is a single compiled scan.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.support.hof import hof_best
from deap_tpu.support.stats import fitness_stats


def main(smoke: bool = False):
    n, ngen = (300, 40) if not smoke else (60, 10)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(64), n,
                          ops.bernoulli_genome(100), FitnessSpec((1.0,)))
    pop, logbook, hof = algorithms.ea_simple(
        jax.random.key(65), pop, toolbox, cxpb=0.5, mutpb=0.2, ngen=ngen,
        stats=fitness_stats(), halloffame_size=1, verbose=not smoke)
    genome, w = hof_best(hof)
    print("Best:", float(w[0]))
    return float(w[0])


if __name__ == "__main__":
    main()
