"""Hypervolume-indicator-based multi-objective selection.

Counterpart of /root/reference/examples/ga/mo_rhv.py: survivors chosen
by discarding the least-hypervolume-contributing individual of the
worst front (the leave-one-out contribution the native extension
computes, deap/tools/indicator.py:10-31).
"""

import jax
import jax.numpy as jnp
import numpy as np

from deap_tpu import algorithms, benchmarks, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import concat, gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.native import hv_contributions


def main(smoke: bool = False, mu: int = 40):
    ngen = 40 if not smoke else 8
    ndim = 30

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: jax.vmap(benchmarks.zdt1)(g))
    toolbox.register("mate", ops.cx_simulated_binary_bounded,
                     eta=20.0, low=0.0, up=1.0)
    toolbox.register("mutate", ops.mut_polynomial_bounded,
                     eta=20.0, low=0.0, up=1.0, indpb=1.0 / ndim)

    pop = init_population(jax.random.key(23), mu,
                          ops.uniform_genome(ndim, 0.0, 1.0),
                          FitnessSpec((-1.0, -1.0)))
    pop = algorithms.evaluate_invalid(pop, toolbox.evaluate)

    def hv_select(pool, k):
        """Drop the least-contributing individual one at a time
        (mo_rhv's selection; host-side like the reference's C call)."""
        fit = np.asarray(pool.fitness)
        alive = list(range(fit.shape[0]))
        ref = fit.max(axis=0) + 1.0
        while len(alive) > k:
            contribs = hv_contributions(fit[alive], ref)
            alive.pop(int(np.argmin(contribs)))
        return gather(pool, jnp.asarray(alive))

    @jax.jit
    def make_offspring(key, pop):
        k_par, k_var = jax.random.split(key)
        parents = mo.sel_tournament_dcd(k_par, pop.wvalues, pop.size)
        off = algorithms.var_and(k_var, gather(pop, parents), toolbox,
                                 cxpb=0.9, mutpb=1.0)
        return algorithms.evaluate_invalid(off, toolbox.evaluate)

    key = jax.random.key(24)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        pop = hv_select(concat([pop, make_offspring(kg, pop)]), mu)

    from deap_tpu.benchmarks.tools import hypervolume
    hv = float(hypervolume(pop.fitness, ref=jnp.asarray([11.0, 11.0]),
                           weights=(-1.0, -1.0)))
    print(f"Final hypervolume: {hv:.3f}")
    return hv


if __name__ == "__main__":
    main()
