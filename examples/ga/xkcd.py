"""The xkcd #287 "NP-complete" menu problem, multi-objective.

Counterpart of /root/reference/examples/ga/xkcd.py: order appetizers so
the total cost hits exactly $15.05, minimising both the price gap and
the total eating time; NSGA-II over integer order-count genomes.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

ITEMS = ["Mixed Fruit", "French Fries", "Side Salad", "Hot Wings",
         "Mozzarella Sticks", "Sampler Plate", "Barbecue"]
COST = jnp.asarray([2.15, 2.75, 3.35, 3.55, 4.20, 5.80, 6.55])
TIME = jnp.asarray([3.0, 5.0, 4.0, 6.0, 5.0, 10.0, 8.0])
TARGET = 15.05


def main(smoke: bool = False):
    n, ngen = (100, 40) if not smoke else (40, 10)

    def evaluate(counts):
        cost_gap = jnp.abs((counts * COST).sum(-1) - TARGET)
        time = (counts * TIME).sum(-1)
        return jnp.stack([cost_gap, time], axis=-1)

    toolbox = Toolbox()
    toolbox.register("evaluate", evaluate)
    toolbox.register("mate", ops.cx_uniform, indpb=0.5)
    toolbox.register("mutate", ops.mut_uniform_int, low=0, up=3, indpb=0.2)
    toolbox.register("select", mo.sel_nsga2)

    pop = init_population(jax.random.key(31), n,
                          ops.randint_genome(len(ITEMS), 0, 4),
                          FitnessSpec((-1.0, -1.0)))
    pop, logbook, _ = algorithms.ea_mu_plus_lambda(
        jax.random.key(32), pop, toolbox, mu=n, lambda_=n,
        cxpb=0.5, mutpb=0.4, ngen=ngen)
    gap = float(pop.fitness[:, 0].min())
    best = pop.genomes[jnp.argmin(pop.fitness[:, 0])]
    order = {name: int(c) for name, c in zip(ITEMS, best) if int(c)}
    print(f"Closest cost gap: ${gap:.2f} with order {order}")
    return gap


if __name__ == "__main__":
    main()
