"""OneMax with data-parallel fitness evaluation over local devices.

Counterpart of /root/reference/examples/ga/onemax_mp.py, which registers
``multiprocessing.Pool.map`` as ``toolbox.map`` (onemax_mp.py:58-59) to
spread evaluation over CPU cores. The TPU-native equivalent (SURVEY.md
§2.3 P2): shard the population axis over the local device mesh — the
same jit program runs SPMD on every device and XLA inserts the
collectives. Run under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``
to try multi-device on CPU.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import population_mesh, shard_population


def main(smoke: bool = False):
    n, ngen = (1024, 40) if not smoke else (64, 8)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(2), n,
                          ops.bernoulli_genome(100), FitnessSpec((1.0,)))
    mesh = population_mesh()
    pop = shard_population(pop, mesh)
    print(f"devices: {jax.device_count()}, population sharded over mesh "
          f"{mesh.shape}")

    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(3), pop, toolbox, 0.5, 0.2, ngen)
    best = float(pop.wvalues.max())
    print("Best:", best)
    return best


if __name__ == "__main__":
    main()
