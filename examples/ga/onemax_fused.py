"""OneMax with the fused Pallas generation kernel.

The same GA as ``onemax.py`` (reference examples/ga/onemax.py), but the
whole variation+evaluation — two-point crossover, flip-bit mutation and
popcount fitness — runs as one hand-written TPU kernel
(:func:`deap_tpu.ops.fused_variation_eval`), one HBM round trip per
generation, with per-gene random bits from the TPU hardware PRNG when
available. This is the configuration ``bench.py`` measures; see
``docs/advanced/kernels.md``.

Off-TPU the kernel runs under the Pallas interpreter with bits streamed
in (``prng='auto'``) — correct everywhere, fast on TPU.
"""

import jax
import jax.numpy as jnp

from deap_tpu import ops


def main(smoke: bool = False, seed: int = 64):
    n, ngen, length = (300, 40, 100) if not smoke else (64, 6, 32)

    key = jax.random.key(seed)
    k_init, k_run = jax.random.split(key)
    genomes = jax.random.bernoulli(k_init, 0.5, (n, length))
    fitness = genomes.sum(-1).astype(jnp.float32)

    @jax.jit
    def generation(carry, k):
        genomes, fitness = carry
        k_sel, k_var = jax.random.split(k)
        idx = ops.sel_tournament(k_sel, fitness[:, None], n, tournsize=3)
        children, newfit = ops.fused_variation_eval(
            k_var, genomes[idx], cxpb=0.5, mutpb=0.2, indpb=0.05)
        return (children, newfit), newfit.max()

    (genomes, fitness), best_per_gen = jax.lax.scan(
        generation, (genomes, fitness), jax.random.split(k_run, ngen))

    for gen, best in enumerate(best_per_gen):
        print(f"gen {gen:3d}  best {float(best):.0f}")
    print("final best:", float(fitness.max()))
    return float(fitness.max())


if __name__ == "__main__":
    main()
