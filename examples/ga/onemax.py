"""OneMax — the canonical GA, written out by hand.

Counterpart of /root/reference/examples/ga/onemax.py:72-157 (the long
form with an explicit generational loop, statistics and printing; the
reference seeds ``random.seed(64)`` at onemax.py:73). The loop body —
select → clone → mate → mutate → evaluate invalid — is the same
protocol, but compiled: selection and variation are batched tensor ops
and the whole generation is jit-compiled.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather, init_population
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.support.stats import fitness_stats


def main(smoke: bool = False, seed: int = 64):
    n, ngen = (300, 40) if not smoke else (60, 10)
    length = 100

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(seed), n,
                          ops.bernoulli_genome(length), FitnessSpec((1.0,)))
    pop = algorithms.evaluate_invalid(pop, toolbox.evaluate)
    stats = fitness_stats()

    @jax.jit
    def generation(key, pop):
        k_sel, k_var = jax.random.split(key)
        idx = toolbox.select(k_sel, pop.wvalues, pop.size)
        off = algorithms.var_and(k_var, gather(pop, idx), toolbox,
                                 cxpb=0.5, mutpb=0.2)
        return algorithms.evaluate_invalid(off, toolbox.evaluate)

    key = jax.random.key(seed + 1)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        pop = generation(kg, pop)
        rec = {k: float(v) for k, v in stats.compile(pop).items()}
        print(f"gen {g + 1:3d}  " + "  ".join(
            f"{k} {v:7.2f}" for k, v in rec.items()))

    best = float(pop.wvalues.max())
    print(f"Best individual fitness: {best}")
    return best


if __name__ == "__main__":
    main()
