"""NSGA-II far past the reference's practical population sizes.

The reference's NSGA-II demo (examples/ga/nsga2.py) runs MU≈100; its
Python non-dominated sort is O(MN²) interpreter work, and even a dense
tensor formulation hits an [n, n] memory wall around 50k individuals.
This example runs the same ZDT1 optimisation at pop=50k (the
BASELINE.json config) on any backend: ZDT1 is bi-objective, so the
exact O(n log n) staircase sort (`nd_rank_staircase`,
docs/advanced/kernels.md) ranks the 2n=100k candidate pool with no
dominance pairs at all — ~0.6 s/gen on one CPU core, hypervolume
118.05 after 20 gens against the reference's >116.0 gate. Pass
``nd='tiled'`` to exercise the streaming Pallas kernel instead (the
general >2-objective scale path, TPU-targeted).

On one TPU chip try ``main(pop=100_000)``; smoke mode keeps CI cheap.
"""

import jax
import jax.numpy as jnp

from deap_tpu import mo, ops
from deap_tpu.benchmarks import zdt1


def main(smoke: bool = False, pop: int | None = None, ngen: int = 20,
         seed: int = 0, nd: str | None = None,
         peel_budget: int | None = 256):
    if pop is None:
        # ZDT1 is bi-objective, so the exact O(n log n) staircase sort
        # (mo.nd_rank_staircase, r5) carries pop=50k on ANY backend —
        # the BASELINE.json config runs end-to-end even on a CPU host
        # where the [2n, 2n] dominance matrix would be ~40 GB
        pop = 50_000
    if smoke:
        pop, ngen = 256, 4
    dim = 30
    if nd in (None, "standard", "log", "auto"):
        # same mapping as sel_nsga2's 'auto': bi-objective at scale →
        # staircase; explicit nd='tiled' still exercises the streaming
        # Pallas kernel (the >2-objective path) on TPU
        nd = "staircase" if pop >= 4096 else "matrix"

    key = jax.random.key(seed)
    k_init, k_run = jax.random.split(key)
    genomes = jax.random.uniform(k_init, (pop, dim))

    def evaluate(g):
        return -jax.vmap(zdt1)(g)  # minimisation → weighted values

    w = evaluate(genomes)

    @jax.jit
    def generation(carry, k):
        genomes, w = carry
        k_sel, k_cx, k_mut, k_env = jax.random.split(k, 4)
        parents = mo.sel_tournament_dcd(k_sel, w, pop,
                                        peel_budget=peel_budget)
        g = genomes[parents]
        c1, c2 = ops.pair_vmap(ops.cx_simulated_binary_bounded)(
            k_cx, g[0::2], g[1::2], eta=20.0, low=0.0, up=1.0)
        g = jnp.stack([c1, c2], 1).reshape(pop, dim)
        g = jax.vmap(lambda kk, x: ops.mut_polynomial_bounded(
            kk, x, eta=20.0, low=0.0, up=1.0, indpb=1.0 / dim))(
            jax.random.split(k_mut, pop), g)
        w_off = evaluate(g)
        all_g = jnp.concatenate([genomes, g])
        all_w = jnp.concatenate([w, w_off])
        # environmental selection inlined (= sel_nsga2 with
        # peel_budget) so the peel count of the 2n candidate pool —
        # the data-dependent trip count — can be recorded per gen
        ranks, peels = mo.nd_rank(
            all_w, impl=nd, cover_k=pop, max_rank=peel_budget,
            fallback="count", return_peels=True)
        crowd = mo.crowding_distances(
            all_w, jnp.minimum(ranks, 2 * pop))
        keep = jnp.lexsort((-crowd, ranks))[:pop]
        return (all_g[keep], all_w[keep]), peels

    (genomes, w), peels = jax.lax.scan(
        generation, (genomes, w), jax.random.split(k_run, ngen))

    front = w[mo.nd_rank(w, impl=nd, max_rank=1) == 0]
    f1 = -w[:, 0]
    fc = [int(x) for x in peels]
    # the reference's NSGA-II quality gate — hypervolume vs ref point
    # [11, 11] > 116.0 (deap/tests/test_algorithms.py:110-113) — on
    # the at-scale run's first front (2-D hv is a sort + sweep, cheap
    # even at 50k points)
    from deap_tpu.benchmarks.tools import hypervolume
    hv = float(hypervolume(-front, ref=jnp.array([11.0, 11.0])))
    print(f"pop={pop}  front size={front.shape[0]}  "
          f"f1 range [{float(f1.min()):.3f}, {float(f1.max()):.3f}]  "
          f"hypervolume {hv:.3f}")
    print(f"fronts peeled per gen over the 2n pool (budget "
          f"{peel_budget}): min={min(fc)} max={max(fc)} last={fc[-1]}")
    return hv


if __name__ == "__main__":
    main()
