"""Multi-objective knapsack with SPEA2 selection.

Counterpart of /root/reference/examples/ga/knapsack.py: set-typed
individuals, two objectives (minimise weight, maximise value), custom
set crossover/mutation, ``selSPEA2`` + ``eaMuPlusLambda``. Sets become
boolean membership masks; the set operators become mask arithmetic.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

NBR_ITEMS = 20
MAX_ITEM, MAX_WEIGHT = 50, 50


def main(smoke: bool = False):
    mu, lam = 50, 100
    ngen = 50 if not smoke else 10
    k_items = jax.random.split(jax.random.key(12), 2)
    weights = jax.random.randint(k_items[0], (NBR_ITEMS,), 1, 11)
    values = jax.random.uniform(k_items[1], (NBR_ITEMS,)) * 100

    def evaluate(masks):
        w = (masks * weights).sum(-1).astype(jnp.float32)
        v = (masks * values).sum(-1)
        # overweight/oversized → the reference's penalty (knapsack.py:61-62)
        over = (w > MAX_WEIGHT) | (masks.sum(-1) > MAX_ITEM)
        w = jnp.where(over, 10000.0, w)
        v = jnp.where(over, 0.0, v)
        return jnp.stack([w, v], axis=-1)

    def cx_set(key, a, b):
        """intersection / symmetric difference (knapsack.py:66-70)."""
        return a & b, a ^ b

    def mut_set(key, a):
        """flip one random item in or out (knapsack.py:73-80)."""
        i = jax.random.randint(key, (), 0, NBR_ITEMS)
        return a.at[i].set(~a[i])

    toolbox = Toolbox()
    toolbox.register("evaluate", evaluate)
    toolbox.register("mate", cx_set)
    toolbox.register("mutate", mut_set)
    toolbox.register("select", mo.sel_spea2)

    pop = init_population(jax.random.key(13), mu,
                          ops.bernoulli_genome(NBR_ITEMS, p=0.25),
                          FitnessSpec((-1.0, 1.0)))
    pop, logbook, _ = algorithms.ea_mu_plus_lambda(
        jax.random.key(14), pop, toolbox, mu=mu, lambda_=lam,
        cxpb=0.7, mutpb=0.2, ngen=ngen)
    front = pop.wvalues
    best_value = float(front[:, 1].max())
    print(f"Best value in final population: {best_value:.1f}")
    return best_value


if __name__ == "__main__":
    main()
