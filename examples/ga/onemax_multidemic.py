"""OneMax, multi-demic evolution in one process.

Counterpart of /root/reference/examples/ga/onemax_multidemic.py: a list
of demes evolved in lockstep with ``migRing`` every generation. Here
the demes are one stacked population and migration is
:func:`deap_tpu.parallel.mig_ring` (SURVEY.md §2.3 P6).
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import gather
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import island_init, mig_ring


def main(smoke: bool = False):
    demes, deme_size = 3, 50
    ngen, mig_freq = (40, 5) if not smoke else (10, 3)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pops = island_init(jax.random.key(8), demes, deme_size,
                       ops.bernoulli_genome(100), FitnessSpec((1.0,)))

    @jax.jit
    def generation(key, pops):
        def one(key, pop):
            k_sel, k_var = jax.random.split(key)
            pop = algorithms.evaluate_invalid(pop, toolbox.evaluate)
            idx = toolbox.select(k_sel, pop.wvalues, pop.size)
            off = algorithms.var_and(k_var, gather(pop, idx), toolbox,
                                     0.5, 0.2)
            return algorithms.evaluate_invalid(off, toolbox.evaluate)

        return jax.vmap(one)(jax.random.split(key, demes), pops)

    key = jax.random.key(9)
    for g in range(ngen):
        key, kg, km = jax.random.split(key, 3)
        pops = generation(kg, pops)
        if (g + 1) % mig_freq == 0:
            pops = mig_ring(km, pops, k=5)
    best = float(pops.wvalues.max())
    print("Best:", best)
    return best


if __name__ == "__main__":
    main()
