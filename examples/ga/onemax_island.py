"""OneMax island model, single process.

Counterpart of /root/reference/examples/ga/onemax_island.py, where each
deme is an OS process and migration travels blocking multiprocessing
pipes in a ring (onemax_island.py:45-75, :140-154). Here the demes are a
stacked leading axis evolved by one vmapped program and the ring is a
tensor roll — the blocking lockstep the reference builds from pipes
falls out of SPMD for free (SURVEY.md §2.3 P5).
"""

import jax
import jax.numpy as jnp

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import island_init, make_island_step


def main(smoke: bool = False):
    demes, deme_size = 5, 60
    epochs, freq = (8, 5) if not smoke else (3, 2)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pops = island_init(jax.random.key(4), demes, deme_size,
                       ops.bernoulli_genome(100), FitnessSpec((1.0,)))
    step = jax.jit(make_island_step(toolbox, cxpb=0.5, mutpb=0.2,
                                    freq=freq, mig_k=5))
    key = jax.random.key(5)
    for e in range(epochs):
        key, ke = jax.random.split(key)
        pops = step(ke, pops)
        per_isle = pops.wvalues[..., 0].max(axis=1)
        print(f"epoch {e}: best per island "
              + " ".join(f"{float(b):5.1f}" for b in per_isle))
    best = float(pops.wvalues.max())
    print("Best:", best)
    return best


if __name__ == "__main__":
    main()
