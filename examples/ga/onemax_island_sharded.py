"""OneMax islands sharded over the device mesh (one island per device).

Counterpart of /root/reference/examples/ga/onemax_island_scoop.py, which
ships whole islands to SCOOP network workers through ``toolbox.map``
(onemax_island_scoop.py:49, :65) and migrates master-side with
``migRing`` (:67). TPU-native (SURVEY.md §2.3 P4): islands live on the
``island`` mesh axis, local evolution is per-device SPMD, and the ring
migration is a ``lax.ppermute`` over ICI — no pickling, no master.
Multi-host runs use the same program under ``jax.distributed``.

Run with ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` to test
on a CPU mesh.
"""

import jax
import jax.numpy as jnp

from deap_tpu import ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.toolbox import Toolbox
from deap_tpu.parallel import (
    island_init,
    make_island_step,
    population_mesh,
    shard_population,
)


def main(smoke: bool = False):
    n_islands = jax.device_count()
    deme_size = 60
    epochs, freq = (8, 5) if not smoke else (3, 2)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    mesh = population_mesh(n_islands, axis_names=("island",))
    pops = island_init(jax.random.key(6), n_islands, deme_size,
                       ops.bernoulli_genome(100), FitnessSpec((1.0,)))
    pops = shard_population(pops, mesh, axis="island")
    step = jax.jit(make_island_step(toolbox, cxpb=0.5, mutpb=0.2,
                                    freq=freq, mig_k=5, mesh=mesh))

    key = jax.random.key(7)
    for e in range(epochs):
        key, ke = jax.random.split(key)
        pops = step(ke, pops)
    best = float(pops.wvalues.max())
    print(f"{n_islands} islands on mesh, best: {best}")
    return best


if __name__ == "__main__":
    main()
