"""NSGA-II on ZDT1 — the reference's flagship multi-objective example.

Counterpart of /root/reference/examples/ga/nsga2.py (144 LoC): SBX
bounded crossover + polynomial bounded mutation, tournament-DCD
parenting, NSGA-II environmental selection, hypervolume quality gate
(the test suite asserts hv > 116.0 against ref point [11, 11],
deap/tests/test_algorithms.py:110-113).
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, benchmarks, mo, ops
from deap_tpu.benchmarks.tools import (
    convergence,
    diversity,
    hypervolume,
    optimal_front,
)
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import concat, gather, init_population
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False, mu: int = 100):
    ngen = 100 if not smoke else 15
    ndim = 30

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: jax.vmap(benchmarks.zdt1)(g))
    toolbox.register("mate", ops.cx_simulated_binary_bounded,
                     eta=20.0, low=0.0, up=1.0)
    toolbox.register("mutate", ops.mut_polynomial_bounded,
                     eta=20.0, low=0.0, up=1.0, indpb=1.0 / ndim)

    pop = init_population(jax.random.key(19), mu,
                          ops.uniform_genome(ndim, 0.0, 1.0),
                          FitnessSpec((-1.0, -1.0)))
    pop = algorithms.evaluate_invalid(pop, toolbox.evaluate)

    @jax.jit
    def generation(key, pop):
        k_par, k_var = jax.random.split(key)
        parents = mo.sel_tournament_dcd(k_par, pop.wvalues, pop.size)
        off = algorithms.var_and(k_var, gather(pop, parents), toolbox,
                                 cxpb=0.9, mutpb=1.0)
        off = algorithms.evaluate_invalid(off, toolbox.evaluate)
        pool = concat([pop, off])
        keep = mo.sel_nsga2(None, pool.wvalues, mu)
        return gather(pool, keep)

    key = jax.random.key(20)
    for g in range(ngen):
        key, kg = jax.random.split(key)
        pop = generation(kg, pop)

    hv = hypervolume(pop.fitness, ref=jnp.asarray([11.0, 11.0]),
                     weights=(-1.0, -1.0))
    print(f"Final hypervolume: {float(hv):.3f} (optimum 120.777)")

    # convergence/diversity vs the analytic optimal front — reference
    # nsga2.py reads sampled zdt1.json fixtures for the same report
    opt = optimal_front("zdt1", 1000)
    ranks = mo.nd_rank(pop.wvalues)
    ff = pop.fitness[jnp.asarray(ranks == 0)]
    ff = ff[jnp.argsort(ff[:, 0])]
    print(f"Convergence: {convergence(ff, opt):.5f}")
    print(f"Diversity: {diversity(ff, opt[0], opt[-1]):.5f}")
    return float(hv)


if __name__ == "__main__":
    main()
