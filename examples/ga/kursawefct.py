"""Kursawe multi-objective function with NSGA-II.

Counterpart of /root/reference/examples/ga/kursawefct.py: real-valued
genomes on the Kursawe landscape (benchmarks/__init__.py:364+),
Gaussian mutation + blend crossover, NSGA-II selection.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, benchmarks, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False):
    n, ngen = (100, 50) if not smoke else (40, 10)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: jax.vmap(benchmarks.kursawe)(g))
    toolbox.register("mate", ops.cx_blend, alpha=1.5)
    toolbox.register("mutate", ops.mut_gaussian, mu=0.0, sigma=3.0,
                     indpb=0.3)
    toolbox.register("select", mo.sel_nsga2)

    pop = init_population(
        jax.random.key(17), n, ops.uniform_genome(3, -5.0, 5.0),
        FitnessSpec((-1.0, -1.0)))
    pop, logbook, _ = algorithms.ea_mu_plus_lambda(
        jax.random.key(18), pop, toolbox, mu=n, lambda_=n,
        cxpb=0.5, mutpb=0.3, ngen=ngen)
    nd = mo.nondominated_mask(pop.wvalues)
    print(f"Non-dominated individuals in final pop: {int(nd.sum())}")
    return int(nd.sum())


if __name__ == "__main__":
    main()
