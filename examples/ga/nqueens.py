"""N-queens with permutation encoding.

Counterpart of /root/reference/examples/ga/nqueens.py: a permutation
maps columns to rows (no row/column conflicts by construction), fitness
counts diagonal conflicts (evalNQueens), partially-matched crossover +
shuffle mutation.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False, size: int = 20):
    n, ngen = (300, 100) if not smoke else (60, 15)

    def conflicts(perm):
        cols = jnp.arange(size)
        left = perm + cols          # / diagonal index
        right = perm - cols         # \ diagonal index

        def count_dups(diag):
            eq = diag[:, None] == diag[None, :]
            return (jnp.triu(eq, k=1)).sum()

        return (count_dups(left) + count_dups(right)).astype(jnp.float32)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: jax.vmap(conflicts)(g))
    toolbox.register("mate", ops.cx_partialy_matched)
    toolbox.register("mutate", ops.mut_shuffle_indexes, indpb=2.0 / size)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(15), n,
                          ops.permutation_genome(size), FitnessSpec((-1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(16), pop, toolbox, cxpb=0.5, mutpb=0.2, ngen=ngen)
    best = float(-pop.wvalues.max())
    print(f"Fewest diagonal conflicts: {best}")
    return best


if __name__ == "__main__":
    main()
