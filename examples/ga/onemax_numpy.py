"""OneMax over packed numeric genomes.

Counterpart of /root/reference/examples/ga/onemax_numpy.py, whose point
is ndarray individuals (with the cxTwoPointCopy view-aliasing fix,
doc/tutorials/advanced/numpy.rst). In the tensor framework every
population is already an array — this variant shows dtype control
(int8 genomes instead of bool) and that the same operators apply
unchanged, with no aliasing possible because variation is functional
(SURVEY.md §5.2).
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False):
    n, ngen = (300, 40) if not smoke else (60, 10)

    toolbox = Toolbox()
    toolbox.register("evaluate", lambda g: g.sum(-1).astype(jnp.float32))
    toolbox.register("mate", ops.cx_two_point)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(
        jax.random.key(0), n, ops.bernoulli_genome(100, dtype=jnp.int8),
        FitnessSpec((1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(1), pop, toolbox, 0.5, 0.2, ngen)
    assert pop.genomes.dtype == jnp.int8
    best = float(pop.wvalues.max())
    print("Best:", best)
    return best


if __name__ == "__main__":
    main()
