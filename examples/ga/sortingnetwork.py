"""Sorting-network representation and exhaustive evaluation.

Counterpart of /root/reference/examples/ga/sortingnetwork.py: a network
is a sequence of comparator pairs; correctness is checked by sorting
every binary input (the zero-one principle). Networks are fixed-width
comparator arrays ``[max_pairs, 2]`` with a length; evaluation applies
all comparators to all 2^n binary vectors in one batched program.
"""

import jax
import jax.numpy as jnp
from jax import lax


def all_binary_inputs(dimension: int) -> jnp.ndarray:
    n = 1 << dimension
    return ((jnp.arange(n)[:, None] >> jnp.arange(dimension)[None, :]) & 1
            ).astype(jnp.int32)


def apply_network(pairs: jnp.ndarray, length: jnp.ndarray,
                  inputs: jnp.ndarray) -> jnp.ndarray:
    """Run the comparator sequence over a batch of vectors."""

    def step(vecs, t):
        i, j = pairs[t, 0], pairs[t, 1]
        active = t < length
        lo = jnp.minimum(vecs[:, i], vecs[:, j])
        hi = jnp.maximum(vecs[:, i], vecs[:, j])
        new = vecs.at[:, i].set(lo).at[:, j].set(hi)
        return jnp.where(active, new, vecs), None

    out, _ = lax.scan(step, inputs, jnp.arange(pairs.shape[0]))
    return out


def evaluate_network(pairs, length, dimension) -> jnp.ndarray:
    """(errors, length) — the reference's (misses, size) objectives."""
    inputs = all_binary_inputs(dimension)
    out = apply_network(pairs, length, inputs)
    sorted_ref = jnp.sort(inputs, axis=1)
    errors = (out != sorted_ref).any(axis=1).sum()
    return jnp.stack([errors.astype(jnp.float32),
                      length.astype(jnp.float32)])


def main(smoke: bool = False):
    # the known optimal 4-input network: 5 comparators
    pairs = jnp.asarray([[0, 1], [2, 3], [0, 2], [1, 3], [1, 2]] + [[0, 0]] * 3)
    errs, size = evaluate_network(pairs, jnp.int32(5), 4)
    print(f"4-input Batcher network: errors={int(errs)}, size={int(size)}")
    assert int(errs) == 0
    return int(errs)


if __name__ == "__main__":
    main()
