"""Feature selection for kNN by multi-objective GA.

Counterpart of /root/reference/examples/ga/evoknn.py: boolean feature
masks evolved to maximise classification accuracy and minimise the
number of selected features, NSGA-II selection. The whole
population × dataset kNN evaluation is one batched XLA program.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

from examples.ga.knn import N_FEATURES, knn_accuracy, load_csv, make_dataset


def main(smoke: bool = False, csv_path: str | None = None):
    """``csv_path`` (or ``DEAP_TPU_HEART_SCALE``) points at the
    reference's heart_scale.csv for direct comparability; default is
    the synthetic known-informative-features dataset."""
    import os

    n, ngen = (80, 30) if not smoke else (30, 6)
    csv_path = csv_path or os.environ.get("DEAP_TPU_HEART_SCALE")
    if csv_path:
        X, y = load_csv(csv_path)
    else:
        X, y = make_dataset(jax.random.key(28))

    def evaluate(masks):
        acc = jax.vmap(lambda m: knn_accuracy(m.astype(jnp.float32), X, y)
                       )(masks)
        nsel = masks.sum(-1).astype(jnp.float32)
        return jnp.stack([acc, nsel], axis=-1)

    n_features = X.shape[1]  # 13 both for heart_scale and synthetic
    toolbox = Toolbox()
    toolbox.register("evaluate", evaluate)
    toolbox.register("mate", ops.cx_uniform, indpb=0.3)
    toolbox.register("mutate", ops.mut_flip_bit, indpb=1.0 / n_features)
    toolbox.register("select", mo.sel_nsga2)

    pop = init_population(jax.random.key(29), n,
                          ops.bernoulli_genome(n_features),
                          FitnessSpec((1.0, -1.0)))
    pop, logbook, _ = algorithms.ea_mu_plus_lambda(
        jax.random.key(30), pop, toolbox, mu=n, lambda_=n,
        cxpb=0.6, mutpb=0.3, ngen=ngen)
    best_acc = float(pop.fitness[:, 0].max())
    print(f"Best accuracy on the front: {best_acc:.3f}")
    return best_acc


if __name__ == "__main__":
    main()
