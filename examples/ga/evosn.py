"""Evolving sorting networks (miss-count + size objectives).

Counterpart of /root/reference/examples/ga/evosn.py: evolve comparator
sequences for an n-input sorting network, minimising (misses, size).
Variable-length individuals become fixed-width pair arrays + length
with length-aware crossover/mutation.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, mo, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox

from examples.ga.sortingnetwork import evaluate_network

DIM = 6
MAX_PAIRS = 24


def main(smoke: bool = False):
    n, ngen = (200, 40) if not smoke else (60, 8)

    def init_net(key):
        k1, k2, k3 = jax.random.split(key, 3)
        a = jax.random.randint(k1, (MAX_PAIRS,), 0, DIM)
        off = jax.random.randint(k2, (MAX_PAIRS,), 1, DIM)
        b = (a + off) % DIM
        pairs = jnp.stack([jnp.minimum(a, b), jnp.maximum(a, b)], axis=-1)
        length = jax.random.randint(k3, (), MAX_PAIRS // 2, MAX_PAIRS + 1)
        return {"pairs": pairs, "length": length}

    def evaluate(genomes):
        return jax.vmap(
            lambda g: evaluate_network(g["pairs"], g["length"], DIM)
        )(genomes)

    def mate(key, g1, g2):
        """One-point crossover on the comparator sequence."""
        cut = jax.random.randint(key, (), 1, MAX_PAIRS)
        sel = (jnp.arange(MAX_PAIRS) < cut)[:, None]
        c1 = {"pairs": jnp.where(sel, g1["pairs"], g2["pairs"]),
              "length": jnp.maximum(g1["length"], g2["length"])}
        c2 = {"pairs": jnp.where(sel, g2["pairs"], g1["pairs"]),
              "length": jnp.maximum(g1["length"], g2["length"])}
        return c1, c2

    def mutate(key, g):
        """Replace a random comparator; small chance to grow/shrink."""
        k1, k2, k3, k4 = jax.random.split(key, 4)
        i = jax.random.randint(k1, (), 0, MAX_PAIRS)
        a = jax.random.randint(k2, (), 0, DIM)
        off = jax.random.randint(k3, (), 1, DIM)
        b = (a + off) % DIM
        pair = jnp.stack([jnp.minimum(a, b), jnp.maximum(a, b)])
        delta = jax.random.randint(k4, (), -1, 2)
        return {"pairs": g["pairs"].at[i].set(pair),
                "length": jnp.clip(g["length"] + delta, 1, MAX_PAIRS)}

    toolbox = Toolbox()
    toolbox.register("evaluate", evaluate)
    toolbox.register("mate", mate)
    toolbox.register("mutate", mutate)
    toolbox.register("select", mo.sel_nsga2)

    pop = init_population(jax.random.key(25), n, init_net,
                          FitnessSpec((-1.0, -1.0)))
    pop, logbook, _ = algorithms.ea_mu_plus_lambda(
        jax.random.key(26), pop, toolbox, mu=n, lambda_=n,
        cxpb=0.6, mutpb=0.3, ngen=ngen)
    misses = pop.fitness[:, 0]
    best_misses = float(misses.min())
    perfect = misses == 0
    sizes = jnp.where(perfect, pop.fitness[:, 1], jnp.inf)
    print(f"Best misses: {best_misses}; smallest perfect network: "
          f"{float(sizes.min())} comparators")
    return best_misses


if __name__ == "__main__":
    main()
