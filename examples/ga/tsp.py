"""Travelling salesman with permutation genomes.

Counterpart of /root/reference/examples/ga/tsp.py (PMX crossover +
index-shuffle mutation over permutation individuals; the reference
loads a gr17/gr24 distance matrix from examples/ga/tsp/*.json). Here a
reproducible random Euclidean instance is generated on device and tour
length is a batched gather + norm.
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox


def main(smoke: bool = False, n_cities: int = 24):
    n, ngen = (300, 120) if not smoke else (60, 15)
    cities = jax.random.uniform(jax.random.key(42), (n_cities, 2))
    dist = jnp.linalg.norm(cities[:, None, :] - cities[None, :, :], axis=-1)

    def tour_length(perm):
        return dist[perm, jnp.roll(perm, -1)].sum()

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda g: jax.vmap(tour_length)(g))
    toolbox.register("mate", ops.cx_partialy_matched)
    toolbox.register("mutate", ops.mut_shuffle_indexes, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(10), n,
                          ops.permutation_genome(n_cities),
                          FitnessSpec((-1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(11), pop, toolbox, cxpb=0.7, mutpb=0.2, ngen=ngen)
    best = float(-pop.wvalues.max())
    greedy_bound = float(dist[dist > 0].mean() * n_cities)
    print(f"Best tour length: {best:.3f} (random-tour scale "
          f"~{greedy_bound:.1f})")
    return best


if __name__ == "__main__":
    main()
