"""Travelling salesman with permutation genomes.

Counterpart of /root/reference/examples/ga/tsp.py (PMX crossover +
index-shuffle mutation over permutation individuals; the reference
loads a gr17/gr24 TSPLIB distance matrix from examples/ga/tsp/*.json).

Instead of vendoring TSPLIB data, the instance here is synthetic with a
*provable* optimum: cities in convex position (a circle with jittered
angles). For points in convex position the optimal tour is exactly the
cyclic hull order, so the optimal length is computable in closed form —
which makes solution quality measurable (gap-to-optimum) the way the
reference's known gr17 optimum (2085) did, with zero licensing
questions. See examples/README.md "Datasets".
"""

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox


def convex_instance(n_cities: int, seed: int = 42):
    """Cities on a unit circle with jittered angles — convex position,
    so the optimal tour is the angular order; returns (cities, dist,
    optimal_length)."""
    angles = jnp.sort(
        2 * jnp.pi * jax.random.uniform(jax.random.key(seed), (n_cities,)))
    cities = jnp.stack([jnp.cos(angles), jnp.sin(angles)], axis=-1)
    dist = jnp.linalg.norm(cities[:, None, :] - cities[None, :, :], axis=-1)
    optimum = float(dist[jnp.arange(n_cities),
                         jnp.roll(jnp.arange(n_cities), -1)].sum())
    return cities, dist, optimum


def main(smoke: bool = False, n_cities: int = 24):
    n, ngen = (300, 120) if not smoke else (60, 15)
    _, dist, optimum = convex_instance(n_cities)

    def tour_length(perm):
        return dist[perm, jnp.roll(perm, -1)].sum()

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda g: jax.vmap(tour_length)(g))
    toolbox.register("mate", ops.cx_partialy_matched)
    toolbox.register("mutate", ops.mut_shuffle_indexes, indpb=0.05)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(10), n,
                          ops.permutation_genome(n_cities),
                          FitnessSpec((-1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(11), pop, toolbox, cxpb=0.7, mutpb=0.2, ngen=ngen)
    best = float(-pop.wvalues.max())
    gap = (best - optimum) / optimum
    print(f"Best tour length: {best:.3f} "
          f"(optimum {optimum:.3f}, gap {100 * gap:.1f}%)")
    return best


if __name__ == "__main__":
    main()
