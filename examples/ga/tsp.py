"""Travelling salesman with permutation genomes.

Counterpart of /root/reference/examples/ga/tsp.py (PMX crossover +
index-shuffle mutation over permutation individuals; the reference
loads a gr17/gr24 TSPLIB distance matrix from examples/ga/tsp/*.json).

Instead of vendoring TSPLIB data, the default instance is synthetic
with a *provable* optimum: cities in convex position (a circle with
jittered angles). For points in convex position the optimal tour is
exactly the cyclic hull order, so the optimal length is computable in
closed form — which makes solution quality measurable (gap-to-optimum)
the way the reference's known gr17 optimum (2085) did, with zero
licensing questions. See examples/README.md "Datasets".

For a *direct* quality comparison against the reference, point
``main(instance=...)`` (or ``DEAP_TPU_TSP_INSTANCE``) at a
reference-format instance file — a JSON dict with ``DistanceMatrix``
and optionally ``OptDistance``/``TourSize``, the exact schema of the
reference's ``examples/ga/tsp/gr*.json`` — and the run reports the
gap against that instance's known optimum instead.
"""

import json
import os

import jax
import jax.numpy as jnp

from deap_tpu import algorithms, ops
from deap_tpu.core.fitness import FitnessSpec
from deap_tpu.core.population import init_population
from deap_tpu.core.toolbox import Toolbox


def convex_instance(n_cities: int, seed: int = 42):
    """Cities on a unit circle with jittered angles — convex position,
    so the optimal tour is the angular order; returns (cities, dist,
    optimal_length)."""
    angles = jnp.sort(
        2 * jnp.pi * jax.random.uniform(jax.random.key(seed), (n_cities,)))
    cities = jnp.stack([jnp.cos(angles), jnp.sin(angles)], axis=-1)
    dist = jnp.linalg.norm(cities[:, None, :] - cities[None, :, :], axis=-1)
    optimum = float(dist[jnp.arange(n_cities),
                         jnp.roll(jnp.arange(n_cities), -1)].sum())
    return cities, dist, optimum


def load_instance(path: str):
    """A reference-format TSP instance (gr17/gr24 JSON schema): returns
    (dist, optimum_or_None). The matrix is used as-is; ``OptDistance``
    (2085 for gr17) becomes the quality anchor when present."""
    with open(path) as f:
        data = json.load(f)
    dist = jnp.asarray(data["DistanceMatrix"], jnp.float32)
    opt = data.get("OptDistance")
    return dist, None if opt is None else float(opt)


def main(smoke: bool = False, n_cities: int = 24,
         instance: str | None = None):
    n, ngen = (300, 120) if not smoke else (60, 15)
    instance = instance or os.environ.get("DEAP_TPU_TSP_INSTANCE")
    if instance:
        dist, optimum = load_instance(instance)
        n_cities = dist.shape[0]
        if optimum is None:
            optimum = float("nan")
    else:
        _, dist, optimum = convex_instance(n_cities)

    def tour_length(perm):
        return dist[perm, jnp.roll(perm, -1)].sum()

    def memetic_mutate(key, g, indpb=0.05):
        # shuffle kick + 2-opt polish: iterated local search per
        # mutated offspring — closes the few-percent gap the pure
        # PMX+shuffle GA leaves on TSPLIB instances (gr24: 1347 →
        # optimum 1272)
        g = ops.mut_shuffle_indexes(key, g, indpb)
        return ops.mut_two_opt(key, g, dist)

    toolbox = Toolbox()
    toolbox.register("evaluate",
                     lambda g: jax.vmap(tour_length)(g))
    toolbox.register("mate", ops.cx_partialy_matched)
    toolbox.register("mutate", memetic_mutate)
    toolbox.register("select", ops.sel_tournament, tournsize=3)

    pop = init_population(jax.random.key(10), n,
                          ops.permutation_genome(n_cities),
                          FitnessSpec((-1.0,)))
    pop, logbook, _ = algorithms.ea_simple(
        jax.random.key(11), pop, toolbox, cxpb=0.7, mutpb=0.2, ngen=ngen)
    best = float(-pop.wvalues.max())
    gap = (best - optimum) / optimum
    print(f"Best tour length: {best:.3f} "
          f"(optimum {optimum:.3f}, gap {100 * gap:.1f}%)")
    return best


if __name__ == "__main__":
    main()
