"""Batched k-nearest-neighbours classifier (fitness backend for evoknn).

Counterpart of /root/reference/examples/ga/knn.py, which implements a
small kNN over the heart_scale dataset for the feature-selection GA.
Here the classifier is a fully batched jnp program: masked features,
pairwise distances, top-k vote — one XLA kernel per population member.
A reproducible synthetic two-class dataset stands in for the CSV
fixture.
"""

import jax
import jax.numpy as jnp

N_FEATURES = 13


def make_dataset(key, n: int = 160, informative: int = 5):
    """Two classes separated along ``informative`` features; the rest
    is noise (the selection target)."""
    k1, k2, k3 = jax.random.split(key, 3)
    y = (jnp.arange(n) % 2).astype(jnp.float32)
    centers = jnp.where(
        jnp.arange(N_FEATURES) < informative, 1.5, 0.0)
    X = jax.random.normal(k1, (n, N_FEATURES))
    X = X + y[:, None] * centers[None, :]
    perm = jax.random.permutation(k2, n)
    return X[perm], y[perm]


def load_csv(path: str):
    """The reference's ``heart_scale.csv`` layout (examples/ga/knn.py
    reads it with the label in the first column, ±1): returns
    ``(X f32[n, d], y f32[n] in {0, 1})``."""
    import numpy as np

    data = jnp.asarray(np.loadtxt(path, delimiter=","), jnp.float32)
    return data[:, 1:], (data[:, 0] > 0).astype(jnp.float32)


def knn_accuracy(mask, X, y, k: int = 5) -> jnp.ndarray:
    """Leave-one-out accuracy of kNN restricted to masked features."""
    Xm = X * mask[None, :]
    d = jnp.linalg.norm(Xm[:, None, :] - Xm[None, :, :], axis=-1)
    d = d + jnp.eye(X.shape[0]) * 1e9          # exclude self
    _, idx = jax.lax.top_k(-d, k)
    votes = y[idx].mean(axis=1)
    pred = (votes > 0.5).astype(jnp.float32)
    return (pred == y).mean()


def main(smoke: bool = False):
    X, y = make_dataset(jax.random.key(27))
    full = knn_accuracy(jnp.ones(N_FEATURES), X, y)
    informative = knn_accuracy(
        (jnp.arange(N_FEATURES) < 5).astype(jnp.float32), X, y)
    print(f"kNN accuracy all features: {float(full):.3f}, "
          f"informative only: {float(informative):.3f}")
    return float(informative)


if __name__ == "__main__":
    main()
