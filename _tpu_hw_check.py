"""On-chip validation of the hardware-PRNG kernel paths.

The test suite pins the CPU backend (tests/conftest.py), so the
``prng='hw'`` kernels — TPU-only by nature — have no pytest coverage on
the real chip. This script runs the distributional and semantic checks
on the device and prints one JSON verdict line; ``tpu_capture.py`` runs
it before any benchmark so a broken hw kernel can never produce a
plausible-looking throughput artifact.

Checks (packed and byte-genome kernels):
- cxpb=0, mutpb=0: children identical to parents, fitness == popcount
- mutpb=1: per-gene flip rate within 4 sigma of indpb
- cxpb=1 from (all-zeros, all-ones) pairs: every child gene count in
  [0, L] and pair gene totals conserved (two-point swap preserves the
  pair's multiset per position)

Version 3 adds the tiled dominance kernels (nd_rank_tiled /
strengths_tiled vs the XLA matrix path at n=16k): until r4 they had
only ever executed under the Pallas interpreter in CI, never on a real
TPU core, yet they are the nsga2_pop50k suite config's entire compute.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _axon_probe import axon_tunnel_reachable

if not axon_tunnel_reachable():
    os.environ["JAX_PLATFORMS"] = "cpu"

import jax
import jax.numpy as jnp
import numpy as np

if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")


def main():
    if jax.default_backend() != "tpu":
        print(json.dumps({"check": "hw_kernels", "skipped": "no tpu"}))
        return 0

    from deap_tpu import ops
    from deap_tpu.ops import packed as pk

    failures = []
    N, L = 2048, 100
    W = pk.words_for(L)

    def expect(name, ok):
        if not bool(ok):
            failures.append(name)

    # --- packed kernel -----------------------------------------------------
    g = jax.random.bernoulli(jax.random.key(0), 0.5, (N, L))
    p = pk.pack_genomes(g)

    c, fit = pk.fused_variation_eval_packed(
        jax.random.key(1), p, L, cxpb=0.0, mutpb=0.0, indpb=0.05,
        prng="hw", interpret=False)
    expect("packed_identity", (np.asarray(c) == np.asarray(p)).all())
    expect("packed_fitness_popcount",
           (np.asarray(fit) == np.asarray(g.sum(-1))).all())

    z = jnp.zeros((N, W), jnp.uint32)
    c, fit = pk.fused_variation_eval_packed(
        jax.random.key(2), z, L, cxpb=0.0, mutpb=1.0, indpb=0.05,
        prng="hw", interpret=False)
    rate = float(np.asarray(fit).sum()) / (N * L)
    sigma = (0.05 * 0.95 / (N * L)) ** 0.5
    expect("packed_flip_rate", abs(rate - 0.05) < 4 * sigma)
    # no flips past the genome length (pack invariant)
    expect("packed_tail_clean",
           (np.asarray(pk.unpack_genomes(c, W * 32))[:, L:] == 0).all())

    ones_row = pk.pack_genomes(jnp.ones((1, L)))[0]  # uint32[W]
    half = jnp.where((jnp.arange(N) % 2 == 0)[:, None],
                     jnp.zeros((W,), jnp.uint32), ones_row)
    c, fit = pk.fused_variation_eval_packed(
        jax.random.key(3), half, L, cxpb=1.0, mutpb=0.0, indpb=0.05,
        prng="hw", interpret=False)
    f = np.asarray(fit)
    expect("packed_cx_range", ((f >= 0) & (f <= L)).all())
    pair_tot = f[0::2] + f[1::2]
    expect("packed_cx_conserved", (pair_tot == float(L)).all())

    # --- byte-genome kernel ------------------------------------------------
    c, fit = ops.fused_variation_eval(
        jax.random.key(4), jnp.zeros((N, L)), cxpb=0.0, mutpb=1.0,
        indpb=0.05, prng="hw", interpret=False)
    rate = float(np.asarray(fit).sum()) / (N * L)
    expect("bytes_flip_rate", abs(rate - 0.05) < 4 * sigma)

    # core verdict printed (and flushed) BEFORE the experimental
    # selgather block: a compile wedge or process abort in there must
    # not discard the core checks that already passed on-chip
    from tpu_capture import HW_CHECK_VERSION

    verdict = {"check": "hw_kernels", "ok": not failures,
               "version": HW_CHECK_VERSION}
    if failures:
        verdict["failed"] = failures
    print(json.dumps(verdict), flush=True)

    # --- tiled dominance kernels (nsga2 pop=50k's compute) -----------------
    # CI runs these only under the Pallas interpreter; this is their
    # first-ever execution on a real TPU core. Validated against the
    # XLA matrix path at n=16k — past the tiled path's crossover, small
    # enough to hold the [n, n] matrix for the oracle. Own verdict row
    # (wedge isolation), but the capture predicate requires it too.
    tiled_failures = []
    try:
        from deap_tpu.mo import emo as mo_emo
        from deap_tpu.ops import kernels as kn

        n_dom, m_dom = 16384, 3
        wd = jax.random.normal(jax.random.key(8), (n_dom, m_dom))
        ranks_t = np.asarray(kn.nd_rank_tiled(wd, interpret=False))
        ranks_m = np.asarray(mo_emo.nd_rank(wd, impl="matrix"))
        if not (ranks_t == ranks_m).all():
            tiled_failures.append(
                f"nd_rank mismatch on {(ranks_t != ranks_m).sum()} rows")
        s_t = np.asarray(kn.strengths_tiled(wd, interpret=False))
        dom = np.asarray(mo_emo.dominance_matrix(wd))  # dom[i,j]: j dom i
        s_m = dom.sum(axis=0).astype(np.float32)
        if not (s_t == s_m).all():
            tiled_failures.append(
                f"strengths mismatch on {(s_t != s_m).sum()} rows")
    except Exception as e:  # Mosaic lowering gap, VMEM OOM, ...
        if not axon_tunnel_reachable():
            # the exception arrived WITH the relay dying (XlaRuntimeError
            # mid-compile): a transient environment failure, not a
            # deterministic kernel verdict — print NO tiled row, so a
            # later window re-runs the validation instead of recording
            # a "Mosaic gap" for kernels that never actually ran
            print(f"tiled check aborted with relay down: {e}",
                  file=sys.stderr)
            return 1
        tiled_failures.append(f"crashed: {type(e).__name__}: "
                              f"{str(e)[:200]}")
    td = {"check": "tiled_dominance", "ok": not tiled_failures,
          "version": HW_CHECK_VERSION}
    if tiled_failures:
        td["failed"] = tiled_failures
    print(json.dumps(td), flush=True)

    # --- selection+gather kernel (VMEM-resident dynamic_gather) ------------
    # CPU pytest covers the bits path exactly; here the hw-PRNG path and
    # the Mosaic dynamic_gather lowering are validated on the real chip.
    # Separate verdict row: selgather is an experimental CANDIDATE (it
    # self-validates again inside bench.py before being timed) — an
    # unsupported lowering must not block the core kernels' verdict or
    # the capture queue's stop condition.
    selgather_failures = []
    core_expect = expect

    def expect(name, ok):  # noqa: F811 — selgather block only
        if not bool(ok):
            selgather_failures.append(name)

    try:
        g = jax.random.bernoulli(jax.random.key(5), 0.5, (N, L))
        p = pk.pack_genomes(g)
        fit = pk.packed_fitness(p)
        par = pk.sel_tournament_gather_packed(
            jax.random.key(6), p, fit, tournsize=3, prng="hw",
            interpret=False)
        par2 = pk.sel_tournament_gather_packed(
            jax.random.key(6), p, fit, tournsize=3, prng="hw",
            interpret=False)
        expect("selgather_deterministic",
               (np.asarray(par) == np.asarray(par2)).all())
        pop_set = {r.tobytes() for r in np.asarray(p)}
        expect("selgather_membership",
               all(r.tobytes() in pop_set for r in np.asarray(par)))
        # min-of-3 rank tournament: E[winner fitness] strictly above
        # the population mean; at N=2048, L=100 the uplift is ~4 bits —
        # require at least 1 (way outside noise)
        expect("selgather_pressure",
               float(pk.packed_fitness(par).mean())
               > float(fit.mean()) + 1.0)
    except Exception as e:  # Mosaic NotImplementedError, VMEM OOM, ...
        selgather_failures.append(f"crashed: {type(e).__name__}: "
                                  f"{str(e)[:200]}")
    expect = core_expect  # noqa: F841

    sg = {"check": "selgather", "ok": not selgather_failures,
          "version": HW_CHECK_VERSION}
    if selgather_failures:
        sg["failed"] = selgather_failures
    print(json.dumps(sg), flush=True)

    # --- whole-GA mega-kernel (r4 candidate) -------------------------------
    # Informational row, same stance as selgather: an experimental
    # candidate that self-validates again inside bench.py before any
    # timing counts; a crash here must not block the core verdict.
    evolve_failures = []
    try:
        g = jax.random.bernoulli(jax.random.key(9), 0.5, (N, L))
        p = pk.pack_genomes(g)
        fit = pk.packed_fitness(p)
        pop2, fit2 = pk.evolve_packed(
            jax.random.key(10), p, fit, L, 3, cxpb=0.0, mutpb=0.0,
            indpb=0.05, prng="hw", interpret=False)
        pop_set = {r.tobytes() for r in np.asarray(p)}
        if not all(r.tobytes() in pop_set for r in np.asarray(pop2)):
            evolve_failures.append("non-member rows (selection-only)")
        if not (np.asarray(pk.packed_fitness(pop2))
                == np.asarray(fit2)).all():
            evolve_failures.append("fitness/popcount mismatch")
        _, f5 = pk.evolve_packed(
            jax.random.key(11), p, fit, L, 5, cxpb=0.5, mutpb=0.2,
            indpb=0.05, prng="hw", interpret=False)
        uplift = float(f5.mean()) - float(fit.mean())
        if uplift <= 1.0:
            evolve_failures.append(f"no OneMax climb (uplift {uplift:.2f})")
    except Exception as e:
        evolve_failures.append(f"crashed: {type(e).__name__}: "
                               f"{str(e)[:200]}")
    ev = {"check": "evolve", "ok": not evolve_failures,
          "version": HW_CHECK_VERSION}
    if evolve_failures:
        ev["failed"] = evolve_failures
    print(json.dumps(ev))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
